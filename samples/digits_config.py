"""Config file for samples/digits_mlp.py — executed with `root` in scope
(ref per-run config contract, veles __main__ _apply_config)."""

root.digits.update({
    "hidden": 60,
    "learning_rate": 0.1,
    "max_epochs": 10,
    "minibatch_size": 100,
})
