"""Config for samples/cifar_conv.py (ref cifar_caffe hyperparameters)."""

root.cifar.update({
    "learning_rate": 0.001,
    "gradient_moment": 0.9,
    "weight_decay": 0.004,
    "max_epochs": 60,
    "minibatch_size": 100,
    "normalization": "mean_disp",
})
