"""Sample workflow: digits MLP (the MnistSimple-shaped baseline on the
offline-available sklearn digits set).  Run:

    python -m veles_tpu samples/digits_mlp.py samples/digits_config.py

Demonstrates the reference's module contract: define run(load, main)
(ref veles __main__ run-module contract)."""

import numpy as np
from sklearn.datasets import load_digits

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import mnist_mlp


def run(load, main):
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    cfg = root.digits
    loader = FullBatchLoader(
        None, data=x, labels=y,
        minibatch_size=cfg.get("minibatch_size", 100),
        class_lengths=[0, 297, 1500])
    load(StandardWorkflow,
         layers=mnist_mlp(hidden=cfg.get("hidden", 60),
                          lr=cfg.get("learning_rate", 0.1)),
         loader=loader,
         decision_config={"max_epochs": cfg.get("max_epochs", 10)},
         name="digits-mlp")
    main()
