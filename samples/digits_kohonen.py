"""Sample workflow: Kohonen self-organizing map on sklearn digits
(ref the reference's Kohonen engine, manualrst_veles_algorithms.rst:72-84).

    python -m veles_tpu samples/digits_kohonen.py --backend cpu
"""

import numpy as np
from sklearn.datasets import load_digits

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.kohonen import KohonenWorkflow


def run(load, main):
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    cfg = root.digits_kohonen
    loader = FullBatchLoader(None, data=x, minibatch_size=100,
                             class_lengths=[0, 0, len(x)])
    load(KohonenWorkflow, loader=loader,
         sx=cfg.get("sx", 8), sy=cfg.get("sy", 8),
         n_epochs=cfg.get("n_epochs", 5),
         name="digits-kohonen")
    main()
