"""Sample workflow: GPT-style causal LM with the modern stack — RoPE
positions, grouped-query attention, Pallas flash attention (fused
FlashAttention-2 backward), optional activation remat and MoE FFN —
trained through the same StandardWorkflow hot loop as every other model.

Text source: ``root.gpt.text_file`` (raw bytes → byte-level LM) when set,
else a built-in synthetic corpus.  After training, pass ``--serve PORT``
and POST ``{"input": [tokens], "generate": {"max_new": N}}`` for
KV-cached incremental decoding.

    python -m veles_tpu samples/gpt_lm.py --backend cpu \
        --config-list root.gpt.max_epochs=3 root.gpt.n_layers=2

    # train bigger on TPU, fused 8-step dispatch, then serve
    python -m veles_tpu samples/gpt_lm.py --steps-per-dispatch 8 \
        --config-list root.gpt.d_model=512 root.gpt.seq_len=1024 \
        --serve 8180
"""

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import transformer_lm

_SYNTHETIC = (b"the quick brown fox jumps over the lazy dog. "
              b"pack my box with five dozen liquor jugs. " * 48)

#: named model presets (``root.gpt.preset``); explicit --config-list
#: values win over the preset's entries.  "large" is the MFU-credible
#: single-chip flagship from bench.py's lm_large phase: GPT-2-small
#: dims, remat, flash, RoPE, AdamW + clipping, tied embeddings.
PRESETS = {
    "large": {"d_model": 768, "n_heads": 12, "n_kv_heads": 12,
              "n_layers": 12, "seq_len": 1024, "minibatch_size": 8,
              "remat": True, "solver": "adamw", "learning_rate": 6e-4},
}


def run(load, main):
    cfg = root.gpt
    preset = cfg.get("preset", None)
    if preset is not None:
        if preset not in PRESETS:
            raise ValueError("unknown preset %r (have: %s)"
                             % (preset, sorted(PRESETS)))
        for k, v in PRESETS[preset].items():
            if k not in cfg:           # explicit config wins
                setattr(cfg, k, v)
    path = cfg.get("text_file", None)
    if path:
        # an explicitly configured corpus that is missing must fail
        # loudly, not silently train on the toy fallback
        with open(path, "rb") as f:
            text = f.read()
    else:
        text = _SYNTHETIC
    seq = cfg.get("seq_len", 64)
    if path is None and len(text) < 16 * seq:
        # the built-in corpus tiles up to the configured context length
        # (preset "large" wants T=1024); an explicit text_file stays
        # strict — see the loud failure above
        text = text * (16 * seq // len(text) + 1)
    n = len(text) // seq
    if n < 8:
        raise ValueError("corpus too small: %d bytes for seq_len %d"
                         % (len(text), seq))
    tokens = np.frombuffer(text[:n * seq], np.uint8).reshape(
        n, seq).astype(np.int32)
    n_valid = max(1, n // 10)
    loader = FullBatchLoader(
        None, data=tokens, labels=tokens,
        minibatch_size=cfg.get("minibatch_size", 16),
        class_lengths=[0, n_valid, n - n_valid])
    n_heads = cfg.get("n_heads", 8)
    load(StandardWorkflow,
         layers=transformer_lm(
             vocab_size=256,
             d_model=cfg.get("d_model", 128),
             n_heads=n_heads,
             n_kv_heads=cfg.get("n_kv_heads", max(1, n_heads // 4)),
             n_layers=cfg.get("n_layers", 4),
             dropout=cfg.get("dropout", 0.0),
             impl=cfg.get("attention", "flash"),
             pos="rope",
             # pass through verbatim: "dots" selects the selective
             # dots_saveable policy — bool() would silently turn it
             # into full remat
             remat=cfg.get("remat", False),
             n_experts=cfg.get("n_experts", 0),
             tie_embeddings=bool(cfg.get("tie_embeddings", True)),
             window=cfg.get("window", None),
             solver=cfg.get("solver", "adam"),
             lr=cfg.get("learning_rate", 1e-3)),
         loader=loader, loss="lm",
         gd_defaults={
             "clip_norm": cfg.get("clip_norm", 1.0),
             # k× the effective batch without k× activation memory
             "grad_accum_steps": cfg.get("grad_accum_steps", 1),
             # e.g. 0.999 + root.common.serve.use_ema=True to serve
             # the Polyak average
             **({"ema_decay": cfg.get("ema_decay")}
                if cfg.get("ema_decay") else {})},
         decision_config={"max_epochs": cfg.get("max_epochs", 20)},
         name="gpt-lm")
    main()
