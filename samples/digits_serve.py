"""Sample workflow: minimal digit-token LM sized for the serving-plane
static audit.  A tiny causal transformer over a synthetic base-10
corpus (digit sequences with a repeating structure) — small enough
that constructing and abstractly tracing every serving variant
(bf16/int8/w4a8 x dense/paged x speculative) takes seconds on CPU.

This is the CI gate's serving specimen:

    veles-tpu-lint samples/digits_serve.py --serve --concurrency \
        --fail-on error

(`--serve` initializes the workflow, builds the real
LMGenerator/ContinuousBatcher variants and runs the VD7xx decode-path
audit — abstract ShapeDtypeStruct traces only, no decode ever
dispatches; `--concurrency` adds the VT8xx AST lint of
veles_tpu/services.)  It also trains as a normal workflow:

    python -m veles_tpu samples/digits_serve.py --backend cpu \
        --config-list root.digits_serve.max_epochs=3
"""

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import transformer_lm


def run(load, main):
    cfg = root.digits_serve
    seq = cfg.get("seq_len", 16)
    vocab = 13                    # 0-9 digits + pad/bos/eos
    rows = cfg.get("rows", 192)
    r = np.random.RandomState(cfg.get("seed", 31))
    # counting patterns with per-row jitter: learnable but not trivial
    tokens = ((np.arange(seq)[None, :] * 2
               + r.randint(0, 4, rows)[:, None]) % 10).astype(np.int32)
    n_valid = max(1, rows // 4)
    loader = FullBatchLoader(
        None, data=tokens, labels=tokens,
        minibatch_size=cfg.get("minibatch_size", 48),
        class_lengths=[0, n_valid, rows - n_valid])
    load(StandardWorkflow,
         layers=transformer_lm(vocab_size=vocab,
                               d_model=cfg.get("d_model", 32),
                               n_heads=4, n_layers=2,
                               lr=cfg.get("learning_rate", 5e-3),
                               dropout=0.0),
         loader=loader, loss="lm",
         gd_defaults=cfg.get("gd"),
         decision_config={"max_epochs": cfg.get("max_epochs", 1)},
         name="digits-serve")
    main()
