"""Sample workflow: ImageNet AlexNet — the BASELINE flagship config
(BASELINE.md: "ImageNet AlexNet workflow trains end-to-end on v5e-8 at
>= CUDA-backend samples/sec/chip"; ref the i_caffe configs the docs
describe, manualrst_veles_algorithms.rst).

The dataset never materializes in HBM or host RAM: a GeneratorLoader
streams fixed-shape minibatches (host-side JPEG decode + resize when
``root.imagenet.data_dir`` points at an ImageNet-style tree of
``<class>/<image>`` files; synthetic pixels otherwise), and the trainer's
async dispatch double-buffers batch t+1 against device step t.  Scales
over a device mesh with ``--mesh data=8`` (the arriving batch shards over
the data axis).

    # synthetic smoke (any machine)
    python -m veles_tpu samples/imagenet_alexnet.py --backend cpu \
        --config-list root.imagenet.minibatch_size=8 \
                      root.imagenet.steps_per_epoch=2 \
                      root.imagenet.max_epochs=1

    # real data, v5e-8
    python -m veles_tpu samples/imagenet_alexnet.py --mesh data=8 \
        --config-list root.imagenet.data_dir=\\"/data/imagenet/train\\"
"""

import os

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.streaming import GeneratorLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import alexnet

SHAPE = (227, 227, 3)


def _synthetic_generator(n_classes, seed=0):
    def gen(step, size):
        rs = np.random.RandomState(seed + step)
        return (rs.rand(size, *SHAPE).astype(np.float32),
                rs.randint(0, n_classes, size).astype(np.int32))
    return gen


def _imagenet_generator(data_dir, n_threads=8):
    """Host-side decode pipeline over an ImageNet-style directory tree:
    shuffled (path, label) stream, PIL decode + center resize to 227²,
    scaled to [0, 1]; a thread pool overlaps per-image decodes."""
    from concurrent.futures import ThreadPoolExecutor

    from veles_tpu import prng

    classes = sorted(d for d in os.listdir(data_dir)
                     if os.path.isdir(os.path.join(data_dir, d)))
    if not classes:
        raise ValueError("no class subdirectories under %r" % data_dir)
    files = [(os.path.join(data_dir, c, f), i)
             for i, c in enumerate(classes)
             for f in sorted(os.listdir(os.path.join(data_dir, c)))]
    order = prng.get("imagenet-order").permutation(len(files))
    pool = ThreadPoolExecutor(n_threads)

    def decode(pair):
        from PIL import Image
        path, label = pair
        with Image.open(path) as im:
            im = im.convert("RGB").resize(SHAPE[:2])
            return np.asarray(im, np.float32) / 255.0, label

    def gen(step, size):
        take = [files[order[(step * size + j) % len(files)]]
                for j in range(size)]
        out = list(pool.map(decode, take))
        return (np.stack([d for d, _ in out]),
                np.asarray([l for _, l in out], np.int32))

    return gen, len(files), len(classes)


def run(load, main):
    cfg = root.imagenet
    size = cfg.get("minibatch_size", 256)
    data_dir = cfg.get("data_dir", None)
    if data_dir:
        gen, n_files, n_classes = _imagenet_generator(data_dir)
        steps = cfg.get("steps_per_epoch", max(1, n_files // size))
    else:
        n_classes = cfg.get("n_classes", 1000)
        gen = _synthetic_generator(n_classes)
        steps = cfg.get("steps_per_epoch", 50)
    loader = GeneratorLoader(None, generator=gen, sample_shape=SHAPE,
                             steps_per_epoch=steps, minibatch_size=size,
                             prefetch=cfg.get("prefetch", 2))
    load(StandardWorkflow,
         layers=alexnet(n_classes=n_classes,
                        lr=cfg.get("learning_rate", 0.01)),
         loader=loader,
         decision_config={"max_epochs": cfg.get("max_epochs", 90)},
         name="imagenet-alexnet")
    main()
