"""BASELINE config 1: real-MNIST 784-100-10 MLP (ref MnistSimple —
published validation error 1.48 %, train 0.00 %;
docs/source/manualrst_veles_algorithms.rst:32).  Run:

    python -m veles_tpu samples/mnist_mlp.py samples/mnist_config.py

Expects the canonical idx files under <datasets>/mnist/ (gz or raw);
zero-egress: nothing is downloaded."""

from veles_tpu.config import root
from veles_tpu.loader.datasets import load_mnist, mnist_available
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import mnist_mlp


def run(load, main):
    if not mnist_available():
        raise SystemExit(
            "MNIST not found under %s/mnist — mount the idx files "
            "(train/t10k images+labels) to run this config"
            % root.common.dirs.get("datasets", "datasets"))
    cfg = root.mnist
    train_x, train_y, test_x, test_y = load_mnist()
    import numpy as np
    data = np.concatenate([test_x, train_x])
    labels = np.concatenate([test_y, train_y])
    loader = FullBatchLoader(
        None, data=data, labels=labels,
        minibatch_size=cfg.get("minibatch_size", 100),
        class_lengths=[0, len(test_x), len(train_x)])
    load(StandardWorkflow,
         layers=mnist_mlp(hidden=cfg.get("hidden", 100),
                          lr=cfg.get("learning_rate", 0.03),
                          moment=cfg.get("gradient_moment", 0.9)),
         loader=loader,
         decision_config={"max_epochs": cfg.get("max_epochs", 30)},
         lr_adjuster_config=cfg.get("lr_adjuster"),
         name="mnist-mlp")
    main()
