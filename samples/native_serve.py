"""Sample: train a tiny character LM, export a package, and decode
with the NATIVE C++ runtime — no Python in the serving loop.

Demonstrates the dependency-free CPU serving path (the libVeles role,
SURVEY.md §2.10, upgraded to transformers): the exported package
(contents.json + .npy) loads through ``services.native.NativeWorkflow``
and generates with per-block KV caches, token-exact vs the Python
greedy decoder.

    python samples/native_serve.py            # standalone script
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    # force CPU before any jax computation (TPU sessions pin the
    # platform via sitecustomize; serving here is deliberately CPU)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm
    from veles_tpu.services.export import export_workflow
    from veles_tpu.services.native import NativeWorkflow

    prng.seed_all(11)
    text = b"the quick brown fox jumps over the lazy dog. " * 48
    seq = 32
    n = len(text) // seq
    tokens = np.frombuffer(text[:n * seq], np.uint8) \
        .reshape(n, seq).astype(np.int32)
    loader = FullBatchLoader(None, data=tokens, labels=tokens,
                             minibatch_size=16,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(
        layers=transformer_lm(vocab_size=256, d_model=64, n_heads=4,
                              n_layers=2, dropout=0.0, pos="rope",
                              lr=3e-3),
        loader=loader, loss="lm",
        decision_config={"max_epochs": 20}, name="native-serve-demo")
    wf.initialize()
    wf.run()

    path = os.path.join(tempfile.mkdtemp(), "char_lm.zip")
    export_workflow(wf, path)
    print("exported:", path)

    native = NativeWorkflow(path)
    prompt = np.frombuffer(b"the quick brown ", np.uint8) \
        .astype(np.int32)
    toks = native.generate(prompt, max_new=16)
    print("C++ greedy :", bytes(toks.astype(np.uint8)).decode(
        "latin-1"))
    toks = native.generate(prompt, max_new=16, temperature=0.8,
                           top_k=8, seed=3)
    print("C++ sampled:", bytes(toks.astype(np.uint8)).decode(
        "latin-1"))
    native.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
