"""Sample workflow: small convnet on sklearn digits (the cifar_caffe
shape scaled to 8x8 inputs).  Run:

    python -m veles_tpu samples/digits_conv.py --backend cpu \
        --config-list root.digits_conv.max_epochs=5
"""

import numpy as np
from sklearn.datasets import load_digits

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow


def run(load, main):
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32).reshape(-1, 8, 8, 1)
    y = d.target.astype(np.int32)
    cfg = root.digits_conv
    lr = cfg.get("learning_rate", 0.02)
    gd = {"learning_rate": lr, "gradient_moment": 0.9}
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    load(StandardWorkflow,
         layers=[
             dict({"type": "conv_relu", "n_kernels": 16, "kx": 3,
                   "ky": 3}, **gd),
             {"type": "max_pooling", "kx": 2, "ky": 2},
             dict({"type": "all2all_tanh", "output_sample_shape": 64},
                  **gd),
             dict({"type": "softmax", "output_sample_shape": 10}, **gd),
         ],
         loader=loader,
         decision_config={"max_epochs": cfg.get("max_epochs", 25)},
         name="digits-conv")
    main()
