"""BASELINE config: real-MNIST autoencoder (ref — published validation
RMSE 0.5478; docs/source/manualrst_veles_algorithms.rst:70).  Run:

    python -m veles_tpu samples/mnist_ae.py

Expects the canonical idx files under <datasets>/mnist/."""

from veles_tpu.config import root
from veles_tpu.loader.datasets import load_mnist, mnist_available
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import mnist_autoencoder


def run(load, main):
    if not mnist_available():
        raise SystemExit(
            "MNIST not found under %s/mnist — mount the idx files to run "
            "this config" % root.common.dirs.get("datasets", "datasets"))
    cfg = root.mnist_ae
    train_x, _, test_x, _ = load_mnist()
    import numpy as np
    data = np.concatenate([test_x, train_x])
    loader = FullBatchLoader(
        None, data=data,
        minibatch_size=cfg.get("minibatch_size", 100),
        class_lengths=[0, len(test_x), len(train_x)])
    load(StandardWorkflow,
         layers=mnist_autoencoder(
             bottleneck=cfg.get("bottleneck", 16),
             lr=cfg.get("learning_rate", 0.01),
             moment=cfg.get("gradient_moment", 0.9)),
         loader=loader, loss="mse",
         decision_config={"max_epochs": cfg.get("max_epochs", 30)},
         name="mnist-ae")
    main()
