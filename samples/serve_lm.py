"""Sample: the modern LM serving stack, end to end.

Trains a tiny char-level LM, then serves it through ``RESTfulAPI`` with
every serving-plane feature on at once:

* continuous batching (requests join the live decode mid-flight),
* paged KV (block-table pool, memory follows active tokens),
* prefix caching (the shared "system prompt" pays its KV once),
* multi-LoRA routing (one pool serves base + a fine-tuned adapter),
* NDJSON token streaming,
* the SLO metrics endpoint.

Run:

    python samples/serve_lm.py

Prints the streamed continuation chunk by chunk, shows base-vs-adapter
routing on the same prompt, and dumps the serving metrics snapshot.
(ref counterpart: the reference served one request per forward through
Twisted, restful_api.py:112-217 — this sample is the TPU-era redesign
of that surface.)
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.generate import LMGenerator
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.services.restful import RESTfulAPI

VOCAB, T = 13, 16


def train(shift, name, lora_rank=0, warm=None):
    """Tiny ramp LM: next token = current + shift (mod VOCAB) — two
    shifts give visibly different generations, which is all the sample
    needs to SHOW adapter routing."""
    prng.seed_all(11)
    r = np.random.RandomState(2)
    toks = ((np.arange(T)[None, :] * shift
             + r.randint(0, 5, 96)[:, None]) % VOCAB).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=24,
                             class_lengths=[0, 24, 72])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=VOCAB, d_model=32,
                                  n_heads=4, n_layers=2,
                                  lr=5e-2 if lora_rank else 5e-3,
                                  dropout=0.0, pos="rope",
                                  lora_rank=lora_rank),
        loader=loader, loss="lm",
        decision_config={"max_epochs": 4}, name=name)
    wf.initialize()
    if warm is not None:
        wf.warm_start({"params": warm})
    wf.run()
    return wf, toks


def post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.loads(r.read())


def main():
    base, toks = train(2, "serve-base")
    adapted, _ = train(3, "serve-adapter", lora_rank=2,
                       warm=base.trainer.host_params())

    gen = LMGenerator(base.trainer, max_len=T)
    gen.load_adapter_bank([adapted.trainer.host_params()])

    api = RESTfulAPI(lambda x: x, (T,), port=0, generator=gen,
                     continuous_slots=4, paged_block=4,
                     pool_tokens=4 * T, prefix_cache=True)
    api.start()
    url = "http://127.0.0.1:%d/service" % api.port
    try:
        system = toks[0, :6].tolist()          # the shared prefix

        print("== streaming (NDJSON) ==")
        req = urllib.request.Request(
            url, data=json.dumps({
                "input": system,
                "generate": {"max_new": 8, "stream": True}}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            for line in resp:
                msg = json.loads(line)
                if "tokens" in msg:
                    print("  chunk:", msg["tokens"])
                elif "error" in msg:
                    print("  server error:", msg["error"])
                else:
                    print("  done: ", msg["result"])

        print("== adapter routing (same prompt) ==")
        for aid in (0, 1):
            out = post(url, {"input": [system],
                             "generate": {"max_new": 8,
                                          "adapter": aid}})
            print("  adapter %d:" % aid, out["result"][0])

        print("== prefix caching (3 concurrent same-prefix rows) ==")
        # sharing exists while same-adapter requests are concurrently
        # in flight: submit one 3-row request (all rows enter the pool
        # together) and watch the gauges mid-flight
        seen = {"blocks": 0, "refs": 0}

        def burst():
            post(url, {"input": [system, system, system],
                       "generate": {"max_new": 8}})
        t = threading.Thread(target=burst)
        t.start()
        while t.is_alive():
            with urllib.request.urlopen(url + "/metrics",
                                        timeout=60) as resp:
                c = json.loads(resp.read()).get("continuous", {})
            seen["blocks"] = max(seen["blocks"],
                                 c.get("prefix_shared_blocks", 0))
            seen["refs"] = max(seen["refs"],
                               c.get("prefix_block_refs", 0))
            time.sleep(0.02)
        t.join()
        print("  peak shared blocks: %d, peak owner refs: %d"
              % (seen["blocks"], seen["refs"]))

        print("== serving metrics ==")
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=60) as resp:
            m = json.loads(resp.read()).get("continuous", {})
        for k in sorted(m):
            if any(s in k for s in ("kv", "prefix", "p99", "served")):
                print("  %s: %s" % (k, m[k]))
    finally:
        api.stop()


if __name__ == "__main__":
    main()
