"""BASELINE config 2: real-CIFAR-10 conv net (ref cifar_caffe — published
validation error 17.21 %, train 8.31 %;
docs/source/manualrst_veles_algorithms.rst:51).  Run:

    python -m veles_tpu samples/cifar_conv.py samples/cifar_config.py

Expects <datasets>/cifar-10-batches-py/ (the canonical python batches);
zero-egress: nothing is downloaded."""

from veles_tpu.config import root
from veles_tpu.loader.datasets import cifar10_available, load_cifar10
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import cifar_conv


def run(load, main):
    if not cifar10_available():
        raise SystemExit(
            "CIFAR-10 not found under %s/cifar-10-batches-py — mount the "
            "python batches to run this config"
            % root.common.dirs.get("datasets", "datasets"))
    cfg = root.cifar
    train_x, train_y, test_x, test_y = load_cifar10()
    import numpy as np
    data = np.concatenate([test_x, train_x])
    labels = np.concatenate([test_y, train_y])
    loader = FullBatchLoader(
        None, data=data, labels=labels,
        minibatch_size=cfg.get("minibatch_size", 100),
        class_lengths=[0, len(test_x), len(train_x)],
        normalization=cfg.get("normalization", "mean_disp"))
    load(StandardWorkflow,
         layers=cifar_conv(lr=cfg.get("learning_rate", 0.001),
                           moment=cfg.get("gradient_moment", 0.9),
                           wd=cfg.get("weight_decay", 0.004)),
         loader=loader,
         decision_config={"max_epochs": cfg.get("max_epochs", 60)},
         lr_adjuster_config=cfg.get("lr_adjuster"),
         name="cifar-conv")
    main()
