"""BASELINE config 2b: STL-10 with the same conv stack as CIFAR (ref —
published validation error 35.10 %, train 0.12 %;
docs/source/manualrst_veles_algorithms.rst:52).  Run:

    python -m veles_tpu samples/stl10_conv.py

Expects <datasets>/stl10_binary/ ({train,test}_{X,y}.bin);
zero-egress: nothing is downloaded."""

from veles_tpu.config import root
from veles_tpu.loader.datasets import load_stl10, stl10_available
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import cifar_conv


def run(load, main):
    if not stl10_available():
        raise SystemExit(
            "STL-10 not found under %s/stl10_binary — mount the binary "
            "files to run this config"
            % root.common.dirs.get("datasets", "datasets"))
    cfg = root.stl10
    train_x, train_y, test_x, test_y = load_stl10()
    import numpy as np
    data = np.concatenate([test_x, train_x])
    labels = np.concatenate([test_y, train_y])
    loader = FullBatchLoader(
        None, data=data, labels=labels,
        minibatch_size=cfg.get("minibatch_size", 100),
        class_lengths=[0, len(test_x), len(train_x)],
        normalization=cfg.get("normalization", "mean_disp"))
    load(StandardWorkflow,
         layers=cifar_conv(lr=cfg.get("learning_rate", 0.001),
                           moment=cfg.get("gradient_moment", 0.9),
                           wd=cfg.get("weight_decay", 0.004)),
         loader=loader,
         decision_config={"max_epochs": cfg.get("max_epochs", 60)},
         name="stl10-conv")
    main()
