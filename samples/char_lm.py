"""Sample workflow: tiny causal character LM (transformer decoder) on a
synthetic repeated-pattern corpus.  Demonstrates the sequence stack
(embedding, learned positions, causal transformer blocks, loss="lm").

    python -m veles_tpu samples/char_lm.py --backend cpu \
        --config-list root.char_lm.max_epochs=3
"""

import numpy as np

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import transformer_lm


def run(load, main):
    cfg = root.char_lm
    text = (b"the quick brown fox jumps over the lazy dog. " * 64)
    seq = cfg.get("seq_len", 32)
    n = len(text) // seq
    tokens = np.frombuffer(text[:n * seq], np.uint8).reshape(n, seq)
    tokens = tokens.astype(np.int32)
    n_valid = max(1, n // 10)
    loader = FullBatchLoader(
        None, data=tokens, labels=tokens,
        minibatch_size=cfg.get("minibatch_size", 16),
        class_lengths=[0, n_valid, n - n_valid])
    load(StandardWorkflow,
         layers=transformer_lm(vocab_size=256,
                               d_model=cfg.get("d_model", 32),
                               n_heads=4, n_layers=2,
                               lr=cfg.get("learning_rate", 0.003),
                               # > 0: freeze the base, train rank-r
                               # q/v adapters (pair with --warm-start;
                               # ship them with --export-lora)
                               lora_rank=cfg.get("lora_rank", 0)),
         loader=loader, loss="lm",
         gd_defaults=cfg.get("gd"),
         decision_config={"max_epochs": cfg.get("max_epochs", 10)},
         name="char-lm")
    main()
