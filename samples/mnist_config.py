"""Config for samples/mnist_mlp.py (ref MnistSimple hyperparameters)."""

root.mnist.update({
    "hidden": 100,
    "learning_rate": 0.03,
    "gradient_moment": 0.9,
    "max_epochs": 30,
    "minibatch_size": 100,
})
