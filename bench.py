"""Benchmark — prints ONE JSON line on stdout.

Headline metric: the reference's own DeviceBenchmark methodology
(square 3001×3001 f32 gemm, 3 timed repeats — ref
veles/accelerated_units.py:706-824, veles/backends.py:672-731), which the
reference ships a measured number for: 0.1642 s/multiply ≈ 329 GFLOP/s on a
GeForce GTX TITAN (devices/device_infos.json, BASELINE.md).  vs_baseline is
our GFLOP/s over that 329.

Secondary numbers (stderr, informational): MNIST-shape MLP train-step time
and AlexNet train samples/sec/chip on synthetic data."""

import json
import sys
import time

import numpy as np


def _block(x):
    import jax
    jax.block_until_ready(x)


def bench_gemm(n=3001, iters=20):
    """Chained-matmul loop *inside one jit dispatch* (lax.scan): measures
    device compute the way the reference's kernel timer did, immune to the
    per-dispatch overhead of the TPU tunnel and to result caching (each
    multiply consumes the previous one's output).

    precision="highest" = true f32 accumulation, matching the reference's
    PRECISION_LEVEL 0 float math (not bf16 passes)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    a = jnp.asarray(np.random.RandomState(0).rand(n, n).astype(np.float32))

    def body(y, _):
        y = jnp.dot(y, a, precision="highest")
        y = y / jnp.max(jnp.abs(y))   # keep values finite across the chain
        return y, None

    f = jax.jit(lambda y: lax.scan(body, y, None, length=iters)[0])
    _block(f(a))   # compile + warmup
    t0 = time.perf_counter()
    _block(f(a))
    dt = (time.perf_counter() - t0) / iters
    gflops = 2.0 * n * n * n / dt / 1e9
    return dt, gflops


def bench_mlp_step():
    """MNIST 784-100-10 step time (BASELINE 'MNIST MLP step time')."""
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import mnist_mlp

    prng.seed_all(3)
    x = np.random.RandomState(0).rand(2000, 784).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, 2000).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 0, 2000])
    wf = StandardWorkflow(layers=mnist_mlp(), loader=loader,
                          decision_config={"max_epochs": 1}, name="bench-mlp")
    wf.initialize()
    wf.loader.run()
    wf.trainer.run()          # compile
    _block(wf.trainer.class_stats[2]["loss"])
    t0 = time.perf_counter()
    steps = 50
    for _ in range(steps):
        wf.loader.run()
        wf.trainer.run()
    _block(wf.trainer.class_stats[2]["loss"])
    return (time.perf_counter() - t0) / steps


def bench_alexnet(batch=64, steps=10):
    """AlexNet train samples/sec/chip on synthetic 227×227×3 data."""
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import alexnet

    prng.seed_all(4)
    n = batch * 2
    x = np.random.RandomState(0).rand(n, 227, 227, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 1000, n).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=batch,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(layers=alexnet(), loader=loader,
                          decision_config={"max_epochs": 1000},
                          name="bench-alexnet")
    wf.initialize()
    wf.loader.run()
    wf.trainer.run()          # compile
    _block(wf.trainer.class_stats[2]["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        wf.loader.run()
        wf.trainer.run()
    _block(wf.trainer.class_stats[2]["loss"])
    dt = time.perf_counter() - t0
    return batch * steps / dt


def main():
    dt, gflops = bench_gemm()
    print("gemm 3001^2 f32(highest): %.4f s/multiply, %.1f GFLOP/s"
          % (dt, gflops), file=sys.stderr)
    try:
        step = bench_mlp_step()
        print("mnist mlp 784-100-10 step: %.3f ms" % (step * 1e3),
              file=sys.stderr)
        sps = bench_alexnet()
        print("alexnet synthetic: %.1f samples/sec/chip" % sps,
              file=sys.stderr)
    except Exception as e:  # secondary benches must not kill the headline
        print("secondary bench failed: %r" % e, file=sys.stderr)
    print(json.dumps({
        "metric": "gemm_3001x3001_f32_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / 329.0, 2),
    }))


if __name__ == "__main__":
    main()
