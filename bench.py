"""Benchmark — ALWAYS prints exactly ONE JSON line on stdout.

Headline metric: the reference's own DeviceBenchmark methodology
(square 3001x3001 f32 gemm, chained repeats — ref
veles/accelerated_units.py:706-824, veles/backends.py:672-731), which the
reference ships a measured number for: 0.1642 s/multiply ~= 329 GFLOP/s on
a GeForce GTX TITAN (devices/device_infos.json, BASELINE.md).
``vs_baseline`` is our f32 GFLOP/s over that 329.

Engineering (round-2 hardening): every phase runs in its OWN subprocess
with a watchdog timeout, backend-init failures are retried with backoff,
and the final JSON line is emitted no matter what — with an ``error``
field when the chip is unreachable.  Secondary numbers (MLP step time,
AlexNet samples/sec, bf16 gemm, Pallas flash + ring-attention on-chip
smokes) ride along in the same JSON.

Usage:  python bench.py            # orchestrator (the driver runs this)
        python bench.py --phase X  # internal: one phase, child process
"""

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_GEMM_GFLOPS = 329.0   # GTX TITAN, f32, ref devices/device_infos.json

#: (name, watchdog seconds).  Order matters: the headline gemm goes first so
#: a later hang can never cost us the one number BASELINE demands.
PHASES = [
    ("gemm", 420),
    ("mlp", 420),
    ("alexnet", 600),
    ("lm", 600),
    ("flash", 300),
    ("ring", 420),
    ("kohonen", 300),
]

#: stderr substrings that mean "backend init flake — worth retrying"
RETRYABLE = (
    "Unable to initialize backend",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "backend setup/compile error",
    "Socket closed",
    "failed to connect",
)

_BACKOFF = (5, 25, 60)          # seconds between attempts (>=3 over ~2 min)
_RESULT_TAG = "PHASE_RESULT "


def _log(msg):
    print("[bench] %s" % msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Phase implementations — each runs inside a child process.
# --------------------------------------------------------------------------

def _block(x):
    import jax
    jax.block_until_ready(x)


def phase_gemm():
    """Chained-matmul loop *inside one jit dispatch* (lax.scan): measures
    device compute the way the reference's kernel timer did, immune to
    per-dispatch overhead of the TPU tunnel and to result caching (each
    multiply consumes the previous one's output).

    f32 path uses precision="highest" (true f32 accumulation, matching the
    reference's PRECISION_LEVEL 0 float math).  The bf16 path is the TPU's
    native MXU number — reported alongside, since bf16 is what real
    training on this hardware uses."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    def run(n, dtype, precision, iters=20):
        a = jnp.asarray(
            np.random.RandomState(0).rand(n, n).astype(np.float32)
        ).astype(dtype)
        c = jnp.asarray(2.0 / n, dtype)

        def body(y, _):
            # constant rescale keeps the chain finite without a
            # data-dependent reduction serializing against the MXU
            return jnp.dot(y, a, precision=precision) * c, None

        f = jax.jit(lambda y: lax.scan(body, y, None, length=iters)[0])
        _block(f(a))                        # compile + warmup
        dt = float("inf")
        for _ in range(3):                  # best of 3 (shared-chip noise)
            t0 = time.perf_counter()
            _block(f(a))
            dt = min(dt, (time.perf_counter() - t0) / iters)
        return dt, 2.0 * n * n * n / dt / 1e9

    # baseline-comparable: the reference's exact 3001^2 f32 methodology
    dt32, gf32 = run(3001, jnp.float32, "highest")
    _log("gemm 3001^2 f32(highest): %.4f s/multiply, %.1f GFLOP/s"
         % (dt32, gf32))
    # MXU-native: large bf16 gemm, what real TPU training runs on
    dt16, gf16 = run(8192, jnp.bfloat16, "default", iters=10)
    _log("gemm 8192^2 bf16: %.4f s/multiply, %.1f GFLOP/s" % (dt16, gf16))
    return {"s_per_multiply": dt32, "gflops": gf32, "bf16_gflops": gf16,
            "device": str(jax.devices()[0])}


def phase_mlp():
    """MNIST 784-100-10 step time (BASELINE 'MNIST MLP step time'), plus
    the fused steps_per_dispatch=20 sweep (k minibatches per host→device
    round trip — the dispatch-amortized number real training runs at)."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import mnist_mlp

    def build(k):
        prng.seed_all(3)
        x = np.random.RandomState(0).rand(2000, 784).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 10, 2000).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                                 class_lengths=[0, 0, 2000])
        wf = StandardWorkflow(layers=mnist_mlp(), loader=loader,
                              decision_config={"max_epochs": 1},
                              steps_per_dispatch=k, name="bench-mlp")
        wf.initialize()
        return wf

    def measure(wf, steps=60):
        for _ in range(steps):          # compile + warmup (covers sweep)
            wf.loader.run()
            wf.trainer.run()
        wf.trainer.flush()
        _block(wf.trainer.class_stats[2]["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            wf.loader.run()
            wf.trainer.run()
        wf.trainer.flush()
        _block(wf.trainer.class_stats[2]["loss"])
        return (time.perf_counter() - t0) / steps * 1e3

    step_ms = measure(build(1))
    fused_ms = measure(build(20))
    _log("mnist mlp 784-100-10 step: %.3f ms per-step, %.3f ms fused k=20"
         % (step_ms, fused_ms))
    return {"step_ms": step_ms, "step_fused_ms": fused_ms}


def phase_alexnet():
    """AlexNet train samples/sec/chip on synthetic 227x227x3 data."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import alexnet

    prng.seed_all(4)
    batch, steps = 256, 10   # 256 keeps the MXU fed (~1.8x batch 64)
    n = batch * 2
    x = np.random.RandomState(0).rand(n, 227, 227, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 1000, n).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=batch,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(layers=alexnet(), loader=loader,
                          decision_config={"max_epochs": 1000},
                          name="bench-alexnet")
    wf.initialize()
    wf.loader.run()
    wf.trainer.run()          # compile
    _block(wf.trainer.class_stats[2]["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        wf.loader.run()
        wf.trainer.run()
    _block(wf.trainer.class_stats[2]["loss"])
    sps = batch * steps / (time.perf_counter() - t0)
    _log("alexnet synthetic: %.1f samples/sec/chip" % sps)
    return {"samples_per_sec": sps}


def phase_lm():
    """Causal transformer LM training throughput (tokens/sec/chip) — the
    beyond-parity flagship: GPT-style decoder (~25M params, T=1024,
    Pallas flash attention + fused FA2 backward, RoPE, GQA, AdamW with
    global-norm clipping, bf16 MXU compute) through the SAME
    StandardWorkflow hot loop as every other model, with the fused
    k-step dispatch."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm

    prng.seed_all(5)
    batch, seq, steps = 8, 1024, 20
    n = batch * 4
    toks = np.random.RandomState(0).randint(
        0, 8192, (n, seq)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=batch,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(
        layers=transformer_lm(vocab_size=8192, d_model=512, n_heads=8,
                              n_kv_heads=2, n_layers=8, dropout=0.0,
                              impl="flash", pos="rope", solver="adamw",
                              lr=1e-3),
        loader=loader, loss="lm",
        gd_defaults={"clip_norm": 1.0},
        decision_config={"max_epochs": 1000},
        steps_per_dispatch=5, name="bench-lm")
    wf.initialize()
    for _ in range(10):          # compile + warmup (2 fused sweeps)
        wf.loader.run()
        wf.trainer.run()
    wf.trainer.flush()
    _block(wf.trainer.class_stats[2]["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        wf.loader.run()
        wf.trainer.run()
    wf.trainer.flush()
    _block(wf.trainer.class_stats[2]["loss"])
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    _log("transformer lm 25M (T=1024, flash): %.0f tokens/sec/chip, "
         "%.1f ms/step" % (tps, dt / steps * 1e3))
    return {"tokens_per_sec": tps, "ms_per_step": dt / steps * 1e3}


def phase_flash():
    """Pallas flash-attention kernel ON HARDWARE: correctness vs the naive
    reference plus a timing, proving the TPU-only code path executes."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops.attention import attention
    from veles_tpu.ops.pallas.flash import flash_attention

    platform = jax.default_backend()
    key = jax.random.key(0)
    b, h, t, d = 4, 8, 1024, 128
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.float32) * 0.1
               for kk in jax.random.split(key, 3))
    f = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    out = f(q, k, v)
    ref = attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)))
    if err > 5e-3:
        raise AssertionError("flash kernel mismatch: max_err=%g" % err)

    def timed(fn, *args, iters=20):
        _block(fn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            o = fn(*args)
        _block(o)
        return (time.perf_counter() - t0) / iters * 1e3

    ms = timed(f, q, k, v)
    # the mixed-precision path: bf16 MXU multiplies, f32 accumulation —
    # correctness-gated on hardware like the f32 path
    q16, k16, v16 = (x.astype(jnp.bfloat16) for x in (q, k, v))
    err16 = float(jnp.max(jnp.abs(
        f(q16, k16, v16).astype(jnp.float32) - ref)))
    if err16 > 0.05:
        raise AssertionError("bf16 flash mismatch: max_err=%g" % err16)
    ms16 = timed(f, q16, k16, v16)

    # fused Pallas backward (dQ + dK/dV kernels) on hardware,
    # correctness-gated against the naive reference gradient
    loss_flash = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2)))
    loss_ref = jax.grad(lambda q, k, v: jnp.sum(
        attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))
    gf = loss_flash(q, k, v)
    gr = loss_ref(q, k, v)
    bwd_err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gf, gr))
    if bwd_err > 5e-2:
        raise AssertionError("fused backward mismatch: %g" % bwd_err)
    ms_bwd = timed(loss_flash, q, k, v, iters=10)

    # long-context headline: one chip, T=8192 causal bf16 forward —
    # the O(T·block) VMEM tiling is what makes this shape possible.
    # Real-kernel only (interpret mode would outlive the watchdog).
    ms_long = 0.0
    if platform == "tpu":
        bl, hl, tl, dl = 1, 8, 8192, 128
        ql, kl, vl = (jax.random.normal(kk, (bl, hl, tl, dl),
                                        jnp.bfloat16) * 0.1
                      for kk in jax.random.split(jax.random.key(2), 3))
        ms_long = timed(f, ql, kl, vl, iters=10)
        tf_long = (4 * bl * hl * tl * tl * dl / 2
                   / (ms_long / 1e3) / 1e12)
        _log("flash long-context T=8192 bf16: %.2f ms "
             "(%.1f TF/s causal-effective)" % (ms_long, tf_long))

    _log("pallas flash (4,8,1024,128) causal on %s: %.2f ms f32, "
         "%.2f ms bf16, bwd %.2f ms (err %.2e), max_err %.2e"
         % (platform, ms, ms16, ms_bwd, bwd_err, err))
    return {"ms": ms, "ms_bf16": ms16, "ms_bwd": ms_bwd,
            "bwd_max_err": bwd_err, "max_err": err,
            "ms_long_t8192": ms_long, "platform": platform}


def phase_ring():
    """Ring attention through shard_map ON HARDWARE (1-chip mesh here;
    the same code path the 8-device CPU tests exercise for correctness)."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops.attention import attention
    from veles_tpu.parallel.mesh import make_mesh
    from veles_tpu.parallel.ring import ring_attention_sharded

    platform = jax.default_backend()
    mesh = make_mesh({"seq": len(jax.devices())})
    key = jax.random.key(1)
    b, h, t, d = 2, 4, 512, 64
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.float32) * 0.1
               for kk in jax.random.split(key, 3))
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    ref = attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)))
    if err > 5e-3:
        raise AssertionError("ring attention mismatch: max_err=%g" % err)
    _log("ring attention on %s (%d-dev mesh): max_err %.2e"
         % (platform, len(jax.devices()), err))
    return {"max_err": err, "platform": platform,
            "n_devices": len(jax.devices())}


def phase_kohonen():
    """Kohonen SOM training throughput (BASELINE config 4): batched
    (MXU matmul) step vs the per-sample online scan."""
    from veles_tpu.models.kohonen import benchmark_som

    res = benchmark_som(n_samples=2048, n_features=784, sx=16, sy=16,
                        minibatch_size=512, steps=20)
    _log("kohonen 16x16 som, batch 512, 784 feats: %.3f ms/step batched, "
         "%.3f fused-sweep vs %.2f scan (%.1fx / %.1fx), qe %.4f/%.4f"
         % (res["ms_per_step"], res["sweep_ms_per_step"],
            res["scan_ms_per_step"], res["speedup"], res["sweep_speedup"],
            res["quantization_error"], res["sweep_quantization_error"]))
    return res


# --------------------------------------------------------------------------
# Orchestrator
# --------------------------------------------------------------------------

def _probe(deadline):
    """Cheap device probe with retries — decides whether to run phases at
    all.  Runs in a watchdogged child like everything else."""
    code = ("import jax; d = jax.devices(); "
            "print('PROBE_OK', len(d), d[0].platform)")
    for i, backoff in enumerate((0,) + _BACKOFF):
        if backoff:
            _log("probe retry in %ds ..." % backoff)
            time.sleep(backoff)
        if time.monotonic() > deadline:
            return False, "probe: global deadline exceeded"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=150)
        except subprocess.TimeoutExpired:
            _log("probe attempt %d: timeout (150s)" % (i + 1))
            continue
        if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
            _log("probe ok: %s" % proc.stdout.strip())
            return True, None
        _log("probe attempt %d failed: %s"
             % (i + 1, (proc.stderr or "")[-300:].replace("\n", " ")))
    return False, "device probe failed after %d attempts" % (1 + len(_BACKOFF))


def _run_phase(name, timeout, deadline):
    """One phase in a watchdogged subprocess; retry on backend flakes."""
    for i, backoff in enumerate((0,) + _BACKOFF):
        if backoff:
            _log("%s: retry in %ds ..." % (name, backoff))
            time.sleep(backoff)
        remaining = deadline - time.monotonic()
        if remaining < 30:
            return {"ok": False, "error": "skipped: global deadline"}
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--phase", name],
                capture_output=True, text=True,
                timeout=min(timeout, remaining))
        except subprocess.TimeoutExpired:
            _log("%s: WATCHDOG timeout after %ds" % (name, timeout))
            # a hang is rarely cured by retrying — one attempt only
            return {"ok": False, "error": "watchdog timeout (%ds)" % timeout}
        sys.stderr.write(proc.stderr or "")
        sys.stderr.flush()
        for line in (proc.stdout or "").splitlines():
            if line.startswith(_RESULT_TAG):
                out = json.loads(line[len(_RESULT_TAG):])
                out["ok"] = True
                _log("%s: done in %.1fs" % (name, time.time() - t0))
                return out
        err_blob = (proc.stderr or "") + (proc.stdout or "")
        if any(pat in err_blob for pat in RETRYABLE):
            _log("%s: attempt %d hit retryable backend error" % (name, i + 1))
            continue
        tail = err_blob.strip().splitlines()[-3:]
        return {"ok": False, "error": "rc=%d: %s"
                % (proc.returncode, " | ".join(tail)[-400:])}
    return {"ok": False, "error": "retries exhausted (backend unavailable)"}


#: on success the measured numbers persist here; when the chip is later
#: unreachable the fail-soft JSON carries them as last_known_good so a
#: transient tunnel outage doesn't erase the evidence
_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      ".bench_last_good.json")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", help="internal: run one phase")
    parser.add_argument("--budget", type=float,
                        default=float(os.environ.get("BENCH_BUDGET", 1500)),
                        help="global wall-clock budget, seconds")
    args = parser.parse_args()

    if args.phase:
        result = globals()["phase_" + args.phase]()
        print(_RESULT_TAG + json.dumps(result), flush=True)
        return

    deadline = time.monotonic() + args.budget
    results = {}
    ok, probe_err = _probe(deadline)
    if ok:
        for name, timeout in PHASES:
            results[name] = _run_phase(name, timeout, deadline)
    else:
        _log("probe failed — skipping all phases: %s" % probe_err)

    gemm = results.get("gemm", {})
    errors = {n: r["error"] for n, r in results.items() if not r.get("ok")}
    if probe_err:
        errors["probe"] = probe_err
    gflops = gemm.get("gflops", 0.0)
    line = {
        "metric": "gemm_3001x3001_f32_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GEMM_GFLOPS, 2),
        "gemm_bf16_gflops": round(gemm.get("bf16_gflops", 0.0), 1),
        "mlp_step_ms": round(results.get("mlp", {}).get("step_ms", 0.0), 3),
        "mlp_step_fused_ms": round(
            results.get("mlp", {}).get("step_fused_ms", 0.0), 3),
        "alexnet_samples_per_sec": round(
            results.get("alexnet", {}).get("samples_per_sec", 0.0), 1),
        "lm_tokens_per_sec": round(
            results.get("lm", {}).get("tokens_per_sec", 0.0), 1),
        "kohonen_ms_per_step": round(
            results.get("kohonen", {}).get("ms_per_step", 0.0), 2),
        "kohonen_sweep_speedup": round(
            results.get("kohonen", {}).get("sweep_speedup", 0.0), 1),
        "flash_ok": bool(results.get("flash", {}).get("ok")),
        "flash_platform": results.get("flash", {}).get("platform"),
        "ring_ok": bool(results.get("ring", {}).get("ok")),
        "error": ("; ".join("%s: %s" % kv for kv in sorted(errors.items()))
                  or None),
    }
    if gemm.get("ok"):
        try:
            with open(_CACHE, "w") as f:
                json.dump({k: v for k, v in line.items() if k != "error"}
                          | {"measured_at": time.strftime(
                              "%Y-%m-%d %H:%M:%S")}, f)
        except OSError:
            pass
    elif os.path.exists(_CACHE):
        try:
            line["last_known_good"] = json.load(open(_CACHE))
        except (OSError, ValueError):
            pass
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
