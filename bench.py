"""Benchmark — ALWAYS prints exactly ONE JSON line on stdout.

Headline metric: the reference's own DeviceBenchmark methodology
(square 3001x3001 f32 gemm, chained repeats — ref
veles/accelerated_units.py:706-824, veles/backends.py:672-731), which the
reference ships a measured number for: 0.1642 s/multiply ~= 329 GFLOP/s on
a GeForce GTX TITAN (devices/device_infos.json, BASELINE.md).
``vs_baseline`` is our f32 GFLOP/s over that 329.

Engineering (round-2 hardening): every phase runs in its OWN subprocess
with a watchdog timeout, backend-init failures are retried with backoff,
and the final JSON line is emitted no matter what — with an ``error``
field when the chip is unreachable.  Secondary numbers (MLP step time,
AlexNet samples/sec, bf16 gemm, Pallas flash + ring-attention on-chip
smokes) ride along in the same JSON.

Usage:  python bench.py            # orchestrator (the driver runs this)
        python bench.py --phase X  # internal: one phase, child process
"""

import argparse
import json
import os
import subprocess
import sys
import time

BASELINE_GEMM_GFLOPS = 329.0   # GTX TITAN, f32, ref devices/device_infos.json

#: (name, watchdog seconds).  Order matters: the headline gemm goes first
#: so a later hang can never cost us the one number BASELINE demands; the
#: LM flagships and flash head-to-head come next (round-3 priority:
#: MFU-credible numbers on record) — they are also the most hang-prone,
#: so the default budget covers a full worst-case LM+flash stall while
#: still reaching the cheap phases behind them.
#: Ordered by evidence value per minute of tunnel uptime: gemm must run
#: first (its success gates the last-known-good cache write), then the
#: phases that have never produced a hardware number (lm_large / lm /
#: flash post-fix / serve), then the already-evidenced phases — so a
#: tunnel that dies mid-run costs re-measurement, not first-measurement.
PHASES = [
    ("gemm", 420),
    ("lm_large", 900),
    ("lm", 600),
    ("flash", 600),
    ("serve", 600),
    ("mlp", 420),
    ("alexnet", 600),
    ("beam", 420),
    ("ring", 420),
    ("kohonen", 300),
]


def _causal_attn_flops(b, h, t, d):
    """Shared convention — see veles_tpu/ops/flops.py."""
    from veles_tpu.ops.flops import causal_attn_flops
    return causal_attn_flops(b, h, t, d)


def _target(metric, default):
    """Pre-registered goal from the declared target registry
    (telemetry.ledger.TARGETS) — the registry is the one source of
    truth, phases only *report* the bar they are judged against.
    Fail-soft: a broken install must not cost the measurement."""
    try:
        from veles_tpu.telemetry import ledger as _ledgermod
        return _ledgermod.target_goal(metric, default)
    except Exception:  # noqa: BLE001 — fail-soft by contract
        return default

#: detected bf16 peak by device_kind substring (TFLOP/s) — the MFU
#: denominator.  Order matters ("v5 lite" before "v5").
PEAK_BF16_TFLOPS = (
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0), ("v5", 459.0),
    ("v6 lite", 918.0), ("v6e", 918.0), ("v6", 918.0),
    ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
)


def _peak_bf16():
    """bf16 peak TFLOP/s of device 0, or 0.0 when unknown (CPU/unlisted:
    MFU is then omitted rather than fabricated)."""
    import jax
    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in PEAK_BF16_TFLOPS:
        if sub in kind:
            return peak
    return 0.0

#: stderr substrings that mean "backend init flake — worth retrying"
RETRYABLE = (
    "Unable to initialize backend",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "backend setup/compile error",
    "Socket closed",
    "failed to connect",
)

_BACKOFF = (5, 25, 60)          # seconds between attempts (>=3 over ~2 min)
_RESULT_TAG = "PHASE_RESULT "


def _log(msg):
    print("[bench] %s" % msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# Phase implementations — each runs inside a child process.
# --------------------------------------------------------------------------

def _block(x):
    import jax
    return jax.block_until_ready(x)


def _fetch_sync(wf, cls=2):
    """The only trustworthy device barrier on the tunnel backend: FETCH
    the loss scalar.  ``block_until_ready`` acks early and untrustably
    on this backend (tools/diag_async.py measured a 124M train step at
    0.7 ms via block; the fetched-value truth is ~200 ms) — but the
    VALUE of the final step's loss cannot exist before every queued
    predecessor executed, so a device_get is transitively honest.
    Costs one ~64 ms tunnel RTT (tools/diag_sync2.py)."""
    import jax
    return float(jax.device_get(wf.trainer.class_stats[cls]["loss"]))


def _timed_steps(wf, steps, cls=2):
    """Wall seconds for ``steps`` loader+trainer steps, fetch-synced.

    The async enqueues inside the loop are free; the closing fetch
    forces the whole dependency chain.  The returned time includes one
    tunnel RTT — callers timing sub-100ms regions should difference
    two calls (slope) so the constant cancels."""
    tr = wf.trainer
    _fetch_sync(wf, cls)                  # drain anything outstanding
    t0 = time.perf_counter()
    for _ in range(steps):
        wf.loader.run()
        tr.run()
    tr.flush()
    _fetch_sync(wf, cls)
    return time.perf_counter() - t0


def _per_step_ms_slope(wf, steps, cls=2, reps=3):
    """Per-step ms via two-point slope — T(2k) - T(k) over k steps —
    so the constant fetch RTT and enqueue overheads cancel.  For
    phases whose per-step time is comparable to the ~64 ms RTT.
    Median of ``reps`` slope samples; callers pick ``steps`` so the
    differenced region is well above timing jitter (>= ~200 ms).
    A non-positive median slope means the region was jitter-dominated:
    fail LOUDLY (the fail-soft runner reports the phase error) rather
    than publish another physically-impossible throughput."""
    slopes = []
    for _ in range(reps):
        t1 = _timed_steps(wf, steps, cls)
        t2 = _timed_steps(wf, 2 * steps, cls)
        slopes.append((t2 - t1) / steps * 1e3)
    med = sorted(slopes)[len(slopes) // 2]
    if med <= 0.0:
        raise RuntimeError(
            "slope timing jitter-dominated (samples %s ms/step over "
            "%d steps) — raise `steps`" % (slopes, steps))
    return med


def _norm_operand(n):
    """n x n operand pre-normalized by its dominant singular value
    (host-side power iteration) so a y <- y @ a chain needs NO per-iter
    rescale op: the timed loop is pure MXU matmuls."""
    import numpy as np

    a = np.random.RandomState(0).rand(n, n).astype(np.float32)
    v = np.random.RandomState(1).rand(n).astype(np.float32)
    for _ in range(8):
        v = a.T @ (a @ v)
        v /= np.linalg.norm(v)
    return a / float(np.linalg.norm(a @ v))


def phase_gemm():
    """Chained-matmul loop *inside one jit dispatch* (lax.scan): measures
    device compute the way the reference's kernel timer did, immune to
    per-dispatch overhead of the TPU tunnel and to result caching (each
    multiply consumes the previous one's output).

    f32 path uses precision="highest" (true f32 accumulation, matching the
    reference's PRECISION_LEVEL 0 float math).  The bf16 path is the TPU's
    native MXU number — reported alongside, since bf16 is what real
    training on this hardware uses."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def run(n, dtype, precision, iters=20):
        a = jnp.asarray(_norm_operand(n)).astype(dtype)

        def body(y, _):
            return jnp.dot(y, a, precision=precision), None

        f = jax.jit(lambda y: lax.scan(body, y, None, length=iters)[0],
                    donate_argnums=(0,))
        # the seed must not alias the captured multiplicand: f donates it
        y = _block(f(jnp.copy(a)))         # compile + warmup
        dt = float("inf")
        for _ in range(3):                  # best of 3 (shared-chip noise)
            t0 = time.perf_counter()
            y = _block(f(y))
            dt = min(dt, (time.perf_counter() - t0) / iters)
        return dt, 2.0 * n * n * n / dt / 1e9

    # baseline-comparable: the reference's exact 3001^2 f32 methodology
    dt32, gf32 = run(3001, jnp.float32, "highest")
    _log("gemm 3001^2 f32(highest): %.4f s/multiply, %.1f GFLOP/s"
         % (dt32, gf32))
    # MXU-native: large bf16 gemm, what real TPU training runs on
    dt16, gf16 = run(8192, jnp.bfloat16, "default", iters=10)
    peak = _peak_bf16()
    mfu = gf16 / 1e3 / peak if peak else 0.0
    _log("gemm 8192^2 bf16: %.4f s/multiply, %.1f GFLOP/s (MFU %.1f%% of "
         "%s TF/s peak)" % (dt16, gf16, mfu * 100, peak or "unknown"))
    # precision-level overhead at the reference's own 3001^2 shape
    # (BASELINE rows: Kahan level 1 = +9%, multipartial level 2 = +90%
    # on the GTX TITAN).  On TPU, level 0 (bf16 compute) already
    # accumulates in f32 ON THE MXU — the exactness Kahan bought in
    # software is hardware-native and costs nothing; the only "more
    # precision, slower" step left is f32 COMPUTE (level >= 1), whose
    # measured overhead vs bf16 is reported here against those rows.
    dt16s, gf16s = run(3001, jnp.bfloat16, "default")
    overhead = (dt32 / dt16s - 1.0) * 100.0 if dt16s else 0.0
    _log("gemm 3001^2 bf16: %.4f s/multiply, %.1f GFLOP/s -> f32 "
         "precision-level overhead +%.0f%% (ref Kahan +9%%, "
         "multipartial +90%% — both obsolete: f32 accumulation is "
         "MXU-native at level 0)" % (dt16s, gf16s, overhead))
    return {"s_per_multiply": dt32, "gflops": gf32, "bf16_gflops": gf16,
            "bf16_mfu": mfu, "peak_bf16_tflops": peak,
            "bf16_3001_gflops": gf16s,
            "precision_overhead_pct": overhead,
            "device": str(jax.devices()[0])}


def phase_gemmtune():
    """Manual diagnostic (not in PHASES): where do the missing bf16 MFU
    points go?  Sweeps size x iters x chain shape — serial dependence
    (y@a), independent pairs (two live chains interleaved), and an
    f32-output variant — so tunnel amortization, scheduling stalls and
    output-write bandwidth can be told apart."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    peak = _peak_bf16()
    out = {}

    def measure(f, seed, iters, flops_per_iter):
        y = _block(f(seed))
        dt = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            y = _block(f(y))
            dt = min(dt, (time.perf_counter() - t0) / iters)
        return flops_per_iter / dt / 1e12

    for n in (4096, 8192, 16384):
        a = jnp.asarray(_norm_operand(n)).astype(jnp.bfloat16)
        iters = max(10, int(3e12 / (2 * n ** 3)))   # ~3 TFLOP per dispatch
        flops = 2.0 * n ** 3

        f_ser = jax.jit(lambda y, a=a, it=iters: lax.scan(
            lambda y, _: (jnp.dot(y, a), None), y, None, length=it)[0],
            donate_argnums=(0,))
        tf_ser = measure(f_ser, jnp.copy(a), iters, flops)

        # two independent chains per scan step: exposes cross-matmul
        # overlap if the serial chain is scheduling-stalled
        f_par = jax.jit(lambda c, a=a, it=iters: lax.scan(
            lambda c, _: ((jnp.dot(c[0], a), jnp.dot(c[1], a)), None),
            c, None, length=it)[0], donate_argnums=(0,))
        tf_par = measure(f_par, (jnp.copy(a), jnp.copy(a.T)), iters,
                         2 * flops)

        # f32 accumulator output (halved output-write count vs two bf16
        # stores is NOT the point — the doubled store width is: if the
        # serial chain is output-write bound this variant drops hardest)
        f_f32 = jax.jit(lambda y, a=a, it=iters: lax.scan(
            lambda y, _: (jnp.dot(y.astype(jnp.bfloat16), a,
                                  preferred_element_type=jnp.float32),
                          None), y, None, length=it)[0],
            donate_argnums=(0,))
        tf_f32 = measure(f_f32, jnp.copy(a).astype(jnp.float32), iters,
                         flops)

        out[n] = {"serial_tf": round(tf_ser, 1), "pair_tf": round(tf_par, 1),
                  "f32out_tf": round(tf_f32, 1), "iters": iters}
        _log("gemmtune n=%d iters=%d: serial %.1f TF/s (%.1f%%), "
             "pairs %.1f TF/s (%.1f%%), f32-out %.1f TF/s"
             % (n, iters, tf_ser, 100 * tf_ser / peak if peak else 0,
                tf_par, 100 * tf_par / peak if peak else 0, tf_f32))
    return {"peak": peak, "sweep": {str(k): v for k, v in out.items()}}


def phase_mlp():
    """MNIST 784-100-10 step time (BASELINE 'MNIST MLP step time'), plus
    the fused steps_per_dispatch=20 sweep (k minibatches per host→device
    round trip — the dispatch-amortized number real training runs at)."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import mnist_mlp

    def build(k):
        prng.seed_all(3)
        x = np.random.RandomState(0).rand(2000, 784).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 10, 2000).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                                 class_lengths=[0, 0, 2000])
        wf = StandardWorkflow(layers=mnist_mlp(), loader=loader,
                              decision_config={"max_epochs": 1},
                              steps_per_dispatch=k, name="bench-mlp")
        wf.initialize()
        return wf

    def measure(wf, steps):
        for _ in range(60):             # compile + warmup (covers sweep)
            wf.loader.run()
            wf.trainer.run()
        wf.trainer.flush()
        _block(wf.trainer.class_stats[2]["loss"])
        # sub-ms steps: slope timing, the fetch RTT constant cancels;
        # step counts sized so the differenced region clears jitter
        return _per_step_ms_slope(wf, steps)

    step_ms = measure(build(1), steps=200)
    fused_ms = measure(build(20), steps=2000)
    _log("mnist mlp 784-100-10 step: %.3f ms per-step, %.3f ms fused k=20"
         % (step_ms, fused_ms))
    return {"step_ms": step_ms, "step_fused_ms": fused_ms}


def phase_alexnet():
    """AlexNet train samples/sec/chip on synthetic 227x227x3 data."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import alexnet

    prng.seed_all(4)
    batch, steps = 256, 10   # 256 keeps the MXU fed (~1.8x batch 64)
    n = batch * 2
    x = np.random.RandomState(0).rand(n, 227, 227, 3).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 1000, n).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=batch,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(layers=alexnet(), loader=loader,
                          decision_config={"max_epochs": 1000},
                          name="bench-alexnet")
    wf.initialize()
    wf.loader.run()
    wf.trainer.run()          # compile
    _block(wf.trainer.class_stats[2]["loss"])
    # three back-to-back repeats: the r2→r3 "regression" (8,617 → 7,430)
    # was a cross-session comparison with no variance band — same-session
    # repeats make every future number interpretable (median headline,
    # min/max band published alongside)
    reps = []
    for _ in range(3):
        # ~30 ms/step vs the ~64 ms fetch RTT: slope timing
        reps.append(batch / _per_step_ms_slope(wf, steps) * 1e3)
    sps = sorted(reps)[1]
    _log("alexnet synthetic: %.1f samples/sec/chip "
         "(median of 3; band %.1f-%.1f, spread %.1f%%)"
         % (sps, min(reps), max(reps),
            (max(reps) - min(reps)) / sps * 100))
    return {"samples_per_sec": sps, "band_low": min(reps),
            "band_high": max(reps)}


def _lm_train_flops_per_token(d_model, n_layers, seq, vocab, d_ff=None,
                              n_heads=None, n_kv_heads=None):
    """Shared convention — see veles_tpu/ops/flops.py."""
    from veles_tpu.ops.flops import lm_train_flops_per_token
    return lm_train_flops_per_token(d_model, n_layers, seq, vocab,
                                    d_ff=d_ff, n_heads=n_heads,
                                    n_kv_heads=n_kv_heads)


def _run_lm(tag, zoo_kwargs, batch, seq, steps, steps_per_dispatch,
            vocab):
    """Shared LM-throughput harness: train ``steps`` minibatches through
    the StandardWorkflow hot loop, report tokens/sec and model FLOPs
    utilization against the detected chip peak."""
    import jax
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm

    prng.seed_all(5)
    n = batch * 4
    toks = np.random.RandomState(0).randint(
        0, vocab, (n, seq)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=batch,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(
        layers=transformer_lm(vocab_size=vocab, **zoo_kwargs),
        loader=loader, loss="lm",
        gd_defaults={"clip_norm": 1.0},
        decision_config={"max_epochs": 1000},
        steps_per_dispatch=steps_per_dispatch, name="bench-" + tag)
    wf.initialize()
    n_params = sum(int(np.prod(p.shape))
                   for lp in wf.trainer.params.values()
                   for p in jax.tree_util.tree_leaves(lp))
    for _ in range(2 * steps_per_dispatch):  # compile + warmup (2 sweeps)
        wf.loader.run()
        wf.trainer.run()
    wf.trainer.flush()
    _block(wf.trainer.class_stats[2]["loss"])
    ms_step = _per_step_ms_slope(wf, steps)
    tps = batch * seq / ms_step * 1e3
    fpt = _lm_train_flops_per_token(
        zoo_kwargs["d_model"], zoo_kwargs["n_layers"], seq, vocab,
        n_heads=zoo_kwargs.get("n_heads"),
        n_kv_heads=zoo_kwargs.get("n_kv_heads"))
    peak = _peak_bf16()
    mfu = tps * fpt / (peak * 1e12) if peak else 0.0
    _log("%s (%.1fM params, T=%d): %.0f tokens/sec/chip, "
         "%.1f ms/step, MFU %.1f%%"
         % (tag, n_params / 1e6, seq, tps, ms_step, mfu * 100))
    return {"tokens_per_sec": tps, "ms_per_step": ms_step,
            "mfu": mfu, "n_params": n_params,
            "peak_bf16_tflops": peak}


def phase_lm():
    """Causal transformer LM training throughput (tokens/sec/chip):
    GPT-style decoder (~25M params, T=1024, Pallas flash attention +
    fused FA2 backward, RoPE, GQA, AdamW with global-norm clipping, bf16
    MXU compute) through the SAME StandardWorkflow hot loop as every
    other model, with the fused k-step dispatch."""
    return _run_lm(
        "lm-25M",
        dict(d_model=512, n_heads=8, n_kv_heads=2, n_layers=8,
             dropout=0.0, impl="flash", pos="rope", solver="adamw",
             lr=1e-3),
        batch=8, seq=1024, steps=20, steps_per_dispatch=5, vocab=8192)


def phase_lm_large():
    """The MFU-credible flagship (round-3 verdict item #4): GPT-2-small
    class — 124M params, d=768, 12 heads, 12 layers, T=1024, vocab
    50304 (MXU-friendly multiple of 128), tied embeddings, flash
    attention + fused backward, RoPE, AdamW + global-norm clip, bf16
    compute, fused 4-step dispatch.  Target: >= 40% MFU single-chip.

    Walks a three-rung memory ladder, stepping down only on OOM:
    (remat="dots", batch 16) — selective dots_saveable checkpointing,
    no recompute FLOPs burned, the MFU-preserving first choice —
    then (full remat, batch 16), then (full remat, batch 8).  The
    result records which rung produced the headline number
    (``remat``/``batch`` keys)."""
    import gc

    base = dict(d_model=768, n_heads=12, n_layers=12, dropout=0.0,
                impl="flash", pos="rope", solver="adamw", lr=6e-4,
                tie_embeddings=True)
    # MFU ladder: selective remat first — "dots" keeps matmul outputs,
    # so the backward skips the recompute FLOPs that full remat burns
    # (recompute never counts toward MFU).  Full remat at b16, then b8,
    # are the progressively-smaller-memory fallbacks.
    from veles_tpu.ops.flops import LM_LARGE_LADDER
    ladder = [(remat, batch, steps)
              for remat, batch, steps, _ in LM_LARGE_LADDER]
    try:  # the rung order is model-ranked; log the predicted MFUs
        from tools.cost_model import predict_lm_large_ladder
        _log("lm_large ladder predicted MFU: %s"
             % ["%s/b%d: %.1f%%" % (r["remat"], r["batch"],
                                    100 * r["mfu"])
                for r in predict_lm_large_ladder()])
    except Exception:  # noqa: BLE001 — advisory only
        pass
    for i, (remat, batch, steps) in enumerate(ladder):
        try:
            return dict(_run_lm("lm-124M[remat=%s,b%d]" % (remat, batch),
                                dict(base, remat=remat), batch=batch,
                                seq=1024, steps=steps,
                                steps_per_dispatch=4, vocab=50304),
                        batch=batch, remat=str(remat))
        except Exception as e:  # noqa: BLE001 — RESOURCE_EXHAUSTED
            if i == len(ladder) - 1 or (
                    "RESOURCE_EXHAUSTED" not in str(e)
                    and "Out of memory" not in str(e)):
                raise
            _log("lm_large remat=%s b%d OOM — next rung" % (remat, batch))
        # retry OUTSIDE the except block: an in-flight exception's
        # traceback would pin the failed attempt's device buffers
        gc.collect()


def _chain_attn(attn_fn, q, k, v, iters, grad=False):
    """True kernel-time harness: ``iters`` attention calls chained INSIDE
    one jit dispatch (each call consumes the previous output as q — same
    shape), so per-dispatch tunnel latency amortizes away.  The round-2
    session proved per-dispatch timing is useless here: every config
    measured ~4-5 ms regardless of kernel (BENCH_SESSION.md).  With
    ``grad`` the chain feeds dQ back as the next q (fused backward
    timing).  Returns ms per single attention call (fwd or fwd+bwd)."""
    import jax
    from jax import lax

    import jax.numpy as jnp

    if grad:
        # FULL backward on both contenders — dQ and dK/dV (argnums=0
        # alone would let XLA dead-code the dK/dV matmuls and bias the
        # head-to-head).  dQ feeds back as the next chain link; dK/dV
        # stay live through cheap elementwise accumulators.
        g = jax.grad(
            lambda q_, k_, v_: attn_fn(q_, k_, v_).sum(),
            argnums=(0, 1, 2))

        def body(carry, _):
            y, ak, av = carry
            dq, dk, dv = g(y, k, v)
            return (dq.astype(y.dtype), ak + dk, av + dv), None

        def chain(y):
            (y, ak, av), _ = lax.scan(
                body, (y, jnp.zeros_like(k), jnp.zeros_like(v)), None,
                length=iters)
            return y, ak, av
    else:
        def body(y, _):
            return attn_fn(y, k, v).astype(y.dtype), None

        def chain(y):
            return lax.scan(body, y, None, length=iters)[0]

    f = jax.jit(chain, donate_argnums=(0,))
    out = _block(f(jnp.copy(q)))           # compile + warmup
    y = out[0] if grad else out
    dt = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        out = _block(f(y))
        y = out[0] if grad else out
        dt = min(dt, (time.perf_counter() - t0) / iters)
    return dt * 1e3


def phase_flash():
    """Pallas flash-attention kernel ON HARDWARE: correctness vs the
    naive reference, then chained in-jit timing (fwd f32/bf16, fused
    bwd, T=8192 long context) HEAD-TO-HEAD against XLA's O(T²) native
    attention — the number that decides whether the kernel earns its
    keep (round-2 verdict item #2)."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops.attention import attention
    from veles_tpu.ops.pallas.flash import flash_attention

    platform = jax.default_backend()
    key = jax.random.key(0)
    b, h, t, d = 4, 8, 1024, 128
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.float32) * 0.1
               for kk in jax.random.split(key, 3))
    flash = lambda q, k, v: flash_attention(q, k, v, causal=True)  # noqa
    naive = lambda q, k, v: attention(q, k, v, causal=True)        # noqa
    ref = naive(q, k, v)
    err = float(jnp.max(jnp.abs(jax.jit(flash)(q, k, v) - ref)))
    if err > 5e-3:
        raise AssertionError("flash kernel mismatch: max_err=%g" % err)

    # causal attention matmul flops for one call (qk + pv, T²/2 each)
    flops = _causal_attn_flops(b, h, t, d)

    def tf(ms):
        return flops / (ms / 1e3) / 1e12 if ms else 0.0

    ms = _chain_attn(flash, q, k, v, iters=20)
    q16, k16, v16 = (x.astype(jnp.bfloat16) for x in (q, k, v))
    err16 = float(jnp.max(jnp.abs(
        jax.jit(flash)(q16, k16, v16).astype(jnp.float32) - ref)))
    if err16 > 0.05:
        raise AssertionError("bf16 flash mismatch: max_err=%g" % err16)
    ms16 = _chain_attn(flash, q16, k16, v16, iters=20)
    ms16_xla = _chain_attn(naive, q16, k16, v16, iters=20)

    # fused Pallas backward: correctness vs the naive gradient, then
    # chained fwd+bwd timing vs XLA differentiating its own attention
    gf = jax.jit(jax.grad(lambda q, k, v: jnp.sum(
        flash(q, k, v) ** 2), argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        naive(q, k, v) ** 2), argnums=(0, 1, 2))(q, k, v)
    bwd_err = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gf, gr))
    if bwd_err > 5e-2:
        raise AssertionError("fused backward mismatch: %g" % bwd_err)
    ms_bwd = _chain_attn(flash, q16, k16, v16, iters=10, grad=True)
    ms_bwd_xla = _chain_attn(naive, q16, k16, v16, iters=10, grad=True)

    # long-context headline: one chip, T=8192 causal bf16 —
    # the O(T·block) VMEM tiling is what makes this shape possible.
    # Real-kernel only (interpret mode would outlive the watchdog).
    ms_long = ms_long_xla = ms_win = 0.0
    if platform == "tpu":
        bl, hl, tl, dl = 1, 8, 8192, 128
        ql, kl, vl = (jax.random.normal(kk, (bl, hl, tl, dl),
                                        jnp.bfloat16) * 0.1
                      for kk in jax.random.split(jax.random.key(2), 3))
        ms_long = _chain_attn(flash, ql, kl, vl, iters=10)
        fl = _causal_attn_flops(bl, hl, tl, dl)
        try:
            ms_long_xla = _chain_attn(naive, ql, kl, vl, iters=5)
        except Exception as e:  # noqa: BLE001 — XLA may OOM the T² matrix
            _log("naive XLA at T=8192 failed (%s) — flash-only number"
                 % type(e).__name__)
        _log("flash long-context T=8192 bf16: %.2f ms (%.1f TF/s "
             "causal-effective) vs XLA naive %.2f ms"
             % (ms_long, fl / (ms_long / 1e3) / 1e12, ms_long_xla))
        # sliding window at long context: the shrunken k-grid should
        # make this ~T/window times cheaper than full causal
        wfn = lambda q_, k_, v_: flash_attention(  # noqa: E731
            q_, k_, v_, causal=True, window=1024)
        ms_win = _chain_attn(wfn, ql, kl, vl, iters=10)
        _log("flash T=8192 window=1024 bf16: %.2f ms (%.1fx vs full "
             "causal)" % (ms_win, ms_long / ms_win if ms_win else 0.0))

    _log("pallas flash (4,8,1024,128) causal on %s, chained in-jit: "
         "fwd %.2f ms f32 | %.2f ms bf16 (%.1f TF/s) vs XLA %.2f ms | "
         "fwd+bwd %.2f ms vs XLA %.2f ms | errs fwd %.2e bwd %.2e"
         % (platform, ms, ms16, tf(ms16), ms16_xla, ms_bwd, ms_bwd_xla,
            err, bwd_err))
    return {"ms": ms, "ms_bf16": ms16, "ms_bf16_xla": ms16_xla,
            "tf_bf16": tf(ms16), "ms_bwd": ms_bwd,
            "ms_bwd_xla": ms_bwd_xla, "bwd_max_err": bwd_err,
            "max_err": err, "ms_long_t8192": ms_long,
            "ms_long_t8192_xla": ms_long_xla,
            "ms_long_t8192_w1024": ms_win, "platform": platform}


def phase_beam():
    """Long-context beam-search decode rate (T=4096, beam=8) vs greedy —
    the number that prices the per-step full-cache reorder documented at
    models/generate.py (O(T²·beam) HBM traffic per decode)."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm
    import jax.numpy as jnp

    prng.seed_all(9)
    # BENCH_BEAM_T: CPU smoke tests shrink the context (4095 scan
    # positions are a TPU-scale workload)
    t_max = int(os.environ.get("BENCH_BEAM_T", 4096))
    beam = 8
    toks = np.random.RandomState(0).randint(
        0, 512, (8, 32)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=4, class_lengths=[0, 0, 8])
    wf = StandardWorkflow(
        layers=transformer_lm(vocab_size=512, d_model=256, n_heads=8,
                              n_kv_heads=2, n_layers=2, dropout=0.0,
                              pos="rope", impl="flash"),
        loader=loader, loss="lm",
        decision_config={"max_epochs": 1}, name="bench-beam")
    wf.initialize()
    gen = LMGenerator(wf.trainer, max_len=t_max,
                      cache_dtype=jnp.bfloat16)
    prompt = toks[:1, :16]

    def timed(fn):
        fn()                              # compile + warmup
        reps = []
        for _ in range(3):                # median-of-3 (run variance)
            t0 = time.perf_counter()
            fn()
            reps.append(time.perf_counter() - t0)
        # the scan always runs all t_max - 1 positions (traced lengths)
        return sorted(reps)[1] / (t_max - 1) * 1e3

    ms_beam = timed(lambda: gen.beam_search(prompt, max_new=64,
                                            beam=beam))
    ms_greedy = timed(lambda: gen.generate(prompt, max_new=64))

    # speculative decode on a self-similar prompt (the regime n-gram
    # drafting exists for): wall-clock per generated token vs the plain
    # greedy scan — both prefill the long prompt
    rep = np.tile(np.arange(64, dtype=np.int32),
                  t_max // 64 + 1)[None, :t_max // 2]
    max_new = max(16, t_max // 8)

    def timed_gen(fn):
        fn()                              # compile + warmup
        reps = []
        for _ in range(3):                # median-of-3 (run variance)
            t0 = time.perf_counter()
            fn()
            reps.append(time.perf_counter() - t0)
        return sorted(reps)[1] / max_new * 1e3

    ms_spec = timed_gen(lambda: gen.generate_speculative(
        rep, max_new=max_new, draft_k=8))
    ms_plain = timed_gen(lambda: gen.generate(rep, max_new=max_new))
    # both paths prefill the prompt and decode ~max_new positions
    # (generate()'s post-prefill scan buckets on max_new), so ms/token
    # over max_new compares like for like
    _log("beam decode T=%d beam=%d (2L d=256 lm): %.3f ms/pos beam, "
         "%.3f ms/pos greedy (reorder cost x%.1f); speculative "
         "%.3f ms/tok vs plain %.3f ms/tok (x%.1f)"
         % (t_max, beam, ms_beam, ms_greedy,
            ms_beam / ms_greedy if ms_greedy else 0.0,
            ms_spec, ms_plain,
            ms_plain / ms_spec if ms_spec else 0.0))
    return {"ms_per_pos_beam8": ms_beam, "ms_per_pos_greedy": ms_greedy,
            "ms_per_tok_spec": ms_spec, "ms_per_tok_greedy": ms_plain,
            "t": t_max}


def phase_serve():
    """Weight-bound decode throughput: greedy ms/token on a
    GPT-2-small-class stack (untrained — timing only), f32 weights
    (as-trained) vs bf16 vs int8 W8A8 (root.common.serve.weights).
    Expected shape on TPU: f32 ≈ bf16 (XLA hoists the policy's bf16
    cast out of the decode scan, so the f32 baseline already streams
    bf16 per step — bf16 weights save resident memory, not bandwidth);
    int8 is the one that cuts per-step weight traffic, because the
    int8 payload enters the dot itself."""
    import numpy as np
    import jax.numpy as jnp
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm

    prng.seed_all(17)
    d = int(os.environ.get("BENCH_SERVE_D", 768))        # CPU smoke: 64
    n_layers = int(os.environ.get("BENCH_SERVE_L", 12))
    vocab = 50304 if d >= 768 else 512
    t_max = 512 if d >= 768 else 48
    toks = np.random.RandomState(0).randint(
        0, vocab, (4, 32)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=4, class_lengths=[0, 0, 4])
    wf = StandardWorkflow(
        layers=transformer_lm(vocab_size=vocab, d_model=d,
                              n_heads=max(1, d // 64), n_layers=n_layers,
                              dropout=0.0, pos="rope",
                              tie_embeddings=True),
        loader=loader, loss="lm", decision_config={"max_epochs": 1},
        name="bench-serve")
    wf.initialize()
    prompt = toks[:1, :16]

    def timed(gen):
        gen.generate(prompt, max_new=32)           # compile + warmup
        reps = []
        for _ in range(3):       # median-of-3: the 2026-08-01 window
            t0 = time.perf_counter()   # showed ~15% run-to-run spread
            gen.generate(prompt, max_new=32)
            reps.append(time.perf_counter() - t0)
        # the decode scan always runs all t_max - 1 traced positions
        return sorted(reps)[1] / (t_max - 1) * 1e3

    out = {"d_model": d, "n_layers": n_layers, "t": t_max}
    for name, w in (("f32", None), ("bf16", "bf16"), ("int8", "int8"),
                    ("w4a8", "w4a8")):
        gen = LMGenerator(wf.trainer, max_len=t_max,
                          cache_dtype=jnp.bfloat16, weights=w)
        out["ms_per_tok_" + name] = round(timed(gen), 4)
        del gen
    base = out["ms_per_tok_f32"]
    _log("serve decode %dM-class (d=%d L=%d T=%d): f32 %.3f ms/tok, "
         "bf16 %.3f (x%.2f), int8 %.3f (x%.2f), w4a8 %.3f (x%.2f)"
         % (12 * d * d * n_layers // 1_000_000 if d >= 768 else 0,
            d, n_layers, t_max, base, out["ms_per_tok_bf16"],
            base / out["ms_per_tok_bf16"] if out["ms_per_tok_bf16"]
            else 0.0, out["ms_per_tok_int8"],
            base / out["ms_per_tok_int8"] if out["ms_per_tok_int8"]
            else 0.0, out["ms_per_tok_w4a8"],
            base / out["ms_per_tok_w4a8"] if out["ms_per_tok_w4a8"]
            else 0.0))
    # PRE-REGISTERED target for the next TPU window: int8 >= 1.5x bf16
    # ms/tok on this memory-bound workload (BENCH_r05 measured only
    # 1.13x before the quantized-depth work; d=1536 already showed
    # 1.80x, so the flagship width is the honest judge).  The goal
    # itself lives in telemetry.ledger.TARGETS — one registry, so the
    # VL12xx contract lint can cross-check declared vs measured.
    out["target_int8_vs_bf16"] = _target("serve_int8_vs_bf16_x", 1.5)
    out["int8_vs_bf16"] = round(
        out["ms_per_tok_bf16"] / out["ms_per_tok_int8"], 3) \
        if out["ms_per_tok_int8"] else None

    # ---- paged continuous decode: bf16 pool vs int8 (QuantCache)
    # pool through the SAME fused kernel — prices the quantized-pool
    # variant's in-kernel dequant against its halved/quartered KV
    # stream (the serving-shaped number, 4 concurrent streams)
    from veles_tpu.models.generate import PagedContinuousBatcher
    slots, prompt_len = 4, 16
    max_new = max(16, t_max // 8)

    def timed_pool(cb):
        def run_pool():
            for i in range(slots):
                cb.submit(toks[i % toks.shape[0],
                               :prompt_len].tolist(), max_new)
            cb.run_all()
        run_pool()                       # compile + warmup
        t0 = time.perf_counter()
        run_pool()
        return (time.perf_counter() - t0) / (slots * max_new) * 1e3

    for name, cd in (("paged_bf16", jnp.bfloat16), ("paged_int8",
                                                    "int8")):
        # int8 tiles need 32 sublanes on silicon — a 16-block int8
        # pool would silently fall back to the gather tick and the
        # row would measure the wrong kernel (the CPU smoke's t_max
        # isn't 32-divisible; interpret mode fuses any block)
        block = 32 if (cd == "int8" and t_max % 32 == 0) else 16
        need = slots * -(-(prompt_len + max_new + 1) // block) * block
        genp = LMGenerator(wf.trainer, max_len=t_max, cache_dtype=cd,
                           weights="int8")
        cb = PagedContinuousBatcher(genp, slots=slots, block=block,
                                    pool_tokens=need)
        out["ms_per_tok_" + name] = round(timed_pool(cb), 4)
        out[name + "_fused"] = bool(cb.fused)
        out[name + "_block"] = cb.block
        del cb, genp
    _log("paged serve decode (int8 weights, %d streams): bf16 pool "
         "%.3f ms/tok (fused=%s), int8 pool %.3f ms/tok (fused=%s)"
         % (slots, out["ms_per_tok_paged_bf16"],
            out["paged_bf16_fused"], out["ms_per_tok_paged_int8"],
            out["paged_int8_fused"]))

    # ---- the speculation cliff, before/after: an all-greedy spec
    # pool vs the same pool with ONE sampled row.  Per-row routing
    # means the greedy rows keep speculating either way — the ratio
    # is the cliff's depth (was: whole-pool sampled step)
    from veles_tpu.models.generate import ContinuousBatcher
    rep_row = np.tile(np.arange(8, dtype=np.int32),
                      t_max)[: t_max // 2].tolist()
    spec_new = max(8, t_max // 8)

    def timed_spec(mixed):
        cb = ContinuousBatcher(LMGenerator(wf.trainer, max_len=t_max),
                               slots=slots, speculative_k=8)

        def run_pool():
            for i in range(slots):
                cb.submit(rep_row, spec_new,
                          temperature=(0.7 if mixed and i == 0
                                       else 0.0), seed=i)
            cb.run_all()
        run_pool()                       # compile + warmup
        t0 = time.perf_counter()
        run_pool()
        return (time.perf_counter() - t0) / (slots * spec_new) * 1e3

    out["ms_per_tok_spec_all_greedy"] = round(timed_spec(False), 4)
    out["ms_per_tok_spec_mixed"] = round(timed_spec(True), 4)
    cliff = (out["ms_per_tok_spec_mixed"]
             / out["ms_per_tok_spec_all_greedy"]
             if out["ms_per_tok_spec_all_greedy"] else 0.0)
    _log("speculation pool (k=8, %d streams): all-greedy %.3f ms/tok, "
         "one-sampled %.3f ms/tok (cliff x%.2f — per-row routing "
         "keeps greedy rows speculating)"
         % (slots, out["ms_per_tok_spec_all_greedy"],
            out["ms_per_tok_spec_mixed"], cliff))

    # ---- decode-tick stall under long-prompt admission: segmented
    # vs whole-prompt prefill at 3 prompt lengths.  One in-flight
    # decode stream; a long prompt admits mid-stream; the inter-tick
    # gap p50/p99 is what the stream's client feels.  PRE-REGISTERED
    # target: segmented p99 stays within 4x the no-admission cadence
    # while unsegmented scales with the whole prompt.
    from veles_tpu.models.generate import ContinuousBatcher as _CB
    gen_st = LMGenerator(wf.trainer, max_len=t_max)
    seg = max(8, t_max // 32)

    def stall_row(plen, segment):
        cb = _CB(gen_st, slots=2, prefill_segment=segment)
        long_prompt = toks[1 % toks.shape[0], :16].tolist() \
            * (plen // 16 + 1)
        long_prompt = [int(t) for t in long_prompt[:plen]]
        short = [int(t) for t in toks[0, :8]]
        # warm every shape (short decode, prefill buckets)
        cb.submit(short, 4)
        cb.submit(long_prompt, 2)
        cb.run_all()
        cb.submit(short, max(16, t_max // 8))
        cb.tick()
        gaps = []
        cb.submit(long_prompt, 2)
        last = time.perf_counter()
        while not cb.idle():
            cb.tick()
            now = time.perf_counter()
            gaps.append((now - last) * 1e3)
            last = now
        gaps.sort()
        return (gaps[len(gaps) // 2],
                gaps[min(len(gaps) - 1, int(0.99 * len(gaps)))])

    out["prefill_stall"] = {}
    for plen in (t_max // 4, t_max // 2, 3 * t_max // 4):
        p50_u, p99_u = stall_row(plen, 0)
        p50_s, p99_s = stall_row(plen, seg)
        out["prefill_stall"][str(plen)] = {
            "segment": seg,
            "unseg_p50_ms": round(p50_u, 4),
            "unseg_p99_ms": round(p99_u, 4),
            "seg_p50_ms": round(p50_s, 4),
            "seg_p99_ms": round(p99_s, 4)}
        _log("decode stall @ prompt %d: unsegmented p99 %.3f ms vs "
             "segmented(%d) p99 %.3f ms (p50 %.3f/%.3f)"
             % (plen, p99_u, seg, p99_s, p50_u, p50_s))
    # seg p99 <= 4x base cadence (goal declared in ledger.TARGETS)
    out["target_seg_stall_x"] = _target("serve_seg_stall_x", 4.0)

    # ---- cost-weighted vs least-loaded routing under a skewed-
    # length storm: 2 in-process replicas behind a FleetRouter,
    # 75/25 short/long buffered clients; completed wall per token.
    # PRE-REGISTERED: cost-weighted <= round-robin (pricing keeps
    # long prompts off the replica already holding one).
    import json as _json
    import http.client as _http
    import threading as _threading
    from veles_tpu.services.router import FleetRouter as _FR

    def routing_storm(placement):
        router = _FR(port=0, placement=placement,
                     prefill_prompt_min=0, rng_seed=3,
                     health_interval_ms=200)
        router.start()
        router.spawn_local(gen_st, 2, continuous_slots=4)
        short = [int(t) for t in toks[0, :8]]
        longp = [int(t) for t in toks[0, :8]] * (t_max // 16)
        longp = longp[:t_max // 2]
        n_short, n_long = 18, 6
        new_s, new_l = max(8, t_max // 16), 2

        def client(prompt, max_new):
            try:
                conn = _http.HTTPConnection(router.host, router.port,
                                            timeout=600)
                conn.request("POST", router.path, _json.dumps(
                    {"input": prompt,
                     "generate": {"max_new": max_new}}),
                    {"Content-Type": "application/json"})
                conn.getresponse().read()
                conn.close()
            except Exception:  # noqa: BLE001 — bench storm
                pass

        try:
            # warmup both replicas and shapes
            for api in router._local_apis:
                api.engine.wait(api.engine.submit_async(short, new_s))
                api.engine.wait(api.engine.submit_async(longp, new_l))
            jobs = ([(short, new_s)] * n_short
                    + [(longp, new_l)] * n_long)
            threads = [_threading.Thread(target=client, args=(p, n),
                                         daemon=True)
                       for p, n in jobs]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=600)
            wall = time.perf_counter() - t0
            toks_done = n_short * new_s + n_long * new_l
            return wall * 1e3 / toks_done
        finally:
            router.stop()

    out["routing_rr_ms_per_tok"] = round(
        routing_storm("round_robin"), 4)
    out["routing_cost_ms_per_tok"] = round(routing_storm("cost"), 4)
    # cost-weighted must not lose (goal declared in ledger.TARGETS)
    out["target_cost_vs_rr"] = _target("serve_cost_vs_rr_x", 1.0)
    _log("skewed-length routing storm (2 replicas): round-robin "
         "%.3f ms/tok vs cost-weighted %.3f ms/tok (x%.2f)"
         % (out["routing_rr_ms_per_tok"],
            out["routing_cost_ms_per_tok"],
            out["routing_rr_ms_per_tok"]
            / out["routing_cost_ms_per_tok"]
            if out["routing_cost_ms_per_tok"] else 0.0))
    return out


def phase_servecont():
    """Continuous-batching serving throughput — NOT in the default
    phase list; run manually on hardware (``python bench.py --phase
    servecont``).  N concurrent greedy streams through one
    ContinuousBatcher slot pool vs the same N requests decoded solo,
    aggregate tokens/sec each way: the multi-stream utilization number
    a serving deployment actually sees (each tick advances every slot
    for ~one slot's weight-streaming cost)."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.generate import ContinuousBatcher, LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm

    prng.seed_all(17)
    d = int(os.environ.get("BENCH_SERVE_D", 768))        # CPU smoke: 64
    n_layers = int(os.environ.get("BENCH_SERVE_L", 12))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", 8))
    vocab = 50304 if d >= 768 else 512
    t_max = 512 if d >= 768 else 48
    max_new = t_max // 4
    toks = np.random.RandomState(0).randint(
        0, vocab, (slots, 32)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=4,
                             class_lengths=[0, 0, slots])
    wf = StandardWorkflow(
        layers=transformer_lm(vocab_size=vocab, d_model=d,
                              n_heads=max(1, d // 64),
                              n_layers=n_layers, dropout=0.0,
                              pos="rope", tie_embeddings=True),
        loader=loader, loss="lm", decision_config={"max_epochs": 1},
        name="bench-servecont")
    wf.initialize()
    gen = LMGenerator(wf.trainer, max_len=t_max)

    prompt_len = 16     # shared by pool sizing AND the submit slices
    tpd = int(os.environ.get("BENCH_SERVE_TPD", 16))
    # ONE batcher reused across warmup + timed runs (a fresh instance
    # would recompile its fused tick); fuse K engine ticks per dispatch
    # so the remote-tunnel dispatch cost amortizes exactly like the
    # trainer's fused sweep.  BENCH_SERVE_PAGED=<block> swaps in the
    # block-table pool (budget = exactly the workload's tokens) so the
    # window prices the paged gather/scatter overhead vs dense.
    # BENCH_SERVE_PAGED_FUSED=0 forces the gather tick so a window can
    # price fused (pool read inside the Pallas kernel) vs gather
    # (dense re-materialization per tick) on real HBM
    paged = int(os.environ.get("BENCH_SERVE_PAGED", 0))
    fused = os.environ.get("BENCH_SERVE_PAGED_FUSED", "1") != "0"
    if paged:
        from veles_tpu.models.generate import PagedContinuousBatcher
        need = slots * -(-(prompt_len + max_new) // paged) * paged
        cb = PagedContinuousBatcher(gen, slots=slots,
                                    ticks_per_dispatch=tpd,
                                    block=paged, pool_tokens=need,
                                    fused=fused)
    else:
        cb = ContinuousBatcher(gen, slots=slots, ticks_per_dispatch=tpd)

    def run_pool():
        for i in range(slots):
            cb.submit(toks[i, :prompt_len].tolist(), max_new)
        cb.run_all()

    run_pool()                           # compile + warmup
    t0 = time.perf_counter()
    run_pool()
    pool_s = time.perf_counter() - t0
    pool_tps = slots * max_new / pool_s

    gen.generate(toks[:1, :prompt_len], max_new)  # compile + warmup
    t0 = time.perf_counter()
    for i in range(slots):
        gen.generate(toks[i:i + 1, :prompt_len], max_new)
    solo_s = time.perf_counter() - t0
    solo_tps = slots * max_new / solo_s
    _log("continuous serving (%dM-class d=%d L=%d, %d streams x %d "
         "new): pool %.0f tok/s vs solo-sequential %.0f tok/s "
         "(x%.1f)"
         % (12 * d * d * n_layers // 1_000_000 if d >= 768 else 0,
            d, n_layers, slots, max_new, pool_tps, solo_tps,
            pool_tps / solo_tps if solo_tps else 0.0))
    return {"pool_tokens_per_sec": pool_tps,
            "solo_tokens_per_sec": solo_tps,
            "slots": slots, "max_new": max_new, "d_model": d,
            "paged_block": paged,
            "paged_fused": bool(paged) and getattr(cb, "fused", False)}


def phase_flashtune():
    """Block-size sweep for the flash kernels — DELEGATED to the kernel
    autotuner (veles_tpu.tuner): the forward and the SPLIT dq/dkv
    backward grids are swept independently (the backward used to be
    yoked to the forward's geometry — BENCH_r05's 1.7x-slower-than-XLA
    backward was exactly that), every candidate passes the VP6xx
    tile/VMEM audit before it may win, and winners persist in the tuner
    cache — the next TPU window's launches pick them up at
    ``tuner.lookup`` time with no bake step.  NOT in the default phase
    list; run manually on hardware (``python bench.py --phase
    flashtune``).  The legacy ``t{T}_q{bq}_k{bk}`` grid keys are still
    emitted (now with per-config dq/dkv backward timings alongside the
    forward) for watcher logs and tools/bake_flashtune.py."""
    from veles_tpu import tuner as tn
    from veles_tpu.tuner import sweeps

    tuner = tn.get_tuner()
    results = sweeps.sweep_flash(
        tuner, ts=(1024, 8192), d=128, kinds=sweeps.FLASH_KINDS,
        iters=8, repeats=3, warmup=1, log=_log,
        source="bench-flashtune")

    # flatten the per-kernel sweeps back into the legacy grid: one
    # entry per (T, bq, bk) carrying fwd ms + the isolated dq/dkv
    # kernel timings.  Each backward measurement runs its forward at
    # the PINNED geometry (flash_measure passes only the candidate's
    # bwd blocks — constant across candidates, that is the isolation),
    # so the reconstructed fwd+bwd at this row is
    #   ms + (ms_dq - F_pin) + (ms_dkv - F_pin)
    # with F_pin = the measured forward at the pinned geometry;
    # ms_bwd is omitted when that row failed (no honest number exists)
    from veles_tpu.ops.pallas.flash import _resolve_blocks
    per = {}
    for (kind, t), res in results.items():
        for row in res.candidates:
            if row.get("ms") is None:
                continue
            cfg = row["config"]
            per.setdefault((t, cfg["block_q"], cfg["block_k"]),
                           {})[kind] = row["ms"]
    grid = {}
    for (t, bq, bk), kinds in sorted(per.items()):
        if "fwd" not in kinds:
            continue
        b, h, d = (4, 8, 128) if t == 1024 else (1, 8, 128)
        flops = _causal_attn_flops(b, h, t, d)
        ms = kinds["fwd"]
        entry = {"ms": round(ms, 3),
                 "tf": round(flops / (ms / 1e3) / 1e12, 1)}
        pin_q, pin_k = _resolve_blocks(t, t, d, "bfloat16")[:2]
        f_pin = per.get((t, min(pin_q, -(-t // 128) * 128),
                         min(pin_k, -(-t // 128) * 128)),
                        {}).get("fwd")
        if "bwd_dq" in kinds and "bwd_dkv" in kinds:
            entry["ms_dq"] = round(kinds["bwd_dq"], 3)
            entry["ms_dkv"] = round(kinds["bwd_dkv"], 3)
            if f_pin is not None:
                entry["ms_bwd"] = round(
                    max(ms, ms + (kinds["bwd_dq"] - f_pin)
                        + (kinds["bwd_dkv"] - f_pin)), 3)
        grid["t%d_q%d_k%d" % (t, bq, bk)] = entry
    for (kind, t), res in sorted(results.items()):
        if res.winner:
            grid["winner_%s_t%d" % (kind, t)] = {
                "config": res.winner["config"],
                "ms": round(res.winner["ms"], 3),
                "audit_rejected": len(res.audit_rejected)}
    return grid


def phase_ring():
    """Ring attention through shard_map ON HARDWARE (1-chip mesh here;
    the same code path the 8-device CPU tests exercise for correctness)."""
    import jax
    import jax.numpy as jnp
    from veles_tpu.ops.attention import attention
    from veles_tpu.parallel.mesh import make_mesh
    from veles_tpu.parallel.ring import ring_attention_sharded

    platform = jax.default_backend()
    mesh = make_mesh({"seq": len(jax.devices())})
    key = jax.random.key(1)
    b, h, t, d = 2, 4, 512, 64
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.float32) * 0.1
               for kk in jax.random.split(key, 3))
    out = ring_attention_sharded(q, k, v, mesh, causal=True)
    ref = attention(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(out - ref)))
    if err > 5e-3:
        raise AssertionError("ring attention mismatch: max_err=%g" % err)
    _log("ring attention on %s (%d-dev mesh): max_err %.2e"
         % (platform, len(jax.devices()), err))
    return {"max_err": err, "platform": platform,
            "n_devices": len(jax.devices())}


def phase_kohonen():
    """Kohonen SOM training throughput (BASELINE config 4): batched
    (MXU matmul) step vs the per-sample online scan."""
    from veles_tpu.models.kohonen import benchmark_som

    res = benchmark_som(n_samples=2048, n_features=784, sx=16, sy=16,
                        minibatch_size=512, steps=20)
    _log("kohonen 16x16 som, batch 512, 784 feats: %.3f ms/step batched, "
         "%.3f fused-sweep vs %.2f scan (%.1fx / %.1fx), qe %.4f/%.4f"
         % (res["ms_per_step"], res["sweep_ms_per_step"],
            res["scan_ms_per_step"], res["speedup"], res["sweep_speedup"],
            res["quantization_error"], res["sweep_quantization_error"]))
    return res


# --------------------------------------------------------------------------
# Orchestrator
# --------------------------------------------------------------------------

def _probe(deadline):
    """Cheap device probe with retries — decides whether to run phases at
    all.  Runs in a watchdogged child like everything else."""
    code = ("import jax; d = jax.devices(); "
            "print('PROBE_OK', len(d), d[0].platform)")
    for i, backoff in enumerate((0,) + _BACKOFF):
        if backoff:
            _log("probe retry in %ds ..." % backoff)
            time.sleep(backoff)
        if time.monotonic() > deadline:
            return False, "probe: global deadline exceeded"
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code], capture_output=True,
                text=True, timeout=150)
        except subprocess.TimeoutExpired:
            _log("probe attempt %d: timeout (150s)" % (i + 1))
            continue
        if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
            _log("probe ok: %s" % proc.stdout.strip())
            return True, None
        _log("probe attempt %d failed: %s"
             % (i + 1, (proc.stderr or "")[-300:].replace("\n", " ")))
    return False, "device probe failed after %d attempts" % (1 + len(_BACKOFF))


def _run_phase(name, timeout, deadline):
    """One phase in a watchdogged subprocess; retry on backend flakes."""
    for i, backoff in enumerate((0,) + _BACKOFF):
        if backoff:
            _log("%s: retry in %ds ..." % (name, backoff))
            time.sleep(backoff)
        remaining = deadline - time.monotonic()
        if remaining < 30:
            return {"ok": False, "error": "skipped: global deadline"}
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--phase", name],
                capture_output=True, text=True,
                timeout=min(timeout, remaining))
        except subprocess.TimeoutExpired:
            _log("%s: WATCHDOG timeout after %ds" % (name, timeout))
            # a hang is rarely cured by retrying — one attempt only
            return {"ok": False, "error": "watchdog timeout (%ds)" % timeout}
        sys.stderr.write(proc.stderr or "")
        sys.stderr.flush()
        for line in (proc.stdout or "").splitlines():
            if line.startswith(_RESULT_TAG):
                out = json.loads(line[len(_RESULT_TAG):])
                out["ok"] = True
                _log("%s: done in %.1fs" % (name, time.time() - t0))
                return out
        err_blob = (proc.stderr or "") + (proc.stdout or "")
        if any(pat in err_blob for pat in RETRYABLE):
            _log("%s: attempt %d hit retryable backend error" % (name, i + 1))
            continue
        tail = err_blob.strip().splitlines()[-3:]
        return {"ok": False, "error": "rc=%d: %s"
                % (proc.returncode, " | ".join(tail)[-400:])}
    return {"ok": False, "error": "retries exhausted (backend unavailable)"}


#: on success the measured numbers persist here; when the chip is later
#: unreachable the fail-soft JSON carries them as last_known_good so a
#: transient tunnel outage doesn't erase the evidence
_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      ".bench_last_good.json")

#: the checked-in persistent performance ledger (telemetry.ledger) —
#: append-only JSONL, seeded from BENCH_r05's last_known_good.  Every
#: successful run appends its rows here; last_known_good is READ back
#: from it (the single-blob _CACHE stays as write-through legacy so
#: the driver's existing key keeps working).
_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "PERF_LEDGER.jsonl")


def _bank_line(line):
    """Append every measured row to the persistent ledger, each with
    its pre-registered target attached (telemetry.ledger.BENCH_ROWS
    maps line key -> unit/polarity/phase).  Fail-soft by contract:
    ledger I/O must never fail a bench run."""
    try:
        from veles_tpu.telemetry import ledger as _ledgermod
        n = _ledgermod.PerfLedger(_LEDGER).append_bench_line(line)
        _log("banked %d rows into %s" % (n, os.path.basename(_LEDGER)))
    except Exception as e:  # noqa: BLE001 — fail-soft by contract
        _log("perf ledger unavailable: %s" % e)


def _ledger_last_good():
    """last_known_good reconstructed from the ledger's per-key history
    — the persistent, multi-run replacement for the single-blob
    _CACHE (which remains the fallback)."""
    try:
        from veles_tpu.telemetry import ledger as _ledgermod
        return (_ledgermod.PerfLedger(_LEDGER).last_known_good_line()
                or None)
    except Exception:  # noqa: BLE001 — fail-soft by contract
        return None

_EMPTY = (0, 0.0, False, None)

#: result-key prefix → phase whose failure mode decides carry eligibility
_KEY_PHASE = (("gemm", "gemm"), ("mlp_", "mlp"), ("alexnet_", "alexnet"),
              ("lm_large_", "lm_large"), ("lm_", "lm"), ("flash_", "flash"),
              ("beam_", "beam"), ("serve_", "serve"), ("ring_", "ring"),
              ("kohonen_", "kohonen"),
              ("value", "gemm"), ("vs_baseline", "gemm"))


def _merge_cache(line, results):
    """Per-key last-known-good merge: a freshly measured value always
    wins, and a key this run could NOT measure (tunnel died mid-run:
    watchdog timeout, deadline, backend unavailable) keeps the previous
    run's evidence instead of clobbering it with zero.  A phase that RAN
    — whether it succeeded (its zeros are deliberate, e.g. the shrunken
    beam smoke zeroing the t4096 headline) or failed on a real assertion
    — is a real measurement: its keys must NOT be papered over by stale
    numbers.  Only keys of phases with no result at all are carried, and
    ``carried_from`` records the original measurement date per carried
    key so mixed-date records stay honest."""
    new = {k: v for k, v in line.items() if k != "error"}
    new["measured_at"] = time.strftime("%Y-%m-%d %H:%M:%S")
    ran = {p for p, r in results.items()
           if r.get("ok") or "rc=" in str(r.get("error", ""))}
    try:
        with open(_CACHE) as f:
            old = json.load(f)
    except (OSError, ValueError):
        old = {}
    carried = dict(old.get("carried_from", {}))
    for k, v in old.items():
        if k in ("measured_at", "carried_from") or v in _EMPTY:
            continue
        phase = next((p for pre, p in _KEY_PHASE if k.startswith(pre)), None)
        if new.get(k) in _EMPTY and phase not in ran:
            new[k] = v
            carried.setdefault(k, old.get("measured_at", "unknown"))
        else:
            carried.pop(k, None)
    if carried:
        new["carried_from"] = carried
    return new


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", help="internal: run one phase")
    parser.add_argument("--budget", type=float,
                        default=float(os.environ.get("BENCH_BUDGET", 2400)),
                        help="global wall-clock budget, seconds")
    args = parser.parse_args()

    if args.phase:
        # persistent XLA cache: phases run in fresh subprocesses, so
        # without this every phase re-pays first-compile out of tunnel
        # uptime; with it a window's second run (and the driver's
        # end-of-round capture) skips straight to measurement
        from veles_tpu import compile_cache
        compile_cache.enable()
        result = globals()["phase_" + args.phase]()
        print(_RESULT_TAG + json.dumps(result), flush=True)
        return

    deadline = time.monotonic() + args.budget
    results = {}
    ok, probe_err = _probe(deadline)
    if ok:
        for name, timeout in PHASES:
            results[name] = _run_phase(name, timeout, deadline)
    else:
        _log("probe failed — skipping all phases: %s" % probe_err)

    gemm = results.get("gemm", {})
    errors = {n: r["error"] for n, r in results.items() if not r.get("ok")}
    if probe_err:
        errors["probe"] = probe_err
    gflops = gemm.get("gflops", 0.0)
    flash = results.get("flash", {})
    line = {
        "metric": "gemm_3001x3001_f32_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / BASELINE_GEMM_GFLOPS, 2),
        "gemm_bf16_gflops": round(gemm.get("bf16_gflops", 0.0), 1),
        "gemm_bf16_mfu": round(gemm.get("bf16_mfu", 0.0), 3),
        "gemm_precision_overhead_pct": round(
            gemm.get("precision_overhead_pct", 0.0), 1),
        "peak_bf16_tflops": gemm.get("peak_bf16_tflops", 0.0),
        "mlp_step_ms": round(results.get("mlp", {}).get("step_ms", 0.0), 3),
        "mlp_step_fused_ms": round(
            results.get("mlp", {}).get("step_fused_ms", 0.0), 3),
        "alexnet_samples_per_sec": round(
            results.get("alexnet", {}).get("samples_per_sec", 0.0), 1),
        "alexnet_band_low": round(
            results.get("alexnet", {}).get("band_low", 0.0), 1),
        "alexnet_band_high": round(
            results.get("alexnet", {}).get("band_high", 0.0), 1),
        "lm_tokens_per_sec": round(
            results.get("lm", {}).get("tokens_per_sec", 0.0), 1),
        "lm_mfu": round(results.get("lm", {}).get("mfu", 0.0), 3),
        "lm_large_tokens_per_sec": round(
            results.get("lm_large", {}).get("tokens_per_sec", 0.0), 1),
        "lm_large_mfu": round(
            results.get("lm_large", {}).get("mfu", 0.0), 3),
        "kohonen_ms_per_step": round(
            results.get("kohonen", {}).get("ms_per_step", 0.0), 2),
        "kohonen_sweep_speedup": round(
            results.get("kohonen", {}).get("sweep_speedup", 0.0), 1),
        "flash_ok": bool(flash.get("ok")),
        "flash_platform": flash.get("platform"),
        "flash_ms_bf16": round(flash.get("ms_bf16", 0.0), 3),
        "flash_ms_bf16_xla": round(flash.get("ms_bf16_xla", 0.0), 3),
        "flash_ms_bwd": round(flash.get("ms_bwd", 0.0), 3),
        "flash_ms_bwd_xla": round(flash.get("ms_bwd_xla", 0.0), 3),
        "flash_bwd_max_err": flash.get("bwd_max_err", 0.0),
        "flash_ms_long_t8192": round(flash.get("ms_long_t8192", 0.0), 2),
        "flash_ms_long_t8192_xla": round(
            flash.get("ms_long_t8192_xla", 0.0), 2),
        # only a genuine T=4096 run may claim the headline key (a
        # BENCH_BEAM_T-shrunken smoke must not masquerade as it)
        "beam_ms_per_pos_t4096": round(
            results.get("beam", {}).get("ms_per_pos_beam8", 0.0)
            if results.get("beam", {}).get("t") == 4096 else 0.0, 3),
        "serve_ms_per_tok_bf16": round(
            results.get("serve", {}).get("ms_per_tok_bf16", 0.0), 3),
        "serve_ms_per_tok_int8": round(
            results.get("serve", {}).get("ms_per_tok_int8", 0.0), 3),
        "ring_ok": bool(results.get("ring", {}).get("ok")),
        "error": ("; ".join("%s: %s" % kv for kv in sorted(errors.items()))
                  or None),
    }
    # derived ratio headlines — the keys the pre-registered targets
    # (telemetry.ledger.TARGETS) actually judge; computed here so the
    # ledger's target-bearing rows exist whenever their inputs do
    serve = results.get("serve", {})
    if line["serve_ms_per_tok_int8"]:
        line["serve_int8_vs_bf16_x"] = round(
            line["serve_ms_per_tok_bf16"]
            / line["serve_ms_per_tok_int8"], 3)
    stalls = [v for v in (serve.get("prefill_stall") or {}).values()
              if isinstance(v, dict) and v.get("seg_p50_ms")]
    if stalls:
        line["serve_seg_stall_x"] = round(
            max(v["seg_p99_ms"] / v["seg_p50_ms"] for v in stalls), 2)
    if serve.get("routing_cost_ms_per_tok"):
        line["serve_cost_vs_rr_x"] = round(
            serve.get("routing_rr_ms_per_tok", 0.0)
            / serve["routing_cost_ms_per_tok"], 3)
    if line["flash_ms_bwd_xla"]:
        line["flash_bwd_vs_xla_x"] = round(
            line["flash_ms_bwd"] / line["flash_ms_bwd_xla"], 3)
    # predicted-vs-measured record (tools/cost_model.py): every number
    # above has an offline roofline prediction riding alongside, so a
    # short uptime window confirms the model instead of exploring
    try:
        from tools.cost_model import predictions_for_bench
        line["predicted"] = predictions_for_bench()
    except Exception as e:  # noqa: BLE001 — predictions are advisory
        _log("cost model unavailable: %s" % e)
    if gemm.get("ok"):
        try:
            with open(_CACHE, "w") as f:
                json.dump(_merge_cache(line, results), f)
        except OSError:
            pass
        _bank_line(line)
    else:
        lkg = _ledger_last_good()
        if lkg is None and os.path.exists(_CACHE):
            try:
                lkg = json.load(open(_CACHE))
            except (OSError, ValueError):
                lkg = None
        if lkg is not None:
            line["last_known_good"] = lkg
    print(json.dumps(line), flush=True)


def _guarded_main():
    """The one-JSON-line-on-stdout contract must survive even a bug in
    the orchestrator itself (the r02 driver capture once recorded
    ``parsed: null`` from a malformed tail).  Any uncaught exception
    still emits a minimal, parseable fail-soft line.  Phase children
    (``--phase``) are exempt: their parent wants the raw rc + traceback
    to drive retry/error classification."""
    if "--phase" in sys.argv:
        return main()
    try:
        main()
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — fail-soft by contract
        line = {"metric": "gemm_3001x3001_f32_gflops", "value": 0.0,
                "unit": "GFLOP/s", "vs_baseline": 0.0,
                "error": "orchestrator: %s: %s" % (type(e).__name__, e)}
        lkg = _ledger_last_good()
        if lkg is not None:
            line["last_known_good"] = lkg
        else:
            try:
                with open(_CACHE) as f:
                    line["last_known_good"] = json.load(f)
            except (OSError, ValueError):
                pass
        print(json.dumps(line), flush=True)


if __name__ == "__main__":
    _guarded_main()
