#!/usr/bin/env python3
"""Measure the digits-proxy metric distribution across seeds to derive
honest accuracy-gate thresholds (VERDICT r3 #5: thresholds from the
published deltas with the margin math written down, not generous round
numbers).

Each proxy in tests/test_training.py stands in for a published reference
row (manualrst_veles_algorithms.rst) that the zero-egress environment
cannot reproduce.  This sweep runs each proxy at N seeds and prints
mean/min/max so the gate can be set at worst-observed x 1.25 (platform
drift allowance), with the numbers recorded in the test docstring.

    JAX_PLATFORMS=cpu python tools/proxy_margins.py --seeds 5
"""

import argparse
import json
import os
import sys

# force-override (not setdefault): the session env pins JAX_PLATFORMS
# to the TPU plugin, but the margin sweep is CPU statistics
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def run_mlp(seed):
    from tests.test_training import make_workflow
    wf = make_workflow(max_epochs=25, seed=seed)
    wf.initialize()
    wf.run()
    return float(wf.decision.best_metric)


def run_ae(seed):
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    prng.seed_all(seed)
    x = (load_digits().data / 16.0).astype(np.float32)
    loader = FullBatchLoader(None, data=x, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.05, "gradient_moment": 0.9},
            {"type": "all2all", "output_sample_shape": 64,
             "learning_rate": 0.05, "gradient_moment": 0.9},
        ],
        loader=loader, loss="mse",
        decision_config={"max_epochs": 20}, name="margin-ae")
    wf.initialize()
    wf.run()
    return float(wf.decision.best_metric)


def run_conv(seed):
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    prng.seed_all(seed)
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32).reshape(-1, 8, 8, 1)
    y = d.target.astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[
            {"type": "conv_strict_relu", "n_kernels": 8, "kx": 3,
             "ky": 3, "learning_rate": 0.1, "gradient_moment": 0.9},
            {"type": "max_pooling", "kx": 2, "ky": 2},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.1, "gradient_moment": 0.9},
        ],
        loader=loader, decision_config={"max_epochs": 25},
        name="margin-conv")
    wf.initialize()
    wf.run()
    return float(wf.decision.best_metric)


def run_conv_ae(seed):
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import conv_autoencoder
    prng.seed_all(seed)
    x = (load_digits().data / 16.0).astype(np.float32).reshape(-1, 8, 8, 1)
    loader = FullBatchLoader(None, data=x, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=conv_autoencoder(n_kernels=8, lr=0.02), loader=loader,
        loss="mse", decision_config={"max_epochs": 15},
        name="margin-conv-ae")
    wf.initialize()
    wf.run()
    baseline = float(np.sqrt((x ** 2).mean()))
    return float(wf.decision.best_metric) / baseline  # fraction of trivial


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", type=int, default=5)
    ap.add_argument("--proxies", default="mlp,ae,conv,conv_ae")
    args = ap.parse_args()
    seeds = [1234, 5, 9, 17, 42, 77, 101][:args.seeds]
    out = {}
    for name in args.proxies.split(","):
        fn = globals()["run_" + name]
        vals = []
        for s in seeds:
            v = fn(s)
            vals.append(v)
            print("%s seed=%-5d %.4f" % (name, s, v), flush=True)
        out[name] = {"mean": float(np.mean(vals)),
                     "min": float(np.min(vals)),
                     "max": float(np.max(vals)),
                     "gate_1p25x_worst": float(np.max(vals) * 1.25),
                     "seeds": seeds, "values": vals}
        print("%s: mean %.4f  min %.4f  max %.4f  -> gate %.4f"
              % (name, out[name]["mean"], out[name]["min"],
                 out[name]["max"], out[name]["gate_1p25x_worst"]),
              flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
