#!/usr/bin/env python3
"""Synthesize MNIST-shaped idx files for smoke runs.

CI boxes (and fresh checkouts) have no dataset mount, but the telemetry
smoke job must run the REAL MNIST sample — same loader, same idx parser,
same 784-100-10 workflow shape — so this writes structurally-valid
``train/t10k`` idx images+labels full of deterministic noise into a
directory that ``root.common.dirs.datasets`` can point at.  Nothing is
downloaded; accuracy is meaningless by construction (the accuracy gates
keep using the real data via tests/test_accuracy_gates.py).

Usage::

    python tools/make_synth_mnist.py ci-datasets/mnist --train 600 --test 200
"""

import argparse
import os
import struct

import numpy as np


def write_idx_images(path, n, rng):
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))        # u8, 3-dim
        f.write(struct.pack(">III", n, 28, 28))
        f.write(rng.randint(0, 256, (n, 28, 28), np.uint8).tobytes())


def write_idx_labels(path, n, rng):
    with open(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 1))        # u8, 1-dim
        f.write(struct.pack(">I", n))
        f.write(rng.randint(0, 10, (n,), np.uint8).tobytes())


def main(argv=None):
    p = argparse.ArgumentParser(
        description="write synthetic MNIST idx files for smoke runs")
    p.add_argument("directory", help="target dir (the samples expect "
                   "<datasets>/mnist — pass that path)")
    p.add_argument("--train", type=int, default=600)
    p.add_argument("--test", type=int, default=200)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    os.makedirs(args.directory, exist_ok=True)
    rng = np.random.RandomState(args.seed)
    write_idx_images(os.path.join(args.directory,
                                  "train-images-idx3-ubyte"),
                     args.train, rng)
    write_idx_labels(os.path.join(args.directory,
                                  "train-labels-idx1-ubyte"),
                     args.train, rng)
    write_idx_images(os.path.join(args.directory,
                                  "t10k-images-idx3-ubyte"),
                     args.test, rng)
    write_idx_labels(os.path.join(args.directory,
                                  "t10k-labels-idx1-ubyte"),
                     args.test, rng)
    print("synthetic MNIST (%d train / %d test) -> %s"
          % (args.train, args.test, args.directory))


if __name__ == "__main__":
    main()
