#!/usr/bin/env python
"""Chaos gate for the AUTOSCALING SERVING PLANE — the merge of the
old `serve-fleet` harness (tools/serve_loadtest.py --fleet) and the
serving side of `pod-chaos`: the pod master now OWNS the fleet
(`services.podmaster.ServeFleetMaster`, docs/services.md "Autoscaling
fleet"), so the chaos must hit the whole stack at once — router,
replicas, agents, autoscaler and replacement policy — not each tier
in isolation.

The scenario:

1. a ServeFleetMaster over ``--hosts`` per-host agents brings up the
   declarative fleet spec (min replicas, same-seed tiny transformers
   — greedy decode identical everywhere, so splices are checkable);
2. a ``--clients`` streaming storm hits the master's ROUTER; the
   overload drives the SLO shedder's measured queue-wait overshoot
   past 1.0, and the AUTOSCALER must scale the fleet up (the
   measured-feedback loop under test);
3. while the fleet is RESIZING (the scale-up spawn still in flight),
   one whole host is SIGKILLed — agent and every replica process on
   it, machine-is-gone semantics (down marker, no agent respawn);
4. the router must mark the dead replicas down within ONE health
   interval, mid-stream clients must fail over with byte-identical
   splices, and the master must replace the lost capacity on the
   surviving host (``fleet.replace`` cause=host-death, resize
   bucket — planned recovery, never the crash-loop budget);
5. the storm ends; sustained idle must scale the fleet back down to
   min — every scale-down drain of a serving replica must exit 0
   (SIGTERM drain: lossless by construction);
6. audits: ok+shed == clients with byte-identical results, zero
   leaked slots/KV-blocks/threads on every survivor, no crash-loop /
   deterministic-bug valve fired, replacement serving the exact
   expected output.

Exit 0 iff every gate passes; ``--json`` writes the report and
``--flight-dump`` leaves the merged flight/blackbox artifacts.

    python tools/fleet_chaos.py --clients 250 --json fleet-chaos.json \
        --flight-dump fleet-chaos-dump
"""

import argparse
import json
import os
import shutil
import signal
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import chaos_common as cc     # noqa: E402 — path set above
from tools import serve_loadtest as lt   # noqa: E402


def _wait(cond, what, timeout, errors, poll=0.05):
    """Poll ``cond()`` until truthy; records a timeout error and
    returns None otherwise."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = cond()
        if out:
            return out
        time.sleep(poll)
    errors.append("timed out waiting for %s (%.0fs)" % (what, timeout))
    return None


def _ready_ports(status, host=None):
    return {rep: r["port"] for rep, r in status["replicas"].items()
            if r["state"] == "ready" and r["port"]
            and (host is None or r["host"] == host)}


def _kill_host(master, victim, errors):
    """Machine-is-gone: down marker (no agent respawn), SIGKILL the
    agent, then every replica process recorded for that host — both
    the master's pids and the agent's replica pidfiles (a spawn still
    in flight has announced no pid to the master yet)."""
    with open(master.host_down_file(victim), "w") as f:
        f.write("fleet_chaos host kill\n")
    pids = set()
    st = master.status()
    for rep, r in st["replicas"].items():
        if r["host"] == victim and r["pid"]:
            pids.add(r["pid"])
    wd = master.host_workdir(victim)
    try:
        for name in os.listdir(wd):
            if name.startswith("replica-") and name.endswith(".pid"):
                try:
                    pids.add(int(open(os.path.join(wd, name))
                                 .read().split()[0]))
                except (OSError, ValueError, IndexError):
                    pass
    except OSError:
        pass
    agent = master._agent_procs.get(victim)
    if agent is None:
        errors.append("no agent process for host %d" % victim)
        return None
    try:
        agent.kill()
    except OSError:
        pass
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
    return {"agent_pid": agent.pid, "replica_pids": sorted(pids)}


def _trace_cli(router, tid, sample_path):
    """The acceptance-path ``veles-tpu-trace <id>`` invocation
    against the LIVE fleet: its rendered timeline (gapless verdict +
    phase footer included) becomes the CI sample artifact.  Returns
    the CLI's exit code."""
    import contextlib
    import io

    from veles_tpu.telemetry import tracecli
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = tracecli.main(
            [tid, "--url", "http://%s:%d%s"
             % (router.host, router.port, router.path)])
    if sample_path and buf.getvalue():
        try:
            with open(sample_path, "w") as f:
                f.write(buf.getvalue())
        except OSError:
            pass
    return rc


def run_chaos(args):
    from veles_tpu.services.podmaster import ServeFleetMaster
    from veles_tpu.telemetry import flight

    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet_chaos_")
    os.makedirs(workdir, exist_ok=True)
    report = {"workdir": workdir, "clients": args.clients,
              "hosts": args.hosts, "seed": args.seed,
              "spec": {"min": args.fleet_min, "max": args.fleet_max,
                       "per_host": args.per_host}}
    errors = []
    victim = args.hosts - 1
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    replica_argv = lt.replica_cmd(args, 0, dump_dir=args.flight_dump)
    master = ServeFleetMaster(
        replica_argv, n_hosts=args.hosts, workdir=workdir,
        fleet_min=args.fleet_min, fleet_max=args.fleet_max,
        per_host=args.per_host, env=env,
        health_interval_ms=args.health_interval_ms,
        # harness-tempo autoscaler: decide fast, damp generously (the
        # PLANNED resizes under test must never hit the flap valve)
        scale_up_overshoot=1.0, scale_idle_s=args.scale_idle_s,
        scale_cooldown_s=args.scale_cooldown_s,
        scale_window_s=60.0, scale_max_per_window=16,
        autoscale_interval_s=0.2,
        loss_window_s=3.0, loss_strikes=2,
        min_uptime_s=5.0, seed=args.seed)
    prompt = [int(1 + i % 7) for i in range(args.prompt_len)]
    t_all = time.monotonic()
    try:
        master.start()
        # ---- fleet up at spec minimum ------------------------------
        t0 = time.monotonic()
        st = _wait(lambda: (lambda s:
                            s if s["live_replicas"] >= args.fleet_min
                            else None)(master.status()),
                   "fleet at min=%d" % args.fleet_min,
                   args.timeout / 2, errors)
        if st is None:
            report["errors"] = errors
            return report
        report["phases"] = {"fleet_up_s":
                            round(time.monotonic() - t0, 2)}

        # ---- warmup every replica directly; capture the expected
        # uninterrupted result (same seed everywhere)
        expected = None
        for rep, port in sorted(_ready_ports(st).items()):
            status, out = cc.http_json(
                "127.0.0.1", port, "/service", method="POST",
                body=json.dumps({"input": prompt,
                                 "generate":
                                     {"max_new": args.max_new}}),
                timeout=300)
            if status != 200:
                errors.append("warmup of replica %s failed: %s %s"
                              % (rep, status, out))
                report["errors"] = errors
                return report
            if expected is None:
                expected = out["result"][0]
            elif list(expected) != list(out["result"][0]):
                report["replica_divergence"] = True
        report["expected_len"] = len(expected or [])

        # ---- the storm through the ROUTER --------------------------
        router = master.router
        tally, lock = {}, threading.Lock()
        stream_errors, traces = [], []
        threads = [threading.Thread(
            target=cc.fleet_stream_client,
            args=(router.host, router.port, router.path, prompt,
                  args.max_new, expected,
                  "sess-%d" % (i % args.sessions), tally, lock),
            kwargs={"errors": stream_errors, "traces": traces},
            daemon=True)
            for i in range(args.clients)]
        t0 = time.monotonic()
        for th in threads:
            th.start()

        def completed():
            with lock:
                return sum(tally.values())

        # ---- the autoscaler must scale UP under the overload -------
        scaled = _wait(
            lambda: (lambda s: s if s["desired"] > args.fleet_min
                     else None)(master.status()),
            "autoscale-up under the storm", args.timeout / 4, errors)
        report["scale_up_s"] = (round(time.monotonic() - t0, 3)
                                if scaled is not None else None)
        if scaled is None:
            for th in threads:
                th.join(timeout=60)
            report["tally"] = tally
            report["errors"] = errors
            return report

        # ---- SIGKILL a whole host WHILE the fleet is resizing ------
        cc.wait_fraction(completed, args.kill_frac, args.clients,
                         time.monotonic() + args.timeout / 4)
        st = master.status()
        report["resizing_at_kill"] = any(
            r["state"] == "spawning"
            for r in st["replicas"].values())
        report["victim_replicas"] = sorted(
            rep for rep, r in st["replicas"].items()
            if r["host"] == victim)
        kill_ts = time.monotonic()
        killed = _kill_host(master, victim, errors)
        report["host_killed"] = killed
        report["sigkill_at_completed"] = completed()

        # ---- storm completes across the failover -------------------
        for th in threads:
            th.join(timeout=300)
        report["stuck_client_threads"] = sum(
            1 for th in threads if th.is_alive())
        report["phases"]["storm_s"] = round(time.monotonic() - t0, 2)
        report["tally"] = tally
        report["stream_errors"] = stream_errors[:20]

        # ---- trace completeness: every ok request reconstructs a
        # gapless timeline from the live router (survivor spans still
        # resident — must run BEFORE scale-down drains them)
        tfails = []
        tfails, n_gapless, sample = cc.trace_gate(
            router.host, router.port, router.path, traces, tfails,
            label="fleet", sample_path=args.trace_sample)
        report["trace_ids"] = len(traces)
        report["trace_gapless"] = n_gapless
        report["trace_sample"] = sample
        report["trace_fails"] = tfails[:20]
        if sample is not None:
            report["trace_cli_rc"] = _trace_cli(
                router, sample["trace"], args.trace_sample)

        # ---- detection latency: first replica_down after the kill --
        down_ts = None
        for ev in flight.recorder.snapshot():
            if ev["kind"] == "serve.replica_down" \
                    and ev["ts"] >= kill_ts + cc.MONO_TO_WALL:
                down_ts = ev["ts"]
                break
        report["failover_detect_s"] = (
            round(down_ts - (kill_ts + cc.MONO_TO_WALL), 3)
            if down_ts is not None else None)

        # ---- the replacement must land on a survivor and SERVE -----
        def replaced():
            evs = [e for e in flight.recorder.snapshot()
                   if e["kind"] == "fleet.replace"
                   and e.get("cause") == "host-death"]
            return evs or None
        replace_evs = _wait(replaced, "fleet.replace (host-death)",
                            args.timeout / 4, errors)
        if replace_evs is not None:
            report["replace_detect_s"] = round(
                replace_evs[0]["ts"] - (kill_ts + cc.MONO_TO_WALL), 3)
            report["replaced_reps"] = [e.get("rep")
                                       for e in replace_evs]

        def replacement_ready():
            s = master.status()
            fresh = {rep: r for rep, r in s["replicas"].items()
                     if r["state"] == "ready" and r["host"] != victim
                     and rep not in report.get("victim_replicas", ())}
            return fresh if len(fresh) >= args.fleet_min else None
        fresh = _wait(replacement_ready, "replacement replica ready",
                      args.timeout / 2, errors)
        if fresh is not None:
            report["replacement_ready_s"] = round(
                time.monotonic() - kill_ts, 2)
            # the replacement serves the EXACT expected output
            rep, r = sorted(fresh.items())[-1]
            status, out = cc.http_json(
                "127.0.0.1", r["port"], "/service", method="POST",
                body=json.dumps({"input": prompt,
                                 "generate":
                                     {"max_new": args.max_new}}),
                timeout=300)
            report["replacement_serves"] = bool(
                status == 200
                and list(out.get("result", [[]])[0]) == list(expected))

        # ---- sustained idle must scale back DOWN to min ------------
        t0 = time.monotonic()
        st = _wait(
            lambda: (lambda s:
                     s if s["desired"] == args.fleet_min
                     and s["live_replicas"] == args.fleet_min
                     and not any(r["state"] in ("spawning", "dying",
                                                "draining")
                                 for r in s["replicas"].values())
                     else None)(master.status()),
            "scale-down back to min", args.timeout / 2, errors)
        report["scale_down_s"] = (round(time.monotonic() - t0, 2)
                                  if st is not None else None)

        # ---- survivor audits ---------------------------------------
        final = master.status()
        report["final"] = final
        leaks = {}
        for rep, port in sorted(_ready_ports(final).items()):
            ok = _wait(lambda p=port: cc.http_json(
                "127.0.0.1", p, "/service/health")[1]
                .get("queued", 1) == 0 or None,
                "replica %s idle" % rep, 60, errors)
            if ok is None:
                leaks[rep] = {"error": "never idled"}
                continue
            _, leaks[rep] = cc.http_json("127.0.0.1", port,
                                         "/service/leaks")
        report["survivor_leaks"] = leaks
        report["router_metrics"] = master.router.metrics()
        report["history"] = master.history
        report["drained"] = master.drained
        kinds = [e["kind"] for e in flight.recorder.snapshot()]
        report["flight_kinds"] = {
            k: kinds.count(k)
            for k in ("fleet.scale", "fleet.replace", "fleet.drain",
                      "fleet.drained", "serve.replica_up",
                      "serve.replica_down", "serve.failover")}
        if args.flight_dump:
            report["flight_dump"] = flight.dump(
                args.flight_dump, reason="fleet-chaos")
    finally:
        master.stop()
        master.wait(120)
        report["wall_s"] = round(time.monotonic() - t_all, 2)
    report["errors"] = errors
    return report


def gates(report, health_interval_ms=100.0):
    """Pass/fail verdicts (CI `fleet-chaos`); failure strings, empty
    = pass."""
    fails = []
    fails.extend(report.get("errors") or [])
    tally = report.get("tally", {})
    # zero lost/corrupt requests: ok+shed==clients, splices
    # byte-identical (any mismatch shows up as its own outcome)
    cc.tally_gate(tally, report.get("clients", 0), fails)
    if not tally.get("ok"):
        fails.append("no request completed (tally=%r)" % (tally,))
    if report.get("stuck_client_threads"):
        fails.append("stuck client threads: %d"
                     % report["stuck_client_threads"])
    if report.get("replica_divergence"):
        fails.append("replicas disagreed on the warmup output")
    # the autoscaler closed the loop, and the kill landed mid-resize
    if report.get("scale_up_s") is None:
        fails.append("the storm never drove an autoscale-up")
    if not report.get("resizing_at_kill"):
        fails.append("the host kill did not land while the fleet was "
                     "resizing (no spawn in flight)")
    # detection <= one health interval (+1s slack for ring scan and
    # scheduler noise)
    det = report.get("failover_detect_s")
    if det is None:
        fails.append("host SIGKILL never produced a "
                     "serve.replica_down")
    elif det > health_interval_ms / 1e3 + 1.0:
        fails.append("failover took %.3f s (> one %.0f ms health "
                     "interval + slack)" % (det, health_interval_ms))
    # replacement: detected fast, landed on a survivor, serves the
    # exact expected bytes ("registered <= one health interval +
    # spawn": replace_detect_s is the detection half,
    # replacement_ready_s includes the spawn)
    rdet = report.get("replace_detect_s")
    if rdet is None:
        fails.append("no fleet.replace (host-death) was recorded")
    elif rdet > health_interval_ms / 1e3 + 2.0:
        fails.append("replacement decision took %.3f s (> one health "
                     "interval + slack)" % rdet)
    if report.get("replacement_ready_s") is None:
        fails.append("no replacement replica became ready on a "
                     "survivor")
    if not report.get("replacement_serves"):
        fails.append("the replacement replica did not serve the "
                     "expected output")
    # lossless scale-down: back at min, every drained SERVING replica
    # exited 0 through the SIGTERM drain
    if report.get("scale_down_s") is None:
        fails.append("the fleet never scaled back down to min on "
                     "sustained idle")
    drained = report.get("drained") or []
    ready_drains = [d for d in drained if d.get("was_ready")]
    if not ready_drains:
        fails.append("no serving replica was ever drained (scale-"
                     "down/shutdown never exercised the SIGTERM "
                     "path)")
    for d in ready_drains:
        if d.get("rc") != 0 or d.get("kind") != "done":
            fails.append("drained replica %s exited %r (%s) — drain "
                         "was not lossless"
                         % (d.get("rep"), d.get("rc"), d.get("kind")))
    # valves: planned resizes must never consume the crash budget
    final = report.get("final") or {}
    if final.get("hold_replace"):
        fails.append("a valve held replacements: %r"
                     % final["hold_replace"])
    for h in report.get("history") or []:
        if h.get("action") == "replace" \
                and h.get("verdict") not in (None, "respawn"):
            fails.append("replacement valve fired: %r" % (h,))
        if h.get("action") == "replace" \
                and h.get("cause") == "host-death" \
                and h.get("counted"):
            fails.append("a host-death replacement consumed the "
                         "crash-loop budget: %r" % (h,))
    # trace completeness: 100 % of ok-accounted requests reconstruct
    # gapless through the host kill, and the router rollup carries
    # the per-phase decomposition
    fails.extend(report.get("trace_fails") or [])
    if report.get("trace_gapless") != tally.get("ok", 0):
        fails.append("trace completeness: %r gapless timelines for "
                     "%r ok requests"
                     % (report.get("trace_gapless"),
                        tally.get("ok", 0)))
    sample = report.get("trace_sample") or {}
    if not sample.get("crossed"):
        fails.append("no gapless trace crossed the host SIGKILL "
                     "(no router.failover span in any ok timeline)")
    if report.get("trace_cli_rc") != 0:
        fails.append("veles-tpu-trace against the live fleet exited "
                     "%r" % report.get("trace_cli_rc"))
    if not (report.get("router_metrics") or {}).get("phases"):
        fails.append("router /metrics carried no per-phase rollup")
    # survivors leak-free
    for rep, leaks in (report.get("survivor_leaks") or {}).items():
        if leaks.get("error"):
            fails.append("survivor %s: %s" % (rep, leaks["error"]))
            continue
        cc.leak_gate(leaks, fails, label="survivor %s" % rep)
    kinds = report.get("flight_kinds", {})
    for kind in ("fleet.scale", "fleet.replace", "fleet.drain",
                 "fleet.drained", "serve.replica_down",
                 "serve.failover"):
        if not kinds.get(kind):
            fails.append("missing flight event: %s" % kind)
    return fails


def run_prefill_chaos(args):
    """The disaggregated-prefill death gate (docs/services.md
    "Disaggregated prefill" failure matrix): a fleet with ONE
    prefill-role replica serves a storm of LONG prompts (every
    request's first leg lands there), the prefill replica is
    SIGKILLed mid-storm — with tick-delay-stretched segmented
    prefills, provably while admission prefill work is in flight —
    and the gates demand zero lost requests (every stream fails over
    byte-identically) plus a replacement PREFILL-role replica."""
    from veles_tpu.services.podmaster import ServeFleetMaster
    from veles_tpu.telemetry import flight

    workdir = args.workdir or tempfile.mkdtemp(prefix="prefill_chaos_")
    os.makedirs(workdir, exist_ok=True)
    long_len = args.long_prompt_len
    rargs = argparse.Namespace(
        slots=args.slots, paged_block=0, pool_tokens=None,
        slo_ms=0, seed=args.seed, tick_delay_ms=args.tick_delay_ms,
        max_len=long_len + args.max_new + 4,
        prefill_segment=args.prefill_segment)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    replica_argv = lt.replica_cmd(rargs, 0, dump_dir=args.flight_dump)
    master = ServeFleetMaster(
        replica_argv, n_hosts=1, workdir=workdir,
        fleet_min=2, fleet_max=2, per_host=4, env=env,
        prefill_replicas=1, prefill_prompt_min=16,
        prefill_handoff_new=2,
        health_interval_ms=args.health_interval_ms,
        autoscale=False, min_uptime_s=1.0, seed=args.seed)
    report = {"mode": "prefill-kill", "workdir": workdir,
              "clients": args.clients, "long_prompt_len": long_len,
              "prefill_segment": args.prefill_segment}
    errors = []
    prompt = [int(1 + i % 7) for i in range(long_len)]
    t_all = time.monotonic()

    def prefill_rep():
        s = master.status()
        for rep, r in sorted(s["replicas"].items()):
            if r.get("role") == "prefill" and r["state"] == "ready":
                return rep, r
        return None

    try:
        master.start()
        st = _wait(lambda: (lambda s:
                            s if s["live_replicas"] >= 2 else None)(
                                master.status()),
                   "fleet up (1 prefill + 1 decode)",
                   args.timeout / 2, errors)
        if st is None:
            report["errors"] = errors
            return report
        pr = prefill_rep()
        if pr is None:
            errors.append("no ready prefill-role replica")
            report["errors"] = errors
            return report
        report["prefill_rep"] = pr[0]
        # warmup every replica + capture the expected result (same
        # seed everywhere — splices must be byte-identical to it)
        expected = None
        for rep, port in sorted(_ready_ports(master.status()).items()):
            status, out = cc.http_json(
                "127.0.0.1", port, "/service", method="POST",
                body=json.dumps({"input": prompt,
                                 "generate":
                                     {"max_new": args.max_new}}),
                timeout=600)
            if status != 200:
                errors.append("warmup of replica %s failed: %s %s"
                              % (rep, status, out))
                report["errors"] = errors
                return report
            if expected is None:
                expected = out["result"][0]
            elif list(expected) != list(out["result"][0]):
                report["replica_divergence"] = True
        report["expected_len"] = len(expected)

        # ---- the long-prompt storm through the router --------------
        router = master.router
        tally, lock = {}, threading.Lock()
        stream_errors, traces = [], []
        threads = [threading.Thread(
            target=cc.fleet_stream_client,
            args=(router.host, router.port, router.path, prompt,
                  args.max_new, expected,
                  "sess-%d" % (i % args.sessions), tally, lock),
            kwargs={"errors": stream_errors, "timeout": 600,
                    "traces": traces},
            daemon=True) for i in range(args.clients)]
        t0 = time.monotonic()
        for th in threads:
            th.start()

        def completed():
            with lock:
                return sum(tally.values())

        # ---- SIGKILL the prefill replica MID-storm -----------------
        cc.wait_fraction(completed, args.kill_frac, args.clients,
                         time.monotonic() + args.timeout / 2)
        kill_ts = time.monotonic()
        victim = prefill_rep()
        if victim is None:
            errors.append("prefill replica already gone before the "
                          "kill")
        else:
            report["victim"] = victim[0]
            report["sigkill_at_completed"] = completed()
            try:
                os.kill(victim[1]["pid"], signal.SIGKILL)
            except OSError as e:
                errors.append("SIGKILL failed: %r" % (e,))

        for th in threads:
            th.join(timeout=600)
        report["stuck_client_threads"] = sum(
            1 for th in threads if th.is_alive())
        report["phases"] = {"storm_s": round(time.monotonic() - t0, 2)}
        report["tally"] = tally
        report["stream_errors"] = stream_errors[:20]

        # ---- trace completeness through the prefill kill + handoff -
        tfails = []
        tfails, n_gapless, sample = cc.trace_gate(
            router.host, router.port, router.path, traces, tfails,
            label="prefill", sample_path=args.trace_sample)
        report["trace_ids"] = len(traces)
        report["trace_gapless"] = n_gapless
        report["trace_sample"] = sample
        report["trace_fails"] = tfails[:20]
        if sample is not None:
            report["trace_cli_rc"] = _trace_cli(
                router, sample["trace"], args.trace_sample)

        # ---- the replacement must be PREFILL-role and ready --------
        def replacement():
            s = master.status()
            fresh = {rep: r for rep, r in s["replicas"].items()
                     if r["state"] == "ready"
                     and r.get("role") == "prefill"
                     and rep != report.get("victim")}
            return fresh or None
        fresh = _wait(replacement, "replacement prefill replica",
                      args.timeout / 2, errors)
        if fresh is not None:
            report["replacement_ready_s"] = round(
                time.monotonic() - kill_ts, 2)
        report["router_metrics"] = master.router.metrics()
        report["final"] = master.status()
        kinds = [e["kind"] for e in flight.recorder.snapshot()]
        report["flight_kinds"] = {
            k: kinds.count(k)
            for k in ("fleet.replace", "serve.replica_down",
                      "serve.failover", "serve.prefill_handoff")}
        if args.flight_dump:
            report["flight_dump"] = flight.dump(
                args.flight_dump, reason="prefill-chaos")
    finally:
        master.stop()
        master.wait(120)
        report["wall_s"] = round(time.monotonic() - t_all, 2)
    report["errors"] = errors
    return report


def prefill_gates(report):
    """Pass/fail for the prefill-kill leg: zero lost requests across
    the prefill replica's death, the handoff path actually routed,
    and a prefill-role replacement came back."""
    fails = list(report.get("errors") or [])
    tally = report.get("tally", {})
    cc.tally_gate(tally, report.get("clients", 0), fails)
    if not tally.get("ok"):
        fails.append("no request completed (tally=%r)" % (tally,))
    if report.get("stuck_client_threads"):
        fails.append("stuck client threads: %d"
                     % report["stuck_client_threads"])
    if report.get("replica_divergence"):
        fails.append("replicas disagreed on the warmup output")
    counters = report.get("router_metrics", {}).get("counters", {})
    if not counters.get("prefill_handoffs"):
        fails.append("no prefill handoff was ever routed (roles not "
                     "reaching the router?)")
    if not counters.get("failovers"):
        fails.append("the SIGKILL produced no failover — it cannot "
                     "have landed mid-prefill")
    if report.get("replacement_ready_s") is None:
        fails.append("no replacement prefill-role replica became "
                     "ready")
    fails.extend(report.get("trace_fails") or [])
    if report.get("trace_gapless") != tally.get("ok", 0):
        fails.append("trace completeness: %r gapless timelines for "
                     "%r ok requests"
                     % (report.get("trace_gapless"),
                        tally.get("ok", 0)))
    if report.get("trace_cli_rc") != 0:
        fails.append("veles-tpu-trace against the live fleet exited "
                     "%r" % report.get("trace_cli_rc"))
    final = report.get("final") or {}
    if final.get("hold_replace"):
        fails.append("a valve held replacements: %r"
                     % final["hold_replace"])
    kinds = report.get("flight_kinds", {})
    for kind in ("fleet.replace", "serve.replica_down",
                 "serve.failover"):
        if not kinds.get(kind):
            fails.append("missing flight event: %s" % kind)
    return fails


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="chaos gate for the autoscaling serving plane "
        "(docs/services.md 'Autoscaling fleet')")
    ap.add_argument("--clients", type=int, default=250)
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--fleet-min", type=int, default=2)
    ap.add_argument("--fleet-max", type=int, default=4)
    ap.add_argument("--per-host", type=int, default=4)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--paged-block", type=int, default=4)
    ap.add_argument("--pool-tokens", type=int, default=512)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=5)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="replica queue-wait SLO — the storm must "
                    "overshoot it to trip both the shedder and the "
                    "autoscaler")
    ap.add_argument("--tick-delay-ms", type=float, default=20.0,
                    help="per-tick decode delay on replicas "
                    "(stretches streams so the chaos lands "
                    "mid-flight)")
    ap.add_argument("--sessions", type=int, default=16)
    ap.add_argument("--health-interval-ms", type=float, default=100.0)
    ap.add_argument("--kill-frac", type=float, default=0.1,
                    help="completed-client fraction at which the "
                    "victim host is SIGKILLed (after the scale-up "
                    "fired)")
    ap.add_argument("--scale-idle-s", type=float, default=3.0)
    ap.add_argument("--scale-cooldown-s", type=float, default=1.0)
    ap.add_argument("--prefill-kill", action="store_true",
                    help="run the disaggregated-prefill death gate "
                    "instead: 1 prefill + 1 decode replica, long-"
                    "prompt storm, SIGKILL the prefill replica "
                    "mid-prefill, gate zero lost requests + a "
                    "prefill-role replacement")
    ap.add_argument("--long-prompt-len", type=int, default=64,
                    help="(--prefill-kill) long-prompt length")
    ap.add_argument("--prefill-segment", type=int, default=8,
                    help="(--prefill-kill) replica prefill segment "
                    "(tick-delay-stretched so the kill lands "
                    "mid-prefill)")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--workdir", default=None,
                    help="working directory (default: fresh tempdir; "
                    "kept on failure, removed on success unless "
                    "given)")
    ap.add_argument("--json", default=None, metavar="FILE")
    ap.add_argument("--flight-dump", default=None, metavar="DIR",
                    help="merged flight/blackbox artifacts (CI "
                    "upload)")
    ap.add_argument("--trace-sample", default=None, metavar="FILE",
                    help="write one rendered request timeline "
                    "(preferring a failover/handoff survivor) — the "
                    "CI trace artifact")
    args = ap.parse_args(argv)

    if args.prefill_kill:
        report = run_prefill_chaos(args)
        fails = prefill_gates(report)
        report["gates_failed"] = fails
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=2, default=str)
            print("[prefill-chaos] report -> %s" % args.json)
        print(json.dumps({k: report.get(k) for k in
                          ("tally", "victim", "sigkill_at_completed",
                           "replacement_ready_s", "wall_s")},
                         default=str))
        if fails:
            print("[prefill-chaos] GATES FAILED:", flush=True)
            for f in fails:
                print("  - %s" % f)
            print("[prefill-chaos] workdir kept: %s"
                  % report.get("workdir"))
            return 1
        print("[prefill-chaos] ALL GATES PASSED: %d clients "
              "(%d ok / %d shed), prefill replica SIGKILLed "
              "mid-prefill at %s completed, zero lost, prefill-role "
              "replacement ready in %.1fs"
              % (report["clients"], report["tally"].get("ok", 0),
                 report["tally"].get("shed", 0),
                 report.get("sigkill_at_completed"),
                 report["replacement_ready_s"]))
        if args.workdir is None:
            shutil.rmtree(report["workdir"], ignore_errors=True)
        return 0

    report = run_chaos(args)
    fails = gates(report,
                  health_interval_ms=args.health_interval_ms)
    report["gates_failed"] = fails
    # bank the gate numbers into the performance ledger — detection
    # and replacement latencies band run-over-run (fail-soft)
    cc.bank_gates(
        "fleet_chaos",
        {"fleet_failover_detect_s": (report.get("failover_detect_s"),
                                     "s", "lower"),
         "fleet_replace_detect_s": (report.get("replace_detect_s"),
                                    "s", "lower"),
         "fleet_replacement_ready_s": (
             report.get("replacement_ready_s"), "s", "lower")},
        workload="autoscale-storm", gate_failures=len(fails))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print("[fleet-chaos] report -> %s" % args.json)
    print(json.dumps({k: report.get(k) for k in
                      ("tally", "scale_up_s", "resizing_at_kill",
                       "failover_detect_s", "replace_detect_s",
                       "replacement_ready_s", "replacement_serves",
                       "scale_down_s", "wall_s")}, default=str))
    if fails:
        print("[fleet-chaos] GATES FAILED:", flush=True)
        for f in fails:
            print("  - %s" % f)
        print("[fleet-chaos] workdir kept: %s"
              % report.get("workdir"))
        return 1
    print("[fleet-chaos] ALL GATES PASSED: storm of %d clients "
          "(%d ok / %d shed), autoscale-up in %.1fs, host SIGKILL "
          "mid-resize detected in %.3fs, replacement serving in "
          "%.1fs, scale-down drained lossless back to min"
          % (report["clients"], report["tally"].get("ok", 0),
             report["tally"].get("shed", 0), report["scale_up_s"],
             report["failover_detect_s"],
             report["replacement_ready_s"]))
    if args.workdir is None:
        shutil.rmtree(report["workdir"], ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
