#!/usr/bin/env python
"""Chaos harness for the TRAINING survival layer — the train-plane twin
of tools/serve_loadtest.py.

Runs one **golden** (uninterrupted) training run, then the same command
under the respawn supervisor (`veles_tpu.services.supervisor`) while a
killer thread delivers real process deaths mid-sweep:

* **SIGKILL** after a checkpoint commit — the hard-preemption/OOM case:
  no cleanup, no final snapshot; the supervisor respawns and
  ``--snapshot auto`` resumes from ``_current``,
* **SIGTERM** — graceful preemption: the child checkpoints MID-EPOCH at
  the next cycle boundary and exits 75; the supervisor respawns
  immediately,
* **torn-commit injection** — the newest committed checkpoint is
  truncated in place (exactly what a kill inside a storage write leaves
  behind) and the child is SIGKILLed; the respawn must DETECT the torn
  file via its integrity manifest, quarantine it, and fall back to the
  previous commit.

The gate is **exactness**: after the chaos run completes, its final
checkpoint must be bit-identical — params, optimizer state, PRNG
counters, loader position/shuffle order, decision bookkeeping — to the
golden run's (``scripts.compare_snapshots.diff_report`` at threshold
0).  Plus: every planned kill delivered, the torn checkpoint detected
and skipped, zero unquarantined invalid checkpoints left in the ring,
and the supervisor's restart accounting consistent.  Exit code 0 iff
every gate passes; ``--json`` writes the report, ``--artifacts``
collects the children's crashdumps + per-attempt logs for CI.

    python tools/train_chaos.py --epochs 12 --kills 2 \
        --json chaos-report.json --artifacts train-chaos-dumps

Default workload: a self-contained digits MLP (sklearn's bundled set —
no dataset mount).  CI runs the synthetic-MNIST sample instead:

    python tools/make_synth_mnist.py ci-datasets/mnist
    python tools/train_chaos.py \
        --workflow samples/mnist_mlp.py --config samples/mnist_config.py \
        --prefix mnist-mlp \
        --config-list "root.common.dirs.datasets='$PWD/ci-datasets'" \
                      "root.mnist.max_epochs=8"
"""

import argparse
import json
import os
import random
import shutil
import signal
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import chaos_common as cc   # noqa: E402 — path set above

def build_argv(workflow, config, snap_dir, seed, extra_config=(),
               chaos_config=()):
    argv = [sys.executable, "-m", "veles_tpu", workflow]
    if config:
        argv.append(config)
    cl = ["root.common.dirs.snapshots=%r" % str(snap_dir)]
    cl += list(extra_config) + list(chaos_config)
    argv += ["--config-list"] + cl
    argv += ["--backend", "cpu", "--random-seed", str(seed),
             "--snapshot-every", "1", "--snapshot", "auto"]
    return argv


#: shared ``_current`` resolution (chaos_common)
_current_target = cc.current_target


class Killer(threading.Thread):
    """Delivers the kill plan: for each planned signal, wait until the
    CURRENT child (not a predecessor) has committed a checkpoint —
    ``_current`` flipped to a target written after the latest spawn —
    then sleep a beat so the kill lands mid-sweep, and fire.  After the
    plan, optionally injects the torn commit: truncate the newest
    committed checkpoint in place + immediate SIGKILL."""

    def __init__(self, sup, snap_dir, prefix, plan, torn, rng,
                 timeout=300.0, settle=(0.05, 0.35)):
        super(Killer, self).__init__(name="ChaosKiller", daemon=True)
        self.sup = sup
        self.snap_dir = str(snap_dir)
        self.prefix = prefix
        self.plan = list(plan)
        self.torn = bool(torn)
        self.rng = rng
        self.timeout = float(timeout)
        self.settle = settle
        self.delivered = []       # [{"signal", "ts", "pid", "target"}]
        self.torn_report = None   # {"path", "ts"} once injected
        self.errors = []

    def _wait_fresh_commit(self, not_this=None):
        """Block until _current points at a checkpoint committed by the
        live attempt (mtime after the last spawn), returning its path;
        None on timeout/stop."""
        deadline = time.time() + self.timeout
        while time.time() < deadline:
            target, mtime = _current_target(self.snap_dir, self.prefix)
            spawn_ts = self.sup.last_spawn_ts
            if target is not None and spawn_ts is not None \
                    and mtime > spawn_ts and target != not_this:
                return target
            time.sleep(0.02)
        self.errors.append("timed out waiting for a fresh checkpoint "
                           "commit (%.0fs)" % self.timeout)
        return None

    def run(self):
        last_target = None
        for sig in self.plan:
            target = self._wait_fresh_commit(not_this=last_target)
            if target is None:
                return
            last_target = target
            time.sleep(self.rng.uniform(*self.settle))
            pid = self.sup.current_pid()
            if pid is None:
                # the child finished/died between commit and kill —
                # wait for the respawn and retry once
                time.sleep(0.5)
                pid = self.sup.current_pid()
            if pid is None:
                self.errors.append(
                    "no live child to deliver %s to"
                    % signal.Signals(sig).name)
                return
            try:
                os.kill(pid, sig)
            except OSError as e:
                self.errors.append("kill %s failed: %s"
                                   % (signal.Signals(sig).name, e))
                return
            self.delivered.append(
                {"signal": signal.Signals(sig).name,
                 "ts": time.time(), "pid": pid, "target": target})
            print("[chaos] delivered %s to pid %d (after commit %s)"
                  % (signal.Signals(sig).name, pid,
                     os.path.basename(target)), flush=True)
        if self.torn:
            target = self._wait_fresh_commit(not_this=last_target)
            if target is None:
                return
            try:
                cc.truncate_commit(target)
            except OSError as e:
                self.errors.append("torn-commit injection failed: %s"
                                   % e)
                return
            pid = self.sup.current_pid()
            if pid is not None:
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
            self.torn_report = {"path": target, "ts": time.time()}
            print("[chaos] tore checkpoint %s (truncated) + SIGKILL"
                  % os.path.basename(target), flush=True)


#: shared ring audit (chaos_common — scan_commits is the one source
#: of truth for what counts as a commit, same as the agreement's)
_validate_ring = cc.validate_ring


def run_chaos(args):
    """The scenario; returns the report dict for :func:`gates`."""
    from veles_tpu.services.supervisor import Supervisor

    workdir = args.workdir or tempfile.mkdtemp(prefix="train_chaos_")
    os.makedirs(workdir, exist_ok=True)
    golden_dir = os.path.join(workdir, "golden")
    chaos_dir = os.path.join(workdir, "chaos")
    logs_dir = os.path.join(workdir, "logs")
    dumps_dir = os.path.join(workdir, "dumps")
    for d in (golden_dir, chaos_dir, logs_dir, dumps_dir):
        os.makedirs(d, exist_ok=True)

    workflow, config, prefix = args.workflow, args.config, args.prefix
    extra = list(args.config_list)
    if workflow is None:
        workflow = cc.write_digits_workflow(
            os.path.join(workdir, "chaos_workflow.py"),
            ns="chaos_train", name="chaos-train", default_epochs=12)
        extra += ["root.chaos_train.max_epochs=%d" % args.epochs]
        prefix = "chaos-train"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    # blackbox redirect is COMMON: both legs' supervisors classify
    # exits off the children's crashdumps, and both legs' dumps belong
    # in the collected artifacts
    common_cfg = ["root.common.snapshot.keep_last=%d" % args.keep_last,
                  "root.common.blackbox.dir=%r" % dumps_dir]

    report = {"workdir": workdir, "prefix": prefix,
              "seed": args.seed, "kills_planned": args.kills,
              "torn_planned": not args.no_torn}

    # ---- golden: one un-chaosed run ---------------------------------
    # also supervised (no killer): this sandbox's XLA startup can
    # segfault spuriously, and a transient environment crash must cost
    # a respawn+exact-resume — the very property under test — not the
    # golden reference
    t0 = time.time()
    golden_argv = build_argv(workflow, config, golden_dir, args.seed,
                             extra, common_cfg)
    print("[chaos] golden run: %s" % " ".join(golden_argv), flush=True)
    golden_sup = Supervisor(golden_argv, env=env,
                            max_restarts=4, backoff_base_ms=50,
                            backoff_max_ms=1000,
                            blackbox_dir=dumps_dir,
                            progress_paths=[golden_dir],
                            log_dir=os.path.join(logs_dir, "golden"),
                            install_signals=False, seed=args.seed)
    golden_result = {}
    golden_runner = threading.Thread(
        name="GoldenRunner",
        target=lambda: golden_result.update(rc=golden_sup.run()),
        daemon=True)
    golden_runner.start()
    golden_runner.join(timeout=args.timeout)
    if golden_runner.is_alive():
        golden_sup.stop()
        golden_runner.join(timeout=60)
    report["golden_rc"] = golden_result.get("rc")
    report["golden_spawns"] = golden_sup.spawn_count
    report["golden_wall_s"] = round(time.time() - t0, 2)
    golden_final, _ = _current_target(golden_dir, prefix)
    report["golden_final"] = golden_final
    if report["golden_rc"] != 0 or golden_final is None:
        report["error"] = "golden run failed — see logs/golden/"
        return report

    # ---- chaos: supervised run + killer thread ----------------------
    plan = [signal.SIGKILL if i % 2 == 0 else signal.SIGTERM
            for i in range(args.kills)]
    chaos_cfg = common_cfg + [
        "root.common.chaos.unit_delay_ms=%g" % args.unit_delay_ms]
    chaos_argv = build_argv(workflow, config, chaos_dir, args.seed,
                            extra, chaos_cfg)
    sup = Supervisor(chaos_argv, env=env,
                     max_restarts=args.kills + 6,
                     window_seconds=max(args.timeout, 600),
                     backoff_base_ms=50, backoff_max_ms=1000,
                     blackbox_dir=dumps_dir, progress_paths=[chaos_dir],
                     log_dir=logs_dir, install_signals=False,
                     seed=args.seed)
    rng = random.Random(args.seed)
    killer = Killer(sup, chaos_dir, prefix, plan,
                    torn=not args.no_torn, rng=rng,
                    timeout=args.timeout / 2)
    t0 = time.time()
    result = {}
    runner = threading.Thread(
        name="SupervisorRunner",
        target=lambda: result.update(rc=sup.run()), daemon=True)
    runner.start()
    killer.start()
    runner.join(timeout=args.timeout)
    if runner.is_alive():
        sup.stop()
        runner.join(timeout=60)
        report["error"] = "chaos run exceeded --timeout %ds" \
            % args.timeout
    killer.join(timeout=10)
    report["chaos_rc"] = result.get("rc")
    report["chaos_wall_s"] = round(time.time() - t0, 2)
    report["kills_delivered"] = killer.delivered
    report["torn"] = killer.torn_report
    report["killer_errors"] = killer.errors
    report["supervisor"] = {"spawns": sup.spawn_count,
                            "restarts": dict(sup.restarts),
                            "history": sup.history}

    # ---- audits ------------------------------------------------------
    chaos_final, _ = _current_target(chaos_dir, prefix)
    report["chaos_final"] = chaos_final
    # torn-commit detection: the fallback prints its markers on the
    # respawned attempt's stderr (captured per attempt), and the torn
    # file must end up quarantined
    markers = {"failed": False, "recovered": False}
    for name in sorted(os.listdir(logs_dir)):
        if not name.startswith("attempt-"):
            continue
        text = open(os.path.join(logs_dir, name), "rb").read().decode(
            "utf-8", "replace")
        if "failed to load" in text:
            markers["failed"] = True
        if "recovered from" in text:
            markers["recovered"] = True
    report["torn_markers"] = markers
    report["quarantined"] = sorted(
        n for n in os.listdir(chaos_dir) if n.endswith(".corrupt"))
    n_valid, invalid = _validate_ring(chaos_dir, prefix)
    report["ring_valid"] = n_valid
    report["ring_invalid"] = invalid

    if chaos_final and golden_final:
        from veles_tpu.scripts.compare_snapshots import diff_report
        try:
            report["exactness"] = diff_report(golden_final, chaos_final,
                                              threshold=0.0)
        except Exception as e:   # noqa: BLE001 — report, gate fails
            report["exactness"] = {"identical": False,
                                   "error": str(e)}
    return report


def gates(report):
    """Audit the report; returns the list of failed-gate strings."""
    fails = []
    if report.get("error"):
        fails.append(report["error"])
    if report.get("golden_rc") != 0:
        fails.append("golden run rc=%s" % report.get("golden_rc"))
    if report.get("chaos_rc") != 0:
        fails.append("supervised chaos run rc=%s"
                     % report.get("chaos_rc"))
    delivered = report.get("kills_delivered", [])
    if len(delivered) < report.get("kills_planned", 0):
        fails.append("only %d/%d kills delivered"
                     % (len(delivered), report["kills_planned"]))
    sigs = {k["signal"] for k in delivered}
    if report.get("kills_planned", 0) >= 2 and \
            not {"SIGKILL", "SIGTERM"} <= sigs:
        fails.append("kill plan must exercise both SIGKILL and "
                     "SIGTERM (got %s)" % sorted(sigs))
    if report.get("killer_errors"):
        fails.append("killer errors: %s" % report["killer_errors"])
    if report.get("torn_planned"):
        if not report.get("torn"):
            fails.append("torn-commit injection never happened")
        else:
            m = report.get("torn_markers", {})
            if not (m.get("failed") and m.get("recovered")):
                fails.append("torn checkpoint was not detected+skipped "
                             "on respawn (markers: %s)" % m)
            if not report.get("quarantined"):
                fails.append("torn checkpoint was not quarantined "
                             "(*.corrupt)")
    if report.get("ring_invalid"):
        fails.append("invalid checkpoints left in the ring: %s"
                     % report["ring_invalid"])
    exact = report.get("exactness")
    if not exact:
        fails.append("no exactness verdict (missing final checkpoint)")
    elif not exact.get("identical"):
        detail = exact.get("error") or exact.get("diffs", [])[:5]
        fails.append("final state NOT bit-identical to golden: %s"
                     % (detail,))
    sup = report.get("supervisor", {})
    if sup and sup.get("spawns", 0) < len(delivered) + 1:
        fails.append("supervisor accounting inconsistent: %d spawns "
                     "for %d kills" % (sup.get("spawns", 0),
                                       len(delivered)))
    return fails


def main(argv=None):
    p = argparse.ArgumentParser(
        description="chaos harness for preemption-exact supervised "
        "training (docs/distributed_training.md)")
    p.add_argument("--workflow", default=None,
                   help="workflow .py (default: self-contained digits "
                   "MLP)")
    p.add_argument("--config", default=None, help="config .py")
    p.add_argument("--config-list", nargs="*", default=[],
                   help="extra inline config statements for BOTH runs")
    p.add_argument("--prefix", default=None,
                   help="snapshot prefix (the workflow's name; "
                   "required with --workflow)")
    p.add_argument("--epochs", type=int, default=12,
                   help="epochs for the default digits workload")
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--kills", type=int, default=2,
                   help="external kills mid-sweep (alternating "
                   "SIGKILL/SIGTERM)")
    p.add_argument("--no-torn", action="store_true",
                   help="skip the torn-commit injection")
    p.add_argument("--unit-delay-ms", type=float, default=3.0,
                   help="scheduler stretch so kills land mid-sweep")
    p.add_argument("--keep-last", type=int, default=4,
                   help="checkpoint ring size for both runs")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--workdir", default=None,
                   help="working directory (default: fresh tempdir; "
                   "kept on failure, removed on success unless given)")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="write the full report here")
    p.add_argument("--artifacts", default=None, metavar="DIR",
                   help="collect crashdumps + attempt logs + a flight "
                   "dump here (CI upload)")
    args = p.parse_args(argv)
    if args.workflow is not None and args.prefix is None:
        p.error("--workflow needs --prefix (the workflow's name, for "
                "snapshot resolution)")

    report = run_chaos(args)
    fails = gates(report)
    report["gates_failed"] = fails
    # bank the gate numbers into the performance ledger — the chaos
    # overhead (chaos wall vs golden wall) bands run-over-run
    golden, chaos = (report.get("golden_wall_s"),
                     report.get("chaos_wall_s"))
    cc.bank_gates(
        "train_chaos",
        {"train_golden_wall_s": (golden, "s", "lower"),
         "train_chaos_wall_s": (chaos, "s", "lower"),
         "train_chaos_overhead_x": (
             round(chaos / golden, 3) if golden and chaos else None,
             "x", "lower")},
        workload="kill-storm",
        kills=len(report.get("kills_delivered", []) or []))

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print("[chaos] report -> %s" % args.json)
    if args.artifacts:
        os.makedirs(args.artifacts, exist_ok=True)
        workdir = report.get("workdir")
        for sub in ("dumps", "logs"):
            src = os.path.join(workdir, sub)
            dst = os.path.join(args.artifacts, sub)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
        from veles_tpu.telemetry import flight
        flight.dump(directory=args.artifacts, reason="train-chaos")
        print("[chaos] artifacts -> %s" % args.artifacts)

    print(json.dumps({k: report.get(k) for k in
                      ("golden_rc", "chaos_rc", "golden_wall_s",
                       "chaos_wall_s", "quarantined", "ring_valid")},
                     default=str))
    if fails:
        print("[chaos] GATES FAILED:", flush=True)
        for f in fails:
            print("  - %s" % f)
        print("[chaos] workdir kept: %s" % report.get("workdir"))
        return 1
    exact = report.get("exactness", {})
    print("[chaos] ALL GATES PASSED: %d kills + %s mid-sweep, final "
          "state bit-identical to golden (%d leaves)"
          % (len(report.get("kills_delivered", [])),
             "torn-commit" if report.get("torn") else "no torn",
             exact.get("n_leaves", 0)))
    if args.workdir is None:
        shutil.rmtree(report["workdir"], ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
