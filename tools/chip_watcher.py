#!/usr/bin/env python3
"""Chip watcher: probe the TPU tunnel until it answers, then fire the bench.

The tunnel to the single real chip is up only intermittently (round 3: one
16-minute window in ~12 hours).  The moment ``jax.devices()`` answers, the
most valuable thing this repo can do is convert code into *measured
evidence* — so this tool probes cheaply on a fixed cadence and, on the
first successful probe, immediately runs

    1. ``python bench.py``                  (full default phase list)
    2. ``python bench.py --phase flashtune`` (flash block-size sweep)
    3. ``python bench.py --phase gemmtune``  (bf16 MFU attribution sweep)
    4. ``python bench.py --phase servecont`` (continuous-batching pool)
    5. same with ``BENCH_SERVE_PAGED=16``    (paged vs dense serving)

tee-ing every byte to ``.watcher/`` and then EXITING, so a supervising
session is woken up to analyze the numbers while the window is still open.

Mirrors the reference's measured-evidence standard (its device DB is built
by running benchmarks on real silicon, ref ``veles/backends.py:672-731``);
the probe subprocess pattern matches ``bench.py::_probe``.
"""

import argparse
import datetime
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOGDIR = os.path.join(ROOT, ".watcher")

# flight-recorder hookup (fail-soft: the watcher must run on boxes
# where veles_tpu cannot even import) — probes and bench steps join the
# process flight ring, so a watcher crash dumps the probe/bench
# timeline via the health excepthook
sys.path.insert(0, ROOT)
try:
    from veles_tpu.telemetry import flight as _flight
    from veles_tpu.telemetry import health as _health
except Exception:   # noqa: BLE001 — observability is optional here
    _flight = _health = None


def _record(kind, **fields):
    if _flight is not None:
        _flight.record(kind, **fields)

PROBE_CODE = ("import jax; d = jax.devices(); "
              "print('PROBE_OK', len(d), d[0].platform)")


def _ts():
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M:%S")


def _log(line):
    msg = "[%s] %s" % (_ts(), line)
    print(msg, flush=True)
    with open(os.path.join(LOGDIR, "watcher.log"), "a") as f:
        f.write(msg + "\n")


def probe(timeout=150):
    """One cheap device probe in a watchdogged child. True iff it answered."""
    try:
        proc = subprocess.run([sys.executable, "-c", PROBE_CODE],
                              capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return False, "timeout (%ds)" % timeout
    if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
        return True, proc.stdout.strip()
    return False, (proc.stderr or "no output")[-200:].replace("\n", " ")


def run_step(argv, tag, timeout, env=None):
    """Run one bench step, tee output to .watcher/<tag>_<ts>.log."""
    stamp = datetime.datetime.now(
        datetime.timezone.utc).strftime("%Y%m%d_%H%M%S")
    path = os.path.join(LOGDIR, "%s_%s.log" % (tag, stamp))
    _log("running %s -> %s" % (" ".join(argv), path))
    _record("watcher.step.start", tag=tag, log=path)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(argv, cwd=ROOT, capture_output=True,
                              text=True, timeout=timeout, env=env)
        out = (proc.stdout or "") + "\n--- stderr ---\n" + (proc.stderr or "")
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        out = ((e.stdout or b"").decode("utf-8", "replace")
               if isinstance(e.stdout, bytes) else (e.stdout or ""))
        out += "\n--- WATCHDOG: step timeout (%ds) ---" % timeout
        rc = -1
    with open(path, "w") as f:
        f.write(out)
    _log("%s finished rc=%s in %.0fs" % (tag, rc, time.monotonic() - t0))
    _record("watcher.step.stop", tag=tag, rc=rc,
            dur_s=time.monotonic() - t0)
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=120.0,
                    help="seconds between probes")
    ap.add_argument("--once", action="store_true",
                    help="single probe, report, exit (0 iff chip answered)")
    ap.add_argument("--no-bench", action="store_true",
                    help="on success just exit 0 without firing the bench")
    ap.add_argument("--max-hours", type=float, default=0.0,
                    help="give up after this many hours (0 = forever)")
    args = ap.parse_args()
    os.makedirs(LOGDIR, exist_ok=True)
    if _health is not None:
        # a watcher crash leaves the probe/bench timeline behind
        _health.install(mode="watcher")

    deadline = (time.monotonic() + args.max_hours * 3600.0
                if args.max_hours else None)
    attempt = 0
    while True:
        attempt += 1
        ok, detail = probe()
        _record("watcher.probe", attempt=attempt, ok=ok,
                detail=str(detail)[:200])
        if ok:
            _log("probe %d OK: %s" % (attempt, detail))
            if args.no_bench or args.once:
                return 0
            py = sys.executable
            run_step([py, "bench.py"], "bench", timeout=3600)
            run_step([py, "bench.py", "--phase", "flashtune"],
                     "flashtune", timeout=1800)
            # flagship-shape (d=64) attention sweep: keeps the d<=64
            # block defaults (ops/pallas/flash.py) honest per window
            run_step([py, os.path.join("tools", "diag_flag_attn.py")],
                     "flag_attn", timeout=1200)
            run_step([py, "bench.py", "--phase", "gemmtune"],
                     "gemmtune", timeout=1800)
            # serving-plane phases (playbook step 5), three ways:
            # dense pool vs solo, paged GATHER tick (pinned to the
            # historical 'servecont_paged' name + the 420 tok/s anchor
            # series via BENCH_SERVE_PAGED_FUSED=0), and the new paged
            # FUSED tick (Pallas kernel reads the pool through the
            # block table) — the fused-vs-gather delta prices exactly
            # what the kernel buys back on real HBM.
            # the dense baseline must explicitly DROP any inherited
            # BENCH_SERVE_PAGED, or a leftover export would turn the
            # dense-vs-paged A/B into paged-vs-paged
            run_step([py, "bench.py", "--phase", "servecont"],
                     "servecont", timeout=1200,
                     env={k: v for k, v in os.environ.items()
                          if k != "BENCH_SERVE_PAGED"})
            run_step([py, "bench.py", "--phase", "servecont"],
                     "servecont_paged", timeout=1200,
                     env=dict(os.environ, BENCH_SERVE_PAGED="16",
                              BENCH_SERVE_PAGED_FUSED="0"))
            run_step([py, "bench.py", "--phase", "servecont"],
                     "servecont_paged_fused", timeout=1200,
                     env=dict(os.environ, BENCH_SERVE_PAGED="16"))
            _log("bench sequence complete — exiting so the session wakes up")
            return 0
        _log("probe %d down: %s" % (attempt, detail))
        if args.once:
            return 1
        if deadline and time.monotonic() > deadline:
            _log("max-hours reached — giving up")
            return 2
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
