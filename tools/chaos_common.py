"""Shared plumbing for the chaos harnesses — tools/serve_loadtest.py,
tools/train_chaos.py, tools/pod_chaos.py and tools/fleet_chaos.py all
compose the same primitives (READY handshakes, completion-triggered
chaos, startup-flake-tolerant spawns, gate accounting, checkpoint-ring
audits); factoring them here means the four harnesses cannot drift
apart on what "a replica is ready", "a request was lost" or "the ring
is valid" mean.

Nothing here decides POLICY — each harness keeps its own plan and its
own gates; this module is the vocabulary they share.
"""

import http.client
import json
import os
import select
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


# ===================================================================
# READY handshake + startup-flake-tolerant replica spawning
# ===================================================================

def read_ready(proc, deadline, parse=None):
    """Scan one subprocess's piped stdout for the fleet READY
    handshake (``restful.READY_LINE``), select-bounded so a silently
    wedged child hits the deadline instead of blocking the harness on
    the pipe forever.  Returns the parsed dict ({"port", "pid"}), or
    raises RuntimeError on death/timeout (message says which)."""
    if parse is None:
        from veles_tpu.services.restful import parse_ready_line
        parse = parse_ready_line
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            raise RuntimeError("replica startup timed out")
        ready, _, _ = select.select([proc.stdout], [], [],
                                    min(1.0, left))
        if not ready:
            continue
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("replica died during startup "
                               "(exit %r)" % proc.poll())
        parsed = parse(line)
        if parsed is not None:
            return parsed


def spawn_ready(cmds, timeout=300.0, envs=None, flake_retries=2,
                log_dir=None):
    """Spawn N replica subprocesses and wait for each one's READY
    handshake; returns ``[(proc, port, url)]`` (url =
    ``http://127.0.0.1:<port>/service``).

    A child that dies PRE-READY with the sandbox startup-flake
    fingerprint (abort-class signal, startup-shaped stderr — see
    ``supervisor.is_startup_flake``) is respawned up to
    ``flake_retries`` times: the documented environment abort comes
    in storms and must not fail a chaos run before the chaos even
    starts.  stderr goes to ``log_dir/replica-<i>.log`` (or a discard
    file) so the fingerprint has a transcript to judge.
    """
    from veles_tpu.services.supervisor import is_startup_flake
    envs = envs or [None] * len(cmds)
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    deadline = time.monotonic() + timeout

    def launch(i):
        log_path = os.path.join(log_dir, "replica-%d.log" % i) \
            if log_dir else None
        log = open(log_path, "wb") if log_path else \
            open(os.devnull, "wb")
        try:
            return subprocess.Popen(cmds[i], stdout=subprocess.PIPE,
                                    stderr=log, text=True,
                                    env=envs[i]), log_path
        finally:
            log.close()

    # launch ALL replicas first, THEN collect READY lines: N model
    # builds overlap, so fleet-up costs ~max(t_i), not sum(t_i)
    procs = [launch(i) for i in range(len(cmds))]
    out = []
    for i, (proc, log_path) in enumerate(procs):
        for attempt in range(flake_retries + 1):
            try:
                ready = read_ready(proc, deadline)
                break
            except RuntimeError:
                rc = proc.poll()
                if rc is None:     # wedged, not dead: timeout
                    proc.kill()
                    raise
                err = ""
                if log_path:
                    try:
                        with open(log_path, "rb") as f:
                            err = f.read(65536).decode("utf-8",
                                                       "replace")
                    except OSError:
                        pass
                if attempt < flake_retries and \
                        is_startup_flake(rc, "", err):
                    print("[chaos-common] replica %d startup flake "
                          "(rc=%s) — respawning" % (i, rc),
                          flush=True)
                    proc, log_path = launch(i)
                    continue
                raise
        out.append((proc, ready["port"],
                    "http://127.0.0.1:%d/service" % ready["port"]))
    return out


# ===================================================================
# completion-triggered chaos
# ===================================================================

def wait_fraction(completed, fraction, total, deadline,
                  poll_s=0.005):
    """Block until ``completed()`` (a callable) reaches ``fraction``
    of ``total`` — the completion-TRIGGERED chaos primitive: a kill
    gated on client progress provably lands mid-storm on any box
    speed, where a timed kill races the storm.  Returns the observed
    count (which may be short if ``deadline`` — a monotonic
    timestamp — passed first)."""
    target = fraction * total
    while completed() < target and time.monotonic() < deadline:
        time.sleep(poll_s)
    return completed()


# ===================================================================
# HTTP + report helpers
# ===================================================================

def http_json(host, port, path, method="GET", body=None, timeout=30):
    """One JSON request/response against a replica or router;
    returns (status, payload dict)."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request(method, path, body,
                     {"Content-Type": "application/json"}
                     if body else {})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read() or b"{}")
    finally:
        conn.close()


#: flight events stamp wall time; harnesses measure monotonic — one
#: offset sample converts between them (drift over a storm is far
#: below any gate's slack)
MONO_TO_WALL = time.time() - time.monotonic()


# ===================================================================
# the fleet storm client
# ===================================================================

def fleet_stream_client(router_host, router_port, router_path,
                        prompt, max_new, expected, session, tally,
                        lock, errors=None, timeout=180, traces=None):
    """One fleet storm client: stream through the ROUTER and verify
    the full concatenated result — chunk lines must splice to exactly
    the done line's result, and that result must equal the expected
    uninterrupted output (failover must be invisible).  Outcome lands
    in ``tally`` under ``lock``.  ``traces`` (a list): ok requests
    append their done-line trace id — the trace-completeness gate's
    input."""
    body = json.dumps({"input": prompt, "session": session,
                       "generate": {"max_new": max_new,
                                    "stream": True}})
    outcome = "error"
    try:
        conn = http.client.HTTPConnection(router_host, router_port,
                                          timeout=timeout)
        conn.request("POST", router_path, body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status == 503:
            resp.read()
            outcome = "shed"
        elif resp.status != 200:
            resp.read()
            outcome = "http_%d" % resp.status
        else:
            got, result, done = list(prompt), None, False
            trace_id = None
            while True:
                raw = resp.fp.readline()
                if not raw:
                    break
                msg = json.loads(raw)
                if "tokens" in msg:
                    got.extend(msg["tokens"])
                elif msg.get("done"):
                    result, done = msg["result"], True
                    trace_id = msg.get("trace")
                    break
                elif "error" in msg:
                    outcome = "stream_error"
                    if errors is not None:
                        with lock:
                            errors.append(str(msg["error"])[:200])
                    return
            if not done:
                outcome = "truncated"
            elif list(result) != list(got):
                outcome = "splice_mismatch"
            elif expected is not None \
                    and list(result) != list(expected):
                outcome = "bad_result"
            else:
                outcome = "ok"
                if traces is not None and trace_id:
                    with lock:
                        traces.append(trace_id)
        conn.close()
    except Exception:  # noqa: BLE001 — chaos clients absorb anything
        outcome = "error"
    finally:
        with lock:
            tally[outcome] = tally.get(outcome, 0) + 1


def trace_gate(router_host, router_port, router_path, traces, fails,
               label="", sample_path=None):
    """The trace-completeness gate: EVERY ok-accounted storm request
    must reconstruct a gapless cross-process timeline from the
    router's ``/trace/<id>`` aggregation — through kills, failovers
    and prefill handoffs (docs/services.md "Request tracing").
    ``sample_path``: write one rendered timeline as the CI artifact,
    preferring a trace that CROSSED a failover or handoff (the
    interesting kind).  Returns (fails, n_gapless, sample) where
    sample is ``{"trace": id, "crossed": bool}`` or None."""
    from veles_tpu.telemetry import tracing
    prefix = ("%s " % label) if label else ""
    if not traces:
        fails.append("%strace gate: no trace ids captured" % prefix)
        return fails, 0, None
    n_gapless, sample = 0, None
    for tid in traces:
        try:
            status, payload = http_json(
                router_host, router_port,
                "%s/trace/%s" % (router_path, tid))
        except Exception as e:  # noqa: BLE001 — the audit itself
            fails.append("%strace %s: fetch failed (%r)"
                         % (prefix, tid, e))
            continue
        if status != 200:
            fails.append("%strace %s: HTTP %d" % (prefix, tid, status))
            continue
        if not payload.get("gapless"):
            fails.append("%strace %s: not gapless: %s"
                         % (prefix, tid,
                            "; ".join(payload.get("problems") or
                                      ["?"])))
            continue
        n_gapless += 1
        crossed = any(s.get("name") in ("router.failover",
                                        "router.handoff")
                      for s in payload.get("spans") or [])
        if sample is None or (crossed and not sample[1]):
            sample = (tid, crossed, payload["spans"])
    if sample is not None and sample_path:
        try:
            with open(sample_path, "w") as f:
                f.write(tracing.render_timeline(
                    sample[2],
                    title="trace %s (%d spans%s)"
                    % (sample[0], len(sample[2]),
                       ", crossed a failover/handoff"
                       if sample[1] else "")) + "\n")
        except OSError:
            pass
    return fails, n_gapless, (
        {"trace": sample[0], "crossed": sample[1]}
        if sample else None)


# ===================================================================
# gate accounting
# ===================================================================

#: the engine-side resource-audit keys every serving gate checks —
#: one list so a new leak class added to ``leak_check()`` only needs
#: wiring here
LEAK_KEYS = ("ingress", "records", "open_requests",
             "pending_cancels", "slots_busy")


def leak_gate(leaks, fails, label=""):
    """Append one failure string per nonzero leak counter (and the
    paged-KV audit) to ``fails``; the shared spelling of "zero leaked
    slots/blocks"."""
    prefix = ("%s " % label) if label else ""
    for key in LEAK_KEYS:
        if leaks.get(key, 0) != 0:
            fails.append("%sleak: %s=%r" % (prefix, key,
                                            leaks.get(key)))
    if leaks.get("kv_blocks_leaked", 0) != 0:
        fails.append("%sleak: kv_blocks_leaked=%r"
                     % (prefix, leaks["kv_blocks_leaked"]))
    return fails


def tally_gate(tally, clients, fails, allowed=("ok", "shed")):
    """Exhaustive client accounting: EVERY client must end in an
    ``allowed`` outcome (any other — truncated, splice_mismatch,
    bad_result, error, http_5xx... — is a lost/corrupt request) and
    the outcome count must equal the client count (a missing outcome
    is a client that never reported)."""
    unexpected = {k: v for k, v in tally.items()
                  if k not in allowed and v}
    if unexpected:
        fails.append("lost/corrupt requests: %r" % (unexpected,))
    total = sum(tally.values())
    if total != clients:
        fails.append("client accounting: %d outcomes for %d clients"
                     % (total, clients))
    return fails


# ===================================================================
# performance-ledger banking
# ===================================================================

def bank_gates(source, values, workload="-", **extra):
    """Bank a harness's gate numbers into the persistent performance
    ledger (``veles_tpu.telemetry.ledger``): every storm that reached
    a gate verdict leaves its measured numbers in history, so the
    regression sentinel bands them run-over-run instead of each run
    judging itself in isolation.  ``values`` maps metric name to
    either a bare number or ``(value, unit, better)``.  Fail-soft by
    contract — ledger I/O must never fail a chaos run.  Returns the
    number of rows banked."""
    n = 0
    try:
        from veles_tpu.telemetry import ledger
        for metric, spec in sorted(values.items()):
            unit, better, value = "", None, spec
            if isinstance(spec, (tuple, list)):
                value = spec[0] if spec else None
                unit = spec[1] if len(spec) > 1 else ""
                better = spec[2] if len(spec) > 2 else None
            if isinstance(value, bool) \
                    or not isinstance(value, (int, float)):
                continue
            if ledger.record_value(metric, float(value),
                                   workload=workload, unit=unit,
                                   better=better, source=source,
                                   **extra) is not None:
                n += 1
    except Exception:  # noqa: BLE001 — fail-soft by contract
        pass
    return n


# ===================================================================
# checkpoint-ring primitives (train_chaos / pod_chaos)
# ===================================================================

def current_target(snap_dir, prefix):
    """(realpath, mtime) of the directory's ``<prefix>_current``
    symlink target, or (None, None)."""
    cur = os.path.join(snap_dir, "%s_current" % prefix)
    try:
        real = os.path.realpath(cur)
        if os.path.islink(cur) and os.path.exists(real):
            return real, os.path.getmtime(real)
    except OSError:
        pass
    return None, None


def truncate_commit(path, keep_num=3, keep_den=5):
    """Tear one committed checkpoint in place (truncate to
    keep_num/keep_den of its size) — exactly what a kill inside a
    storage write leaves behind.  Raises OSError on failure."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size * keep_num // keep_den, 1))


def validate_ring(snap_dir, prefix):
    """Import every remaining (non-quarantined) checkpoint of the
    prefix — what counts as a commit is ``scan_commits``' call (one
    source of truth with the snapshotter/agreement); returns
    (n_valid, [invalid path strings])."""
    from veles_tpu.services.snapshotter import (SnapshotterBase,
                                                scan_commits)
    if not os.path.isdir(snap_dir):
        return 0, ["unreadable snapshot dir %s" % snap_dir]
    invalid, n_valid = [], 0
    scan = scan_commits(snap_dir, prefix)
    for name in sorted(scan):
        path = scan[name]["path"]
        try:
            SnapshotterBase.import_(path)
            n_valid += 1
        except Exception as e:   # noqa: BLE001 — the audit itself
            invalid.append("%s (%s)" % (path, e))
    return n_valid, invalid


# ===================================================================
# the self-contained digits workload (train_chaos / pod_chaos)
# ===================================================================

_DIGITS_TEMPLATE = '''\
"""Generated by a veles_tpu chaos harness — tiny digits MLP whose
epoch count comes from root.__NS__ (the harness's --epochs)."""
import numpy as np
from sklearn.datasets import load_digits

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow


def run(load, main):
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    loader = FullBatchLoader(
        None, data=x, labels=y,
        minibatch_size=root.__NS__.get("minibatch_size", 64),
        class_lengths=[0, 297, 1500])
    load(StandardWorkflow,
         layers=[
             {"type": "all2all_tanh", "output_sample_shape": 32,
              "learning_rate": 0.1, "gradient_moment": 0.9},
             {"type": "softmax", "output_sample_shape": 10,
              "learning_rate": 0.1, "gradient_moment": 0.9},
         ],
         loader=loader,
         decision_config={"max_epochs":
                          root.__NS__.get("max_epochs", __EPOCHS__)},
         name="__NAME__")
    main()
'''


def write_digits_workflow(path, ns, name, default_epochs):
    """Write the shared self-contained digits-MLP workload (sklearn's
    bundled set — no dataset mount) under the given config namespace;
    returns ``path``."""
    text = (_DIGITS_TEMPLATE
            .replace("__NS__", ns)
            .replace("__NAME__", name)
            .replace("__EPOCHS__", str(int(default_epochs))))
    with open(path, "w") as f:
        f.write(text)
    return path
