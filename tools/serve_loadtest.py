#!/usr/bin/env python
"""Chaos load-test harness for the serving survival layer.

Drives a real ``RESTfulAPI`` + ``ContinuousEngine`` (tiny untrained
transformer, CPU-friendly) with hundreds of concurrent streaming
clients under deliberately hostile conditions —

* a configurable fraction DISCONNECTS mid-stream (RST via SO_LINGER,
  the rude way real phones vanish),
* a fraction are SLOWLORIS readers (accept the stream, read a line,
  then crawl),
* the engine tick raises INJECTED FAULTS at a configurable rate
  (the fault-recovery path must evict, reset the pool, keep serving),
* an overload burst pushes queue waits past the SLO so the closed-loop
  shedder must open (503 + Retry-After) and close again,

then audits the wreckage: zero leaked slots, zero leaked paged-KV
blocks, zero stuck client threads, shed-open AND shed-close observed,
and the engine still serves fresh requests afterwards.  Exit code 0
iff every gate passes; ``--json`` writes the full report and
``--flight-dump`` leaves a flight-recorder crashdump for CI artifacts.

    python tools/serve_loadtest.py --clients 200 --disconnect 0.25 \
        --slowloris 0.1 --fault-rate 0.02 --slots 4 --paged-block 4 \
        --slo-ms 250 --json report.json --flight-dump chaos-dump

Scaled-down flavors run inside tier-1 (`tests/test_lifecycle.py`); the
CI `serve-chaos` job runs this CLI with a few hundred clients, plus a
QUANTIZED leg (``--weights int8 --cache-dtype int8``) that drives the
same storm through the int8 weight matmuls and the fused quantized-
pool paged decode kernel.  The report's ``storm_ms_per_tok``
(completed-request token throughput under the storm — not admission
p50) is what the ``--weights {f32,bf16,int8,w4a8}`` legs compare; on
silicon it carries the pre-registered >= 1.5x int8-vs-bf16 target
(docs/perf.md "Quantized serving").

**Fleet chaos mode** (`--fleet N`): spawn N replica subprocesses, put a
`services.router.FleetRouter` in front, storm the ROUTER with streaming
clients, then SIGKILL one replica and SIGTERM-drain another mid-storm.
Gates: every non-shed request completes with the byte-exact full
result (mid-stream failover splices are invisible), the router marks
the killed replica down within one health-check interval, the drained
replica exits 0, and `leak_check()` is clean on every survivor::

    python tools/serve_loadtest.py --fleet 3 --clients 150 \
        --slots 4 --paged-block 4 --pool-tokens 512 \
        --json fleet-report.json --flight-dump fleet-dump

(`--replica` is the internal subprocess entry the fleet mode spawns;
it serves one engine replica on an OS-assigned port — announced via a
`REPLICA_READY port=...` stdout line — and drains on SIGTERM.)
"""

import argparse
import http.client
import json
import os
import random
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools import chaos_common as cc   # noqa: E402 — path set above


def build_api(slots=4, paged_block=0, pool_tokens=None, slo_ms=0,
              deadline_ms=0, max_len=24, vocab=11, seed=7,
              generator=None, weights=None, cache_dtype=None,
              prefill_segment=0):
    """A serving endpoint around a tiny UNTRAINED transformer (the
    harness tests the lifecycle, not the language model).  Config
    knobs are set process-globally (root.common.serve) exactly as an
    operator would.  ``weights``: None (f32) / "bf16" / "int8" /
    "w4a8" — the serving weight scheme (``--weights``); the quantized
    legs prove the lifecycle machinery over the quantized decode path
    (payload-in-dot matmuls, QuantCache pools with
    ``cache_dtype="int8"``)."""
    from veles_tpu import prng
    from veles_tpu.config import root
    from veles_tpu.services.restful import RESTfulAPI

    root.common.serve.slo_queue_wait_ms = float(slo_ms)
    root.common.serve.default_deadline_ms = float(deadline_ms)
    if generator is None:
        import numpy as np

        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator
        from veles_tpu.models.standard_workflow import StandardWorkflow

        prng.seed_all(seed)
        toks = np.random.RandomState(seed).randint(
            0, vocab, (8, max_len)).astype(np.int32)
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=16,
                                      n_heads=2, n_layers=1,
                                      dropout=0.0),
            loader=FullBatchLoader(None, data=toks, labels=toks,
                                   minibatch_size=4,
                                   class_lengths=[0, 4, 4]),
            loss="lm", decision_config={"max_epochs": 1},
            name="chaos-serve")
        wf.initialize()
        generator = LMGenerator(
            wf.trainer, max_len=max_len,
            weights=(None if weights in (None, "", "f32")
                     else str(weights)),
            cache_dtype=cache_dtype)
    api = RESTfulAPI(lambda xx: xx, (generator.max_len,), port=0,
                     generator=generator, continuous_slots=slots,
                     paged_block=paged_block, pool_tokens=pool_tokens,
                     prefill_segment=prefill_segment)
    api.start()
    return api


class FaultInjector(object):
    """Wraps the engine's batcher tick with a probabilistic raise —
    the ``serve.engine_fault`` recovery path under test.  The rate is
    mutable so the recovery phase can switch chaos off."""

    def __init__(self, engine, rate, seed=0):
        self.rate = float(rate)
        self.count = 0
        self._rng = random.Random(seed)
        self._orig = engine.cb.tick
        # instance attribute shadows the bound method; the engine loop
        # resolves self.cb.tick per call, so this takes effect at the
        # next loop iteration
        engine.cb.tick = self._tick

    def _tick(self):
        if self.rate > 0 and self._rng.random() < self.rate:
            self.count += 1
            raise RuntimeError("injected chaos fault #%d" % self.count)
        return self._orig()


def _rst_close(sock):
    """Close with RST (SO_LINGER 0): the peer's next write fails
    immediately instead of draining into a dead buffer — how the
    harness makes 'client vanished' deterministic."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    sock.close()


def _client(api, prompt, max_new, behavior, tally, lock,
            slow_delay=0.4, deadline_ms=None, traces=None):
    """One load-test client.  behavior: 'normal' | 'disconnect' |
    'slowloris' | 'buffered'.  ``traces``: ok requests append their
    trace id (the trace-completeness gate's input)."""
    opts = {"max_new": max_new, "stream": behavior != "buffered"}
    if deadline_ms:
        opts["deadline_ms"] = deadline_ms
    body = json.dumps({"input": prompt, "generate": opts})
    outcome, tid = "error", None
    try:
        conn = http.client.HTTPConnection(api.host, api.port,
                                          timeout=120)
        conn.request("POST", api.path, body,
                     {"Content-Type": "application/json"})
        # grab the socket NOW: http.client detaches conn.sock (sets it
        # to None) when the response body is EOF-delimited, and the
        # disconnect behavior needs the raw fd to send a RST
        raw_sock = conn.sock
        resp = conn.getresponse()
        if resp.status == 503:
            resp.read()
            outcome = "shed"
        elif resp.status == 504:
            resp.read()
            outcome = "deadline"
        elif resp.status != 200:
            resp.read()
            outcome = "http_%d" % resp.status
        elif behavior == "buffered":
            tid = json.loads(resp.read()).get("trace")
            outcome = "ok"
        else:
            lines, done = 0, False
            while True:
                if behavior == "disconnect" and lines >= 1:
                    _rst_close(raw_sock)
                    outcome = "disconnected"
                    return
                if behavior == "slowloris" and lines >= 1:
                    time.sleep(slow_delay)
                raw = resp.fp.readline()
                if not raw:
                    break
                lines += 1
                msg = json.loads(raw)
                if msg.get("done"):
                    done = True
                    tid = msg.get("trace")
                    break
                if "error" in msg:
                    outcome = "stream_error"
                    return
            outcome = "ok" if done else "truncated"
        conn.close()
    except Exception:  # noqa: BLE001 — chaos clients absorb anything
        outcome = "error"
    finally:
        with lock:
            tally[outcome] = tally.get(outcome, 0) + 1
            if outcome == "ok" and traces is not None and tid:
                traces.append(tid)


def _wait_idle(engine, timeout=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        m = engine.metrics()
        if m["queued"] == 0 and m["in_flight"] == 0:
            return True
        time.sleep(0.05)
    return False


def run(clients=200, disconnect=0.25, slowloris=0.10, buffered=0.15,
        fault_rate=0.02, slots=4, paged_block=0, pool_tokens=None,
        max_new=8, prompt_len=5, slo_ms=250, deadline_ms=0,
        slow_delay=0.4, seed=7, api=None, flight_dump=None,
        weights=None, cache_dtype=None, ramp_s=0.0):
    """Run the chaos scenario; returns the report dict (see gates()).
    Pass ``api`` to reuse a prebuilt endpoint (the tier-1 tests do,
    to share one compiled model across tests).  ``weights`` picks the
    serving weight scheme (f32/bf16/int8/w4a8) for the endpoint this
    harness builds.  ``ramp_s`` spreads client arrivals over that many
    seconds instead of one instantaneous burst: the shed valve opens
    on a MEASURED queue-wait breach one engine-loop update after the
    backlog forms, so when every client submits in the same
    millisecond (small storms on fast hosts) there is nobody left to
    reject — a ramp keeps arrivals flowing past the opening."""
    own_api = api is None
    if own_api:
        # the storm itself runs WITHOUT a default deadline (deadlines
        # at ~the SLO cull the queue before the shed valve can ever
        # open); deadline_ms drives the separate bounded phase below
        api = build_api(slots=slots, paged_block=paged_block,
                        pool_tokens=pool_tokens, slo_ms=slo_ms,
                        deadline_ms=0, seed=seed, weights=weights,
                        cache_dtype=cache_dtype)
    eng = api.engine
    rng = random.Random(seed)
    prompt = [int(1 + i % 7) for i in range(prompt_len)]
    report = {"clients": clients, "tally": {}, "phases": {},
              "weights": weights or "f32"}
    try:
        # ---- warmup: compile every shape OUTSIDE the measured storm
        # (and outside any default deadline — first-dispatch compiles
        # take seconds, and a deadline-cancelled warmup would abort
        # the run before the storm starts)
        t0 = time.monotonic()
        prev_deadline = eng._default_deadline_ms
        eng._default_deadline_ms = 0.0
        eng.wait(eng.submit_async(prompt, max_new))
        eng._default_deadline_ms = prev_deadline
        eng.reset_metrics()
        report["phases"]["warmup_s"] = round(time.monotonic() - t0, 2)

        baseline_threads = set(threading.enumerate())
        chaos = FaultInjector(eng, fault_rate, seed=seed)

        # ---- chaos storm: every behavior at once
        tally, lock = {}, threading.Lock()
        behaviors = []
        for _ in range(clients):
            r = rng.random()
            if r < disconnect:
                behaviors.append("disconnect")
            elif r < disconnect + slowloris:
                behaviors.append("slowloris")
            elif r < disconnect + slowloris + buffered:
                behaviors.append("buffered")
            else:
                behaviors.append("normal")
        t0 = time.monotonic()
        traces = []
        threads = [threading.Thread(
            target=_client,
            args=(api, prompt, max_new, b, tally, lock),
            kwargs={"slow_delay": slow_delay, "traces": traces},
            daemon=True)
            for b in behaviors]
        for th in threads:
            th.start()
            if ramp_s > 0:
                time.sleep(ramp_s / max(1, clients))
        for th in threads:
            th.join(timeout=300)
        stuck_clients = sum(1 for th in threads if th.is_alive())
        storm_s = time.monotonic() - t0
        report["phases"]["storm_s"] = round(storm_s, 2)
        report["tally"] = tally
        report["stuck_client_threads"] = stuck_clients
        # storm-phase ms/tok off COMPLETED requests (ok = fully
        # decoded + delivered): the token throughput the pool actually
        # sustained under the storm, not the admission p50 — the
        # number the quantized-weights legs compare (the pre-
        # registered >= 1.5x int8-vs-bf16 target reads this on
        # silicon; shed/deadline culls don't count, they decoded
        # nothing)
        done_toks = tally.get("ok", 0) * max_new
        report["storm_completed_tokens"] = done_toks
        report["storm_ms_per_tok"] = (round(storm_s * 1e3 / done_toks,
                                            4) if done_toks else None)

        # ---- trace completeness: every ok request reconstructs a
        # gapless timeline from the replica's own span store (the
        # replica is the edge here, so it minted and terminated each
        # trace; docs/services.md "Request tracing")
        from veles_tpu.telemetry import tracing
        time.sleep(0.2)      # let the last handlers' terminal spans land
        tfails, n_gapless, sample_spans = [], 0, None
        for tid in traces:
            try:
                status, payload = cc.http_json(
                    api.host, api.port, api.path + "/trace/" + tid)
            except Exception as e:  # noqa: BLE001 — the audit itself
                tfails.append("trace %s: fetch failed (%r)"
                              % (tid, e))
                continue
            spans = payload.get("spans") or []
            verdict = tracing.validate(spans)
            if status != 200 or not verdict["ok"]:
                tfails.append("trace %s: HTTP %d: %s"
                              % (tid, status,
                                 "; ".join(verdict["problems"])))
                continue
            n_gapless += 1
            if sample_spans is None:
                sample_spans = (tid, spans)
        report["trace_ids"] = len(traces)
        report["trace_gapless"] = n_gapless
        report["trace_fails"] = tfails[:20]
        if sample_spans is not None:
            report["trace_sample"] = sample_spans[0]
            report["trace_sample_timeline"] = tracing.render_timeline(
                sample_spans[1], title="trace %s" % sample_spans[0])

        # ---- recovery: chaos off, drain, the valve must close and
        # fresh requests must succeed
        chaos.rate = 0.0
        report["injected_faults"] = chaos.count
        drained = _wait_idle(eng)
        t0 = time.monotonic()
        recovered = 0
        for _ in range(3):
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    out = eng.wait(eng.submit_async(prompt, max_new))
                    assert len(out) == prompt_len + max_new
                    recovered += 1
                    break
                except Exception:  # noqa: BLE001 — shed while closing
                    time.sleep(0.2)
        report["phases"]["recovery_s"] = round(time.monotonic() - t0, 2)
        report["drained"] = drained
        report["recovered_requests"] = recovered

        # ---- audits
        _wait_idle(eng)
        metrics = eng.metrics()
        report["metrics"] = metrics
        # per-phase latency decomposition (docs/services.md "Request
        # tracing"): where a completed request's time actually went —
        # the same queue/prefill/decode split the router rolls up
        # fleet-wide on /metrics
        report["phase_ms"] = {
            phase: {"p50": metrics.get("p50_" + key),
                    "p99": metrics.get("p99_" + key)}
            for phase, key in (("queue", "queue_wait_ms"),
                               ("prefill", "prefill_ms"),
                               ("decode", "pure_decode_ms"))}
        report["leaks"] = eng.leak_check()
        report["shed_cycle"] = bool(
            metrics["shed_total"] > 0
            and metrics["shed_state"] in ("closed", "disabled"))
        # ---- bounded phase (only with --deadline-ms): re-overload
        # with a default deadline ~= the SLO, which culls any request
        # that could not be admitted in time — completed requests'
        # p99 queue wait must then stay under the SLO (the ISSUE
        # acceptance criterion; the raw storm's p99 includes the
        # pre-shed-open backlog, which only deadlines can bound)
        if slo_ms > 0 and deadline_ms:
            # admission deadline = queue-wait budget (80% of
            # --deadline-ms, margin for estimate drift) + the MEASURED
            # decode estimate: the engine's predictive check then
            # refuses any request whose queue wait would overrun the
            # budget, so completed waits stay under the SLO.  History
            # is kept (the estimate feeds off it); the phase's own
            # percentiles come from the finish_ts slice below.
            # short requests: the phase measures QUEUE wait, so decode
            # must fit the budget comfortably or wave-1 completions
            # get culled mid-decode on a slow box and the sample dries
            bounded_new = max(2, max_new // 4)
            # warm the phase's shape BEFORE arming the deadline — a
            # fresh prefill-bucket compile mid-phase would stall past
            # every deadline and dry the completion sample
            eng.wait(eng.submit_async(prompt, bounded_new))
            est_ms = metrics["p50_ms_per_tok"] * bounded_new
            eng._default_deadline_ms = 0.8 * float(deadline_ms) + est_ms
            t_phase = time.monotonic()
            tally2, lock2 = {}, threading.Lock()
            burst = [threading.Thread(
                target=_client,
                args=(api, prompt, bounded_new, "buffered", tally2,
                      lock2),
                daemon=True) for _ in range(max(8, clients // 2))]
            for th in burst:
                th.start()
            for th in burst:
                th.join(timeout=300)
            _wait_idle(eng)
            eng._default_deadline_ms = 0.0
            waits = sorted(h["queue_wait_ms"]
                           for h in list(eng._history)
                           if h["finish_ts"] >= t_phase)
            p99 = (waits[min(len(waits) - 1,
                             int(0.99 * len(waits)))]
                   if waits else None)
            report["bounded_phase"] = {
                "tally": tally2,
                "completed": len(waits),
                "deadline_ms_effective": round(
                    0.8 * float(deadline_ms) + est_ms, 2),
                "p99_queue_wait_ms": (round(p99, 3)
                                      if p99 is not None else None)}
            report["p99_queue_wait_under_slo"] = bool(
                waits and p99 <= float(slo_ms))
            report["leaks"] = eng.leak_check()   # re-audit after it
        else:
            report["p99_queue_wait_under_slo"] = bool(
                slo_ms <= 0
                or metrics["p99_queue_wait_ms"] <= float(slo_ms))
        # server-side threads (per-connection HTTP workers, engine)
        # get a grace window to exit before counting as leaked
        deadline = time.monotonic() + 10
        leftover = []
        while time.monotonic() < deadline:
            leftover = [th.name for th in threading.enumerate()
                        if th not in baseline_threads and th.is_alive()
                        and th not in threads]
            if not leftover:
                break
            time.sleep(0.2)
        report["new_threads"] = leftover
        if flight_dump:
            from veles_tpu.telemetry import flight
            report["flight_dump"] = flight.dump(flight_dump,
                                                reason="loadtest")
    finally:
        if own_api:
            api.stop()
    return report


def gates(report, expect_shed=True, require_slo=False):
    """The pass/fail verdicts the CI job enforces.  Returns a list of
    failure strings (empty = pass).  ``require_slo`` additionally
    gates on completed requests' p99 queue wait staying under the
    SLO — only meaningful with a deadline configured (``--deadline-ms``
    about equal to the SLO), which culls the backlog that piles up
    before the shed valve opens; without one, those early-queued
    requests legitimately wait past the SLO and raw p99 shows it."""
    fails = []
    if require_slo and not report.get("p99_queue_wait_under_slo", True):
        bp = report.get("bounded_phase", {})
        fails.append(
            "admitted p99 queue wait breached the SLO (bounded phase "
            "p99=%s ms over %d completed)"
            % (bp.get("p99_queue_wait_ms"), bp.get("completed", 0)))
    leaks = report.get("leaks", {})
    cc.leak_gate(leaks, fails)
    if not leaks.get("engine_thread_alive", False):
        fails.append("engine thread died")
    if report.get("stuck_client_threads"):
        fails.append("stuck client threads: %d"
                     % report["stuck_client_threads"])
    if report.get("new_threads"):
        fails.append("leaked server-side threads: %r"
                     % report["new_threads"])
    if not report.get("drained"):
        fails.append("engine never drained to idle")
    if report.get("recovered_requests", 0) < 3:
        fails.append("engine not serving after chaos (%d/3 fresh "
                     "requests ok)" % report.get("recovered_requests", 0))
    if expect_shed and not report.get("shed_cycle"):
        fails.append("no shed+recover cycle (shed_total=%r, state=%r)"
                     % (report.get("metrics", {}).get("shed_total"),
                        report.get("metrics", {}).get("shed_state")))
    # trace completeness: every ok-accounted storm request must have
    # yielded a trace id on its done line AND reconstruct a gapless
    # timeline from the replica span store
    fails.extend(report.get("trace_fails", []))
    n_ids = report.get("trace_ids", 0)
    n_ok = report.get("tally", {}).get("ok", 0)
    if n_ids != n_ok:
        fails.append("trace ids captured (%d) != ok requests (%d)"
                     % (n_ids, n_ok))
    if n_ids and report.get("trace_gapless", 0) != n_ids:
        fails.append("only %d/%d traces reconstruct gapless"
                     % (report.get("trace_gapless", 0), n_ids))
    if not n_ids:
        fails.append("storm captured no trace ids")
    return fails


# ------------------------------------------------------- mixed-prompt mode
def _gap_stream_client(api, prompt, max_new, gaps, tally, lock):
    """One streaming client that records the wall gap between
    consecutive token lines — the client-observed inter-chunk decode
    gap the segmented-prefill gate bounds."""
    body = json.dumps({"input": prompt,
                       "generate": {"max_new": max_new,
                                    "stream": True}})
    outcome = "error"
    try:
        conn = http.client.HTTPConnection(api.host, api.port,
                                          timeout=300)
        conn.request("POST", api.path, body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            resp.read()
            outcome = "http_%d" % resp.status
            return
        last = None
        done = False
        while True:
            raw = resp.fp.readline()
            if not raw:
                break
            msg = json.loads(raw)
            if "tokens" in msg:
                now = time.monotonic()
                if last is not None:
                    with lock:
                        gaps.append((now - last) * 1e3)
                last = now
            if msg.get("done"):
                done = True
                break
            if "error" in msg:
                outcome = "stream_error"
                return
        outcome = "ok" if done else "truncated"
        conn.close()
    except Exception:  # noqa: BLE001 — chaos clients absorb anything
        outcome = "error"
    finally:
        with lock:
            tally[outcome] = tally.get(outcome, 0) + 1


def _mixed_generator(max_len, seed=7, vocab=11, d_model=64,
                     n_layers=2):
    """A BEEFIER tiny model for the stall gate: the whole point is
    that a long prompt's one-pass prefill visibly stalls decode
    ticks, so the prefill must cost real milliseconds — the default
    d=16 single-layer harness model prefills 100 tokens in ~6 ms,
    under scheduler noise."""
    import numpy as np

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models import zoo
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(seed)
    toks = np.random.RandomState(seed).randint(
        0, vocab, (8, 16)).astype(np.int32)
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=d_model,
                                  n_heads=max(2, d_model // 32),
                                  n_layers=n_layers, dropout=0.0,
                                  pos="rope"),
        loader=FullBatchLoader(None, data=toks, labels=toks,
                               minibatch_size=4,
                               class_lengths=[0, 4, 4]),
        loss="lm", decision_config={"max_epochs": 1},
        name="chaos-serve-mixed")
    wf.initialize()
    return LMGenerator(wf.trainer, max_len=max_len)


def _run_mixed_once(prefill_segment, streamers=6, stream_new=48,
                    long_clients=6, long_len=256, long_new=4,
                    short_len=5, slots=4, seed=7, generator=None):
    """One mixed long/short storm against a fresh endpoint with the
    given segmentation; returns the report half (engine decode-stall
    percentiles + client-observed inter-chunk gaps)."""
    api = build_api(slots=slots, slo_ms=0, seed=seed,
                    max_len=long_len + long_new + stream_new,
                    generator=generator,
                    prefill_segment=prefill_segment)
    eng = api.engine
    short = [int(1 + i % 7) for i in range(short_len)]
    longp = [int(1 + i % 7) for i in range(long_len)]
    try:
        # warm every shape OUTSIDE the measurement (prefill buckets,
        # decode scan)
        eng.wait(eng.submit_async(short, stream_new))
        eng.wait(eng.submit_async(longp, long_new))
        eng.reset_metrics()
        gaps, tally, lock = [], {}, threading.Lock()
        threads = [threading.Thread(
            target=_gap_stream_client,
            args=(api, short, stream_new, gaps, tally, lock),
            daemon=True) for _ in range(streamers)]
        for th in threads:
            th.start()
        # long-prompt admissions land WHILE the short streams decode —
        # the head-of-line stall under test
        time.sleep(0.05)
        handles = []
        for _ in range(long_clients):
            handles.append(eng.submit_async(longp, long_new))
            time.sleep(0.02)
        for h in handles:
            eng.wait(h)
        for th in threads:
            th.join(timeout=300)
        m = eng.metrics()

        def pct(vals, q):
            if not vals:
                return None
            vals = sorted(vals)
            return round(vals[min(len(vals) - 1,
                                  int(q / 100.0 * len(vals)))], 3)

        # phase-attribution audit: every completed request's
        # prefill/decode split must partition its admitted→finished
        # span exactly (non-overlapping by construction — a drifted
        # decode-start stamp would show up as residual here)
        hist = [h for h in list(eng._history) if "prefill_ms" in h]
        attr = {
            "n": len(hist),
            "negative": sum(1 for h in hist
                            if h["prefill_ms"] < 0
                            or h["pure_decode_ms"] < 0),
            "max_residual_ms": (round(max(
                abs(h["prefill_ms"] + h["pure_decode_ms"]
                    - h["decode_ms"]) for h in hist), 6)
                if hist else None)}
        return {"prefill_segment": prefill_segment,
                "tally": tally,
                "stuck_streamers": sum(1 for th in threads
                                       if th.is_alive()),
                "p50_decode_stall_ms": m["p50_decode_stall_ms"],
                "p99_decode_stall_ms": m["p99_decode_stall_ms"],
                "prefill_ms_per_tok": m["prefill_ms_per_tok"],
                "prefill_segments_total": m["prefill_segments_total"],
                "p50_prefill_ms": m.get("p50_prefill_ms"),
                "p99_prefill_ms": m.get("p99_prefill_ms"),
                "p50_pure_decode_ms": m.get("p50_pure_decode_ms"),
                "p99_pure_decode_ms": m.get("p99_pure_decode_ms"),
                "phase_attr": attr,
                "client_gap_p50_ms": pct(gaps, 50),
                "client_gap_p99_ms": pct(gaps, 99),
                "client_gaps": len(gaps),
                "leaks": eng.leak_check()}
    finally:
        api.stop()


def run_mixed(prefill_segment=16, long_len=256, stream_new=48,
              long_new=4, seed=7, **kw):
    """The segmented-prefill stall gate: the SAME mixed long/short
    storm twice — segmented vs unsegmented admission — so the bound
    and the strictly-better comparison are measured in one run on one
    box (docs/perf.md "Stall-free serving").  One shared generator:
    both runs decode the same weights through the same compiled
    executables, so the ONLY difference is the admission policy."""
    gen = _mixed_generator(long_len + long_new + stream_new,
                           seed=seed)
    kw.update(long_len=long_len, stream_new=stream_new,
              long_new=long_new, seed=seed, generator=gen)
    report = {"segmented": _run_mixed_once(prefill_segment, **kw),
              "unsegmented": _run_mixed_once(0, **kw),
              "prefill_segment": prefill_segment}
    return report


def _bucket(n):
    return 1 << max(0, int(n) - 1).bit_length()


def mixed_gates(report):
    """Pass/fail for the mixed-prompt leg: the segmented run's p99
    inter-dispatch decode gap must be (a) bounded by the per-tick
    prefill budget — budget-bucket tokens at the run's own measured
    prefill rate, plus the run's baseline cadence and scheduler
    slack — and (b) STRICTLY better than the unsegmented baseline
    measured in the same run.  Plus the usual hygiene."""
    fails = []
    seg = report.get("segmented") or {}
    unseg = report.get("unsegmented") or {}
    for name, half in (("segmented", seg), ("unsegmented", unseg)):
        tally = half.get("tally") or {}
        bad = {k: v for k, v in tally.items() if k != "ok"}
        if bad:
            fails.append("%s run lost requests: %r" % (name, tally))
        if half.get("stuck_streamers"):
            fails.append("%s run stuck streamers: %d"
                         % (name, half["stuck_streamers"]))
        leaks = half.get("leaks") or {}
        cc.leak_gate(leaks, fails, label=name)
        # prefill-vs-decode attribution must be non-overlapping:
        # the two phases partition each request's admitted→finished
        # span, so their sum can never drift off it and neither
        # share can go negative
        attr = half.get("phase_attr") or {}
        if not attr.get("n"):
            fails.append("%s run recorded no phase attribution"
                         % name)
        else:
            if attr.get("negative"):
                fails.append("%s run: %d requests with a negative "
                             "phase share" % (name, attr["negative"]))
            resid = attr.get("max_residual_ms")
            if resid is not None and resid > 0.05:
                fails.append("%s run: prefill+decode attribution "
                             "overlaps/undershoots its span by "
                             "%.3f ms" % (name, resid))
    if not seg.get("prefill_segments_total"):
        fails.append("the segmented run never staged a prefill "
                     "segment (knob not reaching the engine?)")
    p99_seg = seg.get("p99_decode_stall_ms")
    p99_unseg = unseg.get("p99_decode_stall_ms")
    if p99_seg is None or p99_unseg is None:
        fails.append("missing decode-stall percentiles")
        return fails
    # budget-derived bound: one tick may prefill up to the budget
    # (pow2-bucketed) at the measured rate; 4x headroom for dispatch
    # overlap + 25 ms scheduler slack on a shared CI box
    budget = _bucket(report.get("prefill_segment") or 1)
    bound = (4.0 * budget * (seg.get("prefill_ms_per_tok") or 0.0)
             + 4.0 * (seg.get("p50_decode_stall_ms") or 0.0) + 25.0)
    if p99_seg > bound:
        fails.append("segmented p99 decode stall %.3f ms exceeds the "
                     "budget-derived bound %.3f ms" % (p99_seg, bound))
    if not p99_seg < p99_unseg:
        fails.append("segmented p99 decode stall %.3f ms is not "
                     "strictly better than the unsegmented baseline "
                     "%.3f ms" % (p99_seg, p99_unseg))
    return fails


# --------------------------------------------------------------- fleet mode
def replica_main(args):
    """Subprocess entry for one fleet replica: build the tiny model,
    serve it, print READY with the bound port, drain on SIGTERM (exit
    0), die honestly on SIGKILL."""
    from veles_tpu.services.restful import (announce_ready,
                                            install_sigterm_drain)
    from veles_tpu.telemetry import flight

    api = build_api(slots=args.slots, paged_block=args.paged_block,
                    pool_tokens=args.pool_tokens, slo_ms=args.slo_ms,
                    deadline_ms=0, seed=args.seed,
                    max_len=getattr(args, "max_len", 24),
                    prefill_segment=getattr(args, "prefill_segment",
                                            0))
    if getattr(args, "tick_delay_ms", 0):
        # stretch decode so the fleet storm's mid-storm SIGKILL lands
        # while streams are provably in flight (a tiny model on a fast
        # box finishes 8 tokens in microseconds otherwise)
        delay_s = float(args.tick_delay_ms) / 1e3
        orig_tick = api.engine.cb.tick

        def slow_tick():
            time.sleep(delay_s)
            return orig_tick()

        api.engine.cb.tick = slow_tick
    # leave a black box on graceful (drained) exit so the fleet
    # timeline can be merged across processes — the SIGKILLed replica
    # leaves none, which is the point.  The hook rides the drain
    # waiter: os._exit skips atexit handlers.
    install_sigterm_drain(
        api,
        on_drained=(lambda: flight.dump(args.dump_dir,
                                        reason="replica-drain"))
        if args.dump_dir else None)
    # READY handshake: the parent reads the bound port off stdout
    # (the shared handshake every fleet spawner understands —
    # tools/chaos_common.spawn_ready and the pod agent)
    announce_ready(api, force=True)
    while True:
        time.sleep(3600)


def replica_cmd(args, i, dump_dir=None):
    """The replica subprocess command line for fleet chaos — EVERY
    replica builds from the SAME seed: identical weights are what
    make greedy decode — and therefore mid-stream failover splices —
    byte-identical across the fleet."""
    cmd = [sys.executable, os.path.abspath(__file__), "--replica",
           "--slots", str(args.slots),
           "--paged-block", str(args.paged_block),
           "--slo-ms", str(args.slo_ms),
           "--seed", str(args.seed),
           "--max-len", str(getattr(args, "max_len", 24)),
           "--prefill-segment",
           str(getattr(args, "prefill_segment", 0)),
           "--tick-delay-ms",
           str(getattr(args, "tick_delay_ms", 0))]
    if args.pool_tokens:
        cmd += ["--pool-tokens", str(args.pool_tokens)]
    if dump_dir:
        cmd += ["--dump-dir", dump_dir]
    return cmd


def _spawn_replicas(n, args, dump_dir=None):
    """Start n replica subprocesses via the shared READY handshake
    (chaos_common.spawn_ready — select-bounded, startup-flake
    retried); returns [(proc, port, url)]."""
    cmds, envs = [], []
    for i in range(n):
        cmds.append(replica_cmd(args, i, dump_dir=dump_dir))
        env = dict(os.environ)
        env["VELES_TPU_PROCESS_ID"] = str(i + 1)   # distinct blackbox ids
        envs.append(env)
    return cc.spawn_ready(cmds, timeout=300.0, envs=envs,
                          log_dir=dump_dir)


def _fleet_client(router, prompt, max_new, expected, session, tally,
                  lock, errors=None):
    """One fleet storm client (shared verification core:
    chaos_common.fleet_stream_client)."""
    cc.fleet_stream_client(router.host, router.port, router.path,
                           prompt, max_new, expected, session, tally,
                           lock, errors=errors)


_http_json = cc.http_json


def _wait_replica_idle(port, timeout=120.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            _, h = _http_json("127.0.0.1", port, "/service/health")
            if h.get("queued", 0) == 0 and h.get("in_flight", 0) == 0:
                return True
        except OSError:
            return False
        time.sleep(0.05)
    return False


def run_fleet(replicas=3, clients=150, max_new=8, prompt_len=5,
              slots=4, paged_block=0, pool_tokens=None, slo_ms=250,
              kill_frac=0.15, drain_frac=0.35, seed=7,
              health_interval_ms=100, sessions=16, tick_delay_ms=20,
              flight_dump=None, args=None):
    """The fleet chaos scenario (module docstring).  The SIGKILL fires
    once ``kill_frac`` of the clients completed and the SIGTERM drain
    at ``drain_frac`` — completion-triggered, not timed, so the chaos
    provably lands MID-storm on any box speed.  Returns the report
    dict for :func:`fleet_gates`."""
    from veles_tpu.services.router import FleetRouter
    from veles_tpu.telemetry import flight

    if args is None:
        args = argparse.Namespace(
            slots=slots, paged_block=paged_block,
            pool_tokens=pool_tokens, slo_ms=slo_ms, seed=seed,
            tick_delay_ms=tick_delay_ms)
    report = {"replicas": replicas, "clients": clients, "tally": {},
              "phases": {}}
    t0 = time.monotonic()
    fleet = _spawn_replicas(replicas, args, dump_dir=flight_dump)
    report["phases"]["spawn_s"] = round(time.monotonic() - t0, 2)
    router = FleetRouter(port=0,
                         health_interval_ms=health_interval_ms)
    router.start()
    for _, _, url in fleet:
        router.register(url)
    prompt = [int(1 + i % 7) for i in range(prompt_len)]
    try:
        # ---- warmup every replica directly (compiles happen OUTSIDE
        # the storm) and capture the expected uninterrupted result —
        # all replicas are built from the same seed'd tiny model, so
        # greedy decode is identical everywhere
        t0 = time.monotonic()
        expected = None
        for _, port, _ in fleet:
            status, out = _http_json(
                "127.0.0.1", port, "/service", method="POST",
                body=json.dumps({"input": prompt,
                                 "generate": {"max_new": max_new}}),
                timeout=300)
            assert status == 200, (status, out)
            if expected is None:
                expected = out["result"][0]
            elif list(expected) != list(out["result"][0]):
                report["replica_divergence"] = True
        report["phases"]["warmup_s"] = round(time.monotonic() - t0, 2)
        report["expected_len"] = len(expected)

        # ---- storm through the router; mid-storm: SIGKILL one
        # replica, SIGTERM-drain another
        tally, lock = {}, threading.Lock()
        stream_errors = []
        threads = [threading.Thread(
            target=_fleet_client,
            args=(router, prompt, max_new, expected,
                  "sess-%d" % (i % sessions), tally, lock,
                  stream_errors),
            daemon=True) for i in range(clients)]
        t0 = time.monotonic()
        for th in threads:
            th.start()

        def completed():
            with lock:
                return sum(tally.values())

        # completion-triggered chaos: SIGKILL once kill_frac of the
        # clients finished (streams are provably still in flight),
        # SIGTERM-drain another replica at drain_frac
        kill_proc, kill_port, _ = fleet[0]
        drain_proc, drain_port, _ = fleet[1]
        deadline = time.monotonic() + 300
        cc.wait_fraction(completed, kill_frac, clients, deadline)
        kill_ts = time.monotonic()
        kill_proc.kill()                          # SIGKILL: no goodbye
        report["sigkill_replica_port"] = kill_port
        report["sigkill_at_completed"] = completed()
        cc.wait_fraction(completed, drain_frac, clients, deadline)
        drain_proc.send_signal(signal.SIGTERM)    # graceful drain
        report["sigterm_replica_port"] = drain_port
        report["sigterm_at_completed"] = completed()
        for th in threads:
            th.join(timeout=300)
        report["stuck_client_threads"] = sum(
            1 for th in threads if th.is_alive())
        report["phases"]["storm_s"] = round(time.monotonic() - t0, 2)
        report["tally"] = tally
        report["stream_errors"] = stream_errors[:20]

        # ---- failover detection latency: the first replica_down
        # flight event after the SIGKILL (request-path detection
        # usually beats the health probe; one probe interval is the
        # ceiling the acceptance criterion names)
        down_ts = None
        for ev in flight.recorder.snapshot():
            if ev["kind"] == "serve.replica_down" \
                    and ev["ts"] >= kill_ts + _MONO_TO_WALL:
                down_ts = ev["ts"]
                break
        report["failover_detect_s"] = (
            round(down_ts - (kill_ts + _MONO_TO_WALL), 3)
            if down_ts is not None else None)

        # ---- drained replica must exit 0 (stop admission → finish
        # in-flight → exit 0), SIGKILLed one must be gone
        try:
            report["sigterm_exit"] = drain_proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            report["sigterm_exit"] = None
        report["sigkill_exit"] = kill_proc.wait(timeout=30)

        # ---- survivors: idle, then leak-audited
        survivors = fleet[2:]
        leaks = {}
        for _, port, _ in survivors:
            if not _wait_replica_idle(port):
                leaks[port] = {"error": "never idled"}
                continue
            _, leaks[port] = _http_json("127.0.0.1", port,
                                        "/service/leaks")
        report["survivor_leaks"] = leaks
        report["router_metrics"] = router.metrics()
        kinds = [e["kind"] for e in flight.recorder.snapshot()]
        report["flight_kinds"] = {
            k: kinds.count(k)
            for k in ("serve.replica_up", "serve.replica_down",
                      "serve.failover", "serve.drain")}
        if flight_dump:
            report["flight_dump"] = flight.dump(flight_dump,
                                                reason="fleet-loadtest")
    finally:
        router.stop()
        for proc, _, _ in fleet:
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
    return report


#: shared wall/monotonic offset (chaos_common)
_MONO_TO_WALL = cc.MONO_TO_WALL


def fleet_gates(report, health_interval_ms=100):
    """Pass/fail verdicts for the fleet chaos run (CI `serve-fleet`).
    Returns failure strings (empty = pass)."""
    fails = []
    tally = report.get("tally", {})
    # exhaustive accounting (chaos_common.tally_gate): EVERY client
    # must end ok or shed — anything else is a lost/corrupt request,
    # and a missing outcome is a client that never reported
    cc.tally_gate(tally, report.get("clients", sum(tally.values())),
                  fails)
    if not tally.get("ok"):
        fails.append("no request completed (tally=%r)" % (tally,))
    if report.get("stuck_client_threads"):
        fails.append("stuck client threads: %d"
                     % report["stuck_client_threads"])
    if report.get("replica_divergence"):
        fails.append("replicas disagreed on the warmup output")
    det = report.get("failover_detect_s")
    # ceiling: one health-check interval (+1 s slack for the flight
    # ring scan and scheduler noise); request-path detection usually
    # lands far earlier
    if det is None:
        fails.append("SIGKILL never produced a serve.replica_down")
    elif det > health_interval_ms / 1e3 + 1.0:
        fails.append("failover took %.3f s (> one %.0f ms health "
                     "interval + slack)" % (det, health_interval_ms))
    if report.get("sigterm_exit") != 0:
        fails.append("SIGTERM replica exit %r != 0 (graceful drain "
                     "failed)" % (report.get("sigterm_exit"),))
    for port, leaks in report.get("survivor_leaks", {}).items():
        if leaks.get("error"):
            fails.append("survivor %s: %s" % (port, leaks["error"]))
            continue
        cc.leak_gate(leaks, fails, label="survivor %s" % port)
    counters = report.get("router_metrics", {}).get("counters", {})
    if not counters.get("failovers"):
        fails.append("router recorded no failover")
    kinds = report.get("flight_kinds", {})
    for kind in ("serve.replica_up", "serve.replica_down",
                 "serve.failover", "serve.drain"):
        if not kinds.get(kind):
            fails.append("missing flight event: %s" % kind)
    return fails


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="chaos load test for the serving survival layer")
    ap.add_argument("--clients", type=int, default=200)
    ap.add_argument("--disconnect", type=float, default=0.25,
                    help="fraction of clients that RST mid-stream")
    ap.add_argument("--slowloris", type=float, default=0.10)
    ap.add_argument("--buffered", type=float, default=0.15)
    ap.add_argument("--fault-rate", type=float, default=0.02,
                    help="probability an engine tick raises")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--paged-block", type=int, default=0)
    ap.add_argument("--pool-tokens", type=int, default=None)
    ap.add_argument("--weights", default=None,
                    choices=["f32", "bf16", "int8", "w4a8"],
                    help="serving weight scheme for the endpoint "
                         "(default f32 = as-trained); the report's "
                         "storm_ms_per_tok compares schemes")
    ap.add_argument("--cache-dtype", default=None,
                    choices=["bfloat16", "int8"],
                    help="KV-cache dtype (int8 + --paged-block runs "
                         "the fused quantized-pool decode kernel)")
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=5)
    ap.add_argument("--max-len", type=int, default=24,
                    help="model max_len for the endpoint this "
                         "harness builds (raise it for long-prompt "
                         "legs)")
    ap.add_argument("--prefill-segment", type=int, default=0,
                    help="segmented prefill admission: bound each "
                         "admission prefill pass to this many tokens "
                         "(0 = whole-prompt; docs/services.md "
                         "'Disaggregated prefill')")
    ap.add_argument("--mixed", action="store_true",
                    help="mixed long/short-prompt stall gate: run the "
                         "same storm segmented (--prefill-segment) "
                         "and unsegmented, gate the p99 decode gap "
                         "against the budget bound AND the "
                         "unsegmented baseline")
    ap.add_argument("--long-prompt-len", type=int, default=256,
                    help="(--mixed) long-prompt length")
    ap.add_argument("--long-clients", type=int, default=6,
                    help="(--mixed) long-prompt admissions during "
                         "the storm")
    ap.add_argument("--streamers", type=int, default=6,
                    help="(--mixed) short streaming clients whose "
                         "inter-chunk gaps are measured")
    ap.add_argument("--slo-ms", type=float, default=250.0)
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--slow-delay", type=float, default=0.4)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--no-expect-shed", action="store_true",
                    help="don't gate on a shed+recover cycle")
    ap.add_argument("--require-slo", action="store_true",
                    help="gate on completed p99 queue wait <= --slo-ms "
                         "(pair with --deadline-ms ~= --slo-ms)")
    ap.add_argument("--json", metavar="FILE",
                    help="write the full report as JSON")
    ap.add_argument("--flight-dump", metavar="DIR",
                    help="leave a flight-recorder dump (CI artifact)")
    ap.add_argument("--trace-sample", metavar="FILE",
                    help="write one reconstructed request timeline "
                         "(CI artifact; see veles-tpu-trace)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="fleet chaos mode: N replica subprocesses "
                         "behind a FleetRouter; SIGKILL one and "
                         "SIGTERM-drain another mid-storm")
    ap.add_argument("--health-interval-ms", type=float, default=100.0,
                    help="fleet router health-probe period")
    ap.add_argument("--sessions", type=int, default=16,
                    help="distinct affinity session keys in the "
                         "fleet storm")
    ap.add_argument("--kill-frac", type=float, default=0.15,
                    help="completed-client fraction at which replica "
                         "0 is SIGKILLed")
    ap.add_argument("--drain-frac", type=float, default=0.35,
                    help="completed-client fraction at which replica "
                         "1 gets SIGTERM (graceful drain)")
    ap.add_argument("--tick-delay-ms", type=float, default=20.0,
                    help="per-tick decode delay on fleet replicas "
                         "(stretches streams so the chaos lands "
                         "mid-flight)")
    ap.add_argument("--replica", action="store_true",
                    help=argparse.SUPPRESS)   # internal subprocess entry
    ap.add_argument("--dump-dir", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.replica:
        return replica_main(args)

    if args.mixed:
        report = run_mixed(
            prefill_segment=args.prefill_segment or 16,
            streamers=args.streamers,
            long_clients=args.long_clients,
            long_len=args.long_prompt_len,
            short_len=args.prompt_len, slots=args.slots,
            seed=args.seed)
        fails = mixed_gates(report)
        report["failures"] = fails
        # bank the gate numbers: the sentinel bands them run-over-run
        seg_p99 = (report["segmented"] or {}).get(
            "p99_decode_stall_ms")
        unseg_p99 = (report["unsegmented"] or {}).get(
            "p99_decode_stall_ms")
        cc.bank_gates(
            "serve_loadtest.mixed",
            {"serve_p99_stall_seg_ms": (seg_p99, "ms", "lower"),
             "serve_p99_stall_unseg_ms": (unseg_p99, "ms", "lower"),
             "serve_stall_seg_vs_unseg_x": (
                 round(seg_p99 / unseg_p99, 3)
                 if seg_p99 and unseg_p99 else None, "x", "lower")},
            workload="mixed-storm", gate_failures=len(fails))
        out = json.dumps(report, indent=2, default=str)
        if args.json:
            with open(args.json, "w") as f:
                f.write(out + "\n")
        print(out)
        if fails:
            print("FAIL: " + "; ".join(fails), file=sys.stderr)
            return 1
        print("PASS: segmented p99 decode stall %.3f ms vs "
              "unsegmented %.3f ms (budget %d tok)"
              % (report["segmented"]["p99_decode_stall_ms"],
                 report["unsegmented"]["p99_decode_stall_ms"],
                 args.prefill_segment or 16), file=sys.stderr)
        return 0

    if args.fleet:
        report = run_fleet(
            replicas=args.fleet, clients=args.clients,
            max_new=args.max_new, prompt_len=args.prompt_len,
            slots=args.slots, paged_block=args.paged_block,
            pool_tokens=args.pool_tokens, slo_ms=args.slo_ms,
            kill_frac=args.kill_frac,
            drain_frac=args.drain_frac, seed=args.seed,
            health_interval_ms=args.health_interval_ms,
            sessions=args.sessions,
            tick_delay_ms=args.tick_delay_ms,
            flight_dump=args.flight_dump)
        fails = fleet_gates(report,
                            health_interval_ms=args.health_interval_ms)
        report["failures"] = fails
        cc.bank_gates(
            "serve_loadtest.fleet",
            {"fleet_failover_detect_s": (
                report.get("failover_detect_s"), "s", "lower"),
             "storm_ms_per_tok": (report.get("storm_ms_per_tok"),
                                  "ms", "lower")},
            workload="fleet-%d" % args.fleet,
            gate_failures=len(fails))
        out = json.dumps(report, indent=2, default=str)
        if args.json:
            with open(args.json, "w") as f:
                f.write(out + "\n")
        print(out)
        if fails:
            print("FAIL: " + "; ".join(fails), file=sys.stderr)
            return 1
        print("PASS: fleet survived SIGKILL + SIGTERM drain — "
              "%d ok, %d shed, %d failovers, detect %.3fs"
              % (report["tally"].get("ok", 0),
                 report["tally"].get("shed", 0),
                 report["router_metrics"]["counters"]["failovers"],
                 report["failover_detect_s"]), file=sys.stderr)
        return 0

    report = run(clients=args.clients, disconnect=args.disconnect,
                 slowloris=args.slowloris, buffered=args.buffered,
                 fault_rate=args.fault_rate, slots=args.slots,
                 paged_block=args.paged_block,
                 pool_tokens=args.pool_tokens, max_new=args.max_new,
                 prompt_len=args.prompt_len, slo_ms=args.slo_ms,
                 deadline_ms=args.deadline_ms,
                 slow_delay=args.slow_delay, seed=args.seed,
                 flight_dump=args.flight_dump, weights=args.weights,
                 cache_dtype=args.cache_dtype)
    fails = gates(report, expect_shed=not args.no_expect_shed,
                  require_slo=args.require_slo)
    report["failures"] = fails
    cc.bank_gates(
        "serve_loadtest.storm",
        {"storm_ms_per_tok": (report.get("storm_ms_per_tok"), "ms",
                              "lower"),
         "p99_decode_stall_ms": (
             report.get("metrics", {}).get("p99_decode_stall_ms"),
             "ms", "lower")},
        workload=args.weights or "f32", gate_failures=len(fails))
    if args.trace_sample and report.get("trace_sample_timeline"):
        with open(args.trace_sample, "w") as f:
            f.write(report["trace_sample_timeline"] + "\n")
    out = json.dumps(report, indent=2, default=str)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    print(out)
    if fails:
        print("FAIL: " + "; ".join(fails), file=sys.stderr)
        return 1
    print("PASS: zero leaks, %d sheds, %d faults survived"
          % (report["metrics"]["shed_total"],
             report.get("injected_faults", 0)), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
