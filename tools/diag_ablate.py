#!/usr/bin/env python3
"""Flagship step-time attribution by ablation (the tunnel profiler
exposes no per-op device timeline — artifacts/profile_r05 — so where
the 207 ms/step goes is measured by swapping one knob at a time).

Each variant: build the 124M flagship, warm up, then time fused
4-step sweeps with the block-per-dispatch discipline diag_async.py
established.  Prints ms/step + MFU per variant.
"""

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def measure(tag, reps=3, **kw):
    import gc

    import jax
    from tools.profile_capture import build_flagship
    from veles_tpu.ops.flops import lm_train_flops_per_token

    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm

    prng.seed_all(5)
    vocab, seq, batch = 50304, 1024, kw.pop("batch", 16)
    n = batch * 4
    toks = np.random.RandomState(0).randint(
        0, vocab, (n, seq)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=batch,
                             class_lengths=[0, 0, n])
    zoo = dict(vocab_size=vocab, d_model=768, n_heads=12, n_layers=12,
               dropout=0.0, impl="flash", pos="rope", solver="adamw",
               lr=6e-4, tie_embeddings=True, remat="dots")
    zoo.update(kw)
    wf = StandardWorkflow(
        layers=transformer_lm(**zoo), loader=loader, loss="lm",
        gd_defaults={"clip_norm": 1.0},
        decision_config={"max_epochs": 1000},
        steps_per_dispatch=4, name="abl-" + tag)
    try:
        wf.initialize()
        for _ in range(8):
            wf.loader.run()
            wf.trainer.run()
        wf.trainer.flush()
        # fetch = the only honest barrier on this tunnel (bench.py
        # _fetch_sync rationale); slope over 8-vs-16 steps cancels the
        # ~64 ms RTT constant
        jax.device_get(wf.trainer.class_stats[2]["loss"])
        times = []
        for n_sweeps in (2, 4):
            t0 = time.perf_counter()
            for _ in range(4 * n_sweeps):
                wf.loader.run()
                wf.trainer.run()
            wf.trainer.flush()
            jax.device_get(wf.trainer.class_stats[2]["loss"])
            times.append(time.perf_counter() - t0)
        ms = (times[1] - times[0]) / 8 * 1e3
        fpt = lm_train_flops_per_token(768, 12, 1024, 50304, n_heads=12)
        mfu = (batch * 1024 / (ms / 1e3)) * fpt / 197e12
        loss = float(jax.device_get(wf.trainer.class_stats[2]["loss"]))
        print("%-26s %7.1f ms/step  MFU %5.1f%%  loss %.1f"
              % (tag, ms, mfu * 100, loss), flush=True)
    except Exception as e:  # noqa: BLE001 — keep the sweep going
        print("%-26s FAILED: %s" % (tag, str(e)[:120]), flush=True)
    del wf
    gc.collect()


def main():
    import jax
    print("devices:", jax.devices(), flush=True)
    measure("flash/dots  (baseline)")
    measure("naive/dots", impl="naive")
    measure("blockwise/dots", impl="blockwise")
    measure("flash/no-remat", remat=None)
    measure("flash/dots/b32", batch=32)
    return 0


if __name__ == "__main__":
    sys.exit(main())
