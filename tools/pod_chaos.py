#!/usr/bin/env python
"""Chaos gate for the POD survival tier — the multi-host rung of the
PR 6→7→8 survival ladder (tools/serve_loadtest.py → fleet mode →
tools/train_chaos.py → this).

Runs one **golden** 2-host CPU pod to completion under the pod master
(`veles_tpu.services.podmaster`, the `tests/multihost_worker.py`
topology: 2 processes × K virtual CPU devices over one
``jax.distributed`` job), then the same seed/command again while a
chaos driver delivers the three pod-level deaths:

* **worker SIGKILL** — one host's worker killed after a fresh commit,
  mid-sweep: the survivor does not crash, it would hang in its next
  collective; the master must detect the exit and restart the POD.
* **collective hang** — one host's scheduler frozen via
  ``root.common.chaos.unit_delay_ms`` (armed mid-run through
  ``chaos.unit_delay_file``): every worker stays alive and
  heartbeating while step/commit progress goes flat pod-wide; the
  master's hang latch must fire and restart the pod.
* **torn cross-host commit** — ONE host's newest committed checkpoint
  truncated in place + SIGKILL: the cross-host agreement must reject
  that commit pod-wide (it is valid on the other host!), roll both
  hosts back to the previous commit, and resume.

Plus **zombie fencing**: after a restart bumps the incarnation, the
driver registers a fake worker under the old incarnation and must be
refused (``stale-incarnation``).

The gate is **exactness**: the chaos pod's final checkpoint must be
bit-identical to the golden pod's (``compare_snapshots`` at threshold
0), on BOTH hosts.  Exit 0 iff every gate passes; ``--json`` writes
the report, ``--artifacts`` collects agent logs + crashdumps + the
master's flight dump for CI.

    python tools/pod_chaos.py --epochs 10 --json pod-report.json

``--host-loss`` runs the ELASTIC flavor instead — the permanent
host-loss ladder (docs/distributed_training.md "Elastic pods"): one
host's agent is killed and the host marked down (a machine that is
GONE), the strike ladder must classify the loss permanent and
**degrade** the pod to the survivors with exactly ONE resize-bucketed
restart, the survivors must keep committing real epochs, the host is
then revived and must rejoin with exactly ONE re-expand restart
(agreed commit replicated to its frozen ring over the control plane).
Exactness gate: a golden run launched *at the degraded size* from the
same resharded checkpoint must reproduce the chaos pod's degraded-era
commits bit-identically (threshold 0), and no crash-loop or
deterministic-bug valve may fire — planned resizes live in their own
budget.

CI runs the synthetic-MNIST flavor:

    python tools/make_synth_mnist.py ci-datasets/mnist
    python tools/pod_chaos.py \
        --workflow samples/mnist_mlp.py --config samples/mnist_config.py \
        --prefix mnist-mlp \
        --config-list "root.common.dirs.datasets='$PWD/ci-datasets'" \
                      "root.mnist.max_epochs=8"
"""

import argparse
import json
import os
import random
import shutil
import signal
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import chaos_common as cc   # noqa: E402 — path set above

def build_argv(workflow, config, seed, extra_config=(), mesh="data=-1"):
    """The worker command — per-host snapshot dirs / per_host mode /
    blackbox dir are threaded in by the pod master, not here.  The
    host-loss flavor passes a FIXED mesh sized to the full pod: the
    degraded respawn must prove the launcher refits it onto the
    surviving devices (``pod.elastic_mesh`` →
    ``parallel.mesh.fit_axes_to_devices``), not just that a ``-1``
    wildcard adapts."""
    argv = [sys.executable, "-m", "veles_tpu", workflow]
    if config:
        argv.append(config)
    cl = list(extra_config)
    if cl:
        argv += ["--config-list"] + cl
    argv += ["--backend", "cpu", "--random-seed", str(seed),
             "--mesh", mesh,
             "--snapshot-every", "1", "--snapshot", "auto"]
    return argv


#: shared ``_current`` resolution (chaos_common)
_current_target = cc.current_target


class _DriverBase(threading.Thread):
    """Shared observation/injection primitives for the chaos plans:
    status polling with done/giveup abort, restart-count sequencing,
    fresh-commit waits and the worker SIGKILL."""

    def __init__(self, master, prefix, rng, timeout=300.0,
                 settle=(0.05, 0.35), name="PodChaos"):
        super(_DriverBase, self).__init__(name=name, daemon=True)
        self.master = master
        self.prefix = prefix
        self.rng = rng
        self.timeout = float(timeout)
        self.settle = settle
        self.events = []      # [{"event", "ts", ...}]
        self.errors = []

    def run(self):
        try:
            self._run_plan()
        except Exception as e:   # noqa: BLE001 — surfaced via gates
            self.errors.append("chaos driver crashed: %s: %s"
                               % (type(e).__name__, e))

    # ------------------------------------------------------- primitives
    def _note(self, event, **fields):
        rec = dict(fields, event=event, ts=time.time())
        self.events.append(rec)
        print("[pod-chaos] %s %s" % (event, fields), flush=True)

    def _wait(self, cond, what):
        deadline = time.time() + self.timeout
        while time.time() < deadline:
            st = self.master.status()
            if st["phase"] in ("done", "giveup"):
                self.errors.append("pod finished (%s) while waiting "
                                   "for %s" % (st["phase"], what))
                return None
            out = cond(st)
            if out is not None:
                return out
            time.sleep(0.05)
        self.errors.append("timed out waiting for %s (%.0fs)"
                           % (what, self.timeout))
        return None

    def _running_after(self, min_restarts):
        """Wait for the pod to be running with at least
        ``min_restarts`` coordinated restarts recorded, returning the
        status observed.  The plan sequences on the master's restart
        COUNT, not on incarnation numbers: a kill is delivered
        asynchronously (the still-running pre-kill state would satisfy
        an incarnation check before the master even detects the
        death), and uncounted respawns (the sandbox startup flake)
        shift incarnations anyway."""
        return self._wait(
            lambda st: st if st["phase"] == "running"
            and st["restarts"] >= min_restarts else None,
            "running after >= %d restarts" % min_restarts)

    def _wait_fresh_commit(self, host, after):
        d = self.master.host_snapshot_dir(host)

        def cond(st):
            target, mtime = _current_target(d, self.prefix)
            if target is not None and mtime is not None \
                    and mtime > after:
                return (target, mtime)
            return None
        return self._wait(cond, "fresh commit on host %d" % host)

    def _kill_worker(self, host, settle=True):
        pid = self.master.status()["hosts"][host]["worker_pid"]
        if pid is None:
            self.errors.append("no live worker on host %d to kill"
                               % host)
            return False
        if settle:
            time.sleep(self.rng.uniform(*self.settle))  # land mid-sweep
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError as e:
            self.errors.append("SIGKILL host %d pid %s failed: %s"
                               % (host, pid, e))
            return False
        self._note("sigkill", host=host, pid=pid)
        return True

class ChaosDriver(_DriverBase):
    """Delivers the pod chaos plan against a live PodMaster: SIGKILL →
    forged hang → torn single-host commit → zombie-fence probe, each
    after observing a fresh commit in the then-current incarnation."""

    def __init__(self, master, prefix, stall_file, rng, timeout=300.0,
                 settle=(0.05, 0.35)):
        super(ChaosDriver, self).__init__(master, prefix, rng,
                                          timeout=timeout,
                                          settle=settle)
        self.stall_file = stall_file
        self.torn_name = None

    def _tear(self, host, target):
        """Truncate ``target`` in place; records it as the torn commit."""
        try:
            cc.truncate_commit(target)
        except OSError as e:
            self.errors.append("torn-commit injection failed: %s" % e)
            return False
        self.torn_name = os.path.basename(target)
        self._note("torn", host=host, name=self.torn_name)
        return True

    # -------------------------------------------------------------- plan
    def _run_plan(self):
        # ---- event 1: worker SIGKILL on host 0, mid-sweep -----------
        if self._running_after(0) is None:
            return
        if self._wait_fresh_commit(0, time.time()) is None:
            return
        base = self.master.status()["restarts"]
        if not self._kill_worker(0):
            return
        # ---- event 2: forged collective hang on host 1 --------------
        if self._running_after(base + 1) is None:
            return
        if self._wait_fresh_commit(1, time.time()) is None:
            return
        base = self.master.status()["restarts"]
        with open(self.stall_file, "w") as f:
            f.write("stall\n")
        self._note("hang_armed", stall_file=self.stall_file)
        # the master must latch the pod-wide no-progress hang; disarm
        # the stall as soon as the restart begins so the respawn runs
        # clean
        if self._wait(lambda st, _b=base:
                      True if st["restarts"] > _b
                      or st["phase"] != "running" else None,
                      "hang latch") is None:
            return
        try:
            os.remove(self.stall_file)
        except OSError:
            pass
        self._note("hang_disarmed")
        # ---- event 3: torn single-host commit -----------------------
        if self._running_after(base + 1) is None:
            return
        if self._wait_fresh_commit(1, time.time()) is None:
            return
        # land mid-sweep BEFORE the tear: any settle between the tear
        # and the kill lets both hosts commit a valid successor, the
        # agreement picks that instead, and the torn commit is never
        # quarantined on host 0 (spurious gate failure) — so sleep
        # first, tear the THEN-newest commit, and kill immediately
        time.sleep(self.rng.uniform(*self.settle))
        snap_dir = self.master.host_snapshot_dir(1)
        target, _ = _current_target(snap_dir, self.prefix)
        if target is None:
            self.errors.append("no commit to tear on host 1")
            return
        base = self.master.status()["restarts"]
        if not self._tear(1, target):
            return
        if not self._kill_worker(1, settle=False):
            return
        # the worker may still have landed a valid successor in the
        # tear->kill instant; it is dead now, so re-tearing the newest
        # commit is race-free and keeps the torn one newest
        late, _ = _current_target(snap_dir, self.prefix)
        if late is not None and os.path.basename(late) != \
                self.torn_name and not self._tear(1, late):
            return
        # ---- event 4: zombie-fence probe ----------------------------
        if self._running_after(base + 1) is None:
            return
        try:
            s = socket.create_connection(
                ("127.0.0.1", self.master.port), timeout=10)
            s.sendall((json.dumps(
                {"type": "register", "host": 0, "incarnation": 0,
                 "pid": 0}) + "\n").encode())
            reply = json.loads(s.makefile("r").readline() or "{}")
            s.close()
        except OSError as e:
            self.errors.append("zombie probe failed: %s" % e)
            return
        self._note("zombie_probe", reply=reply)
        if reply.get("reason") != "stale-incarnation":
            self.errors.append("zombie registration was NOT refused "
                               "as stale-incarnation: %s" % reply)


class HostLossDriver(_DriverBase):
    """Delivers the permanent-host-loss ladder against a live
    PodMaster: kill the victim host's agent AND worker and mark the
    host down (a machine that is GONE — the down marker keeps the
    local emulation from respawning the agent), observe the strike
    ladder classify the loss permanent and DEGRADE the pod to the
    survivors, wait for real degraded-era training progress, then
    revive the host and observe exactly one RE-EXPAND restart back to
    full size.

    The degrade and re-expand rounds' agreed commits are copied aside
    (+ manifest sidecars) the moment each round lands — the keep-last
    ring prunes them as training continues: the former seeds the
    golden-degraded leg, the latter is the bit-exactness reference."""

    def __init__(self, master, prefix, rng, seed_dir, victim=1,
                 progress_epochs=2, timeout=300.0,
                 settle=(0.05, 0.35)):
        super(HostLossDriver, self).__init__(master, prefix, rng,
                                             timeout=timeout,
                                             settle=settle,
                                             name="PodHostLoss")
        self.seed_dir = seed_dir
        self.victim = int(victim)
        self.progress_epochs = int(progress_epochs)
        self.degrade_commit = None     # saved copy of D (seed)
        self.degrade_epoch = None
        self.reexpand_ring = None      # saved survivor ring at A
        self.reexpand_epoch = None

    # ------------------------------------------------------- primitives
    def _host_down(self, host):
        """Model a machine that is GONE: down marker first (no agent
        respawn), then SIGKILL the agent, then its worker."""
        with open(self.master.host_down_file(host), "w") as f:
            f.write("pod_chaos --host-loss\n")
        proc = self.master._agent_procs.get(host)
        if proc is None:
            self.errors.append("no agent process for host %d" % host)
            return False
        worker_pid = self.master.status()["hosts"][host]["worker_pid"]
        try:
            proc.kill()
        except OSError:
            pass
        if worker_pid:
            try:
                os.kill(worker_pid, signal.SIGKILL)
            except OSError:
                pass
        self._note("host_down", host=host, agent_pid=proc.pid,
                   worker_pid=worker_pid)
        return True

    def _revive(self, host):
        try:
            os.remove(self.master.host_down_file(host))
        except OSError as e:
            self.errors.append("revive of host %d failed: %s"
                               % (host, e))
            return False
        self._note("revive", host=host)
        return True

    def _resize_record(self, kind):
        for rec in self.master.history:
            if rec.get("resize") == kind:
                return rec
        return None

    def _newest_epoch(self, host):
        """Epoch of the host's ``_current`` commit, off the manifest
        sidecar (cheap enough to poll)."""
        from veles_tpu.services.snapshotter import MANIFEST_SUFFIX
        target, _ = _current_target(
            self.master.host_snapshot_dir(host), self.prefix)
        if target is None:
            return -1
        try:
            with open(target + MANIFEST_SUFFIX) as f:
                epoch = json.load(f).get("epoch")
            return -1 if epoch is None else int(epoch)
        except (OSError, ValueError):
            return -1

    def _save_commit(self, name, tag):
        """Copy one agreed commit + manifest out of the survivor's ring
        before keep-last pruning collects it; (saved_path, epoch)."""
        from veles_tpu.services.snapshotter import MANIFEST_SUFFIX
        if not name:
            self.errors.append("%s round has no agreed commit" % tag)
            return None, None
        src_dir = self.master.host_snapshot_dir(0)
        dst_dir = os.path.join(self.seed_dir, tag)
        os.makedirs(dst_dir, exist_ok=True)
        for fname in (name, name + MANIFEST_SUFFIX):
            try:
                shutil.copy2(os.path.join(src_dir, fname),
                             os.path.join(dst_dir, fname))
            except OSError as e:
                self.errors.append("saving the %s commit %s failed: %s"
                                   % (tag, fname, e))
                return None, None
        try:
            with open(os.path.join(
                    dst_dir, name + MANIFEST_SUFFIX)) as f:
                epoch = json.load(f).get("epoch")
        except (OSError, ValueError) as e:
            self.errors.append("unreadable manifest for the %s commit "
                               "%s: %s" % (tag, name, e))
            return None, None
        return os.path.join(dst_dir, name), epoch

    def _save_ring(self, tag):
        """Copy the survivor's WHOLE ring aside — the re-expand
        reference: the agreed commit itself may be a mid-sweep
        preemption snapshot (the coordinated SIGTERM lands mid-epoch
        and PR 8's preempt-exact collect overwrites the same epoch
        name), so the harness compares the newest commit that is an
        ordinary end-of-epoch commit on BOTH legs instead."""
        src_dir = self.master.host_snapshot_dir(0)
        dst_dir = os.path.join(self.seed_dir, tag)
        os.makedirs(dst_dir, exist_ok=True)
        try:
            for fname in os.listdir(src_dir):
                path = os.path.join(src_dir, fname)
                if os.path.isfile(path) and not os.path.islink(path):
                    shutil.copy2(path, os.path.join(dst_dir, fname))
        except OSError as e:
            self.errors.append("saving the %s ring failed: %s"
                               % (tag, e))
            return None
        return dst_dir

    # -------------------------------------------------------------- plan
    def _run_plan(self):
        victim = self.victim
        # ---- both hosts hold real pre-loss state --------------------
        if self._running_after(0) is None:
            return
        if self._wait_fresh_commit(0, 0) is None or \
                self._wait_fresh_commit(victim, 0) is None:
            return
        # ---- event 1: the victim host dies PERMANENTLY --------------
        time.sleep(self.rng.uniform(*self.settle))   # land mid-sweep
        if not self._host_down(victim):
            return
        # ---- the strike ladder must degrade the pod -----------------
        if self._wait(lambda st: st if st["degraded"] else None,
                      "permanent-loss verdict (degraded pod)") is None:
            return
        rec = self._resize_record("degrade")
        if rec is None:
            self.errors.append("pod degraded without a resize=degrade "
                               "restart record")
            return
        self.degrade_commit, self.degrade_epoch = \
            self._save_commit(rec.get("agreed"), "degrade")
        if self.degrade_commit is None:
            return
        self._note("degraded", agreed=rec["agreed"],
                   epoch=self.degrade_epoch,
                   lost=rec.get("lost"))
        # ---- survivors keep TRAINING (not just surviving) -----------
        target_epoch = self.degrade_epoch + self.progress_epochs
        if self._wait(lambda st: True if self._newest_epoch(0)
                      >= target_epoch else None,
                      "degraded-era progress to epoch %d"
                      % target_epoch) is None:
            return
        self._note("degraded_progress", epoch=self._newest_epoch(0))
        # ---- event 2: capacity returns ------------------------------
        if not self._revive(victim):
            return
        if self._wait(lambda st: st if not st["degraded"]
                      and st["resize_restarts"] >= 2 else None,
                      "re-expand back to full size") is None:
            return
        rec = self._resize_record("reexpand")
        if rec is None:
            self.errors.append("pod re-expanded without a "
                               "resize=reexpand restart record")
            return
        self.reexpand_ring = self._save_ring("reexpand")
        if self.reexpand_ring is None:
            return
        from veles_tpu.services.snapshotter import MANIFEST_SUFFIX
        try:
            with open(os.path.join(
                    self.reexpand_ring,
                    (rec.get("agreed") or "") + MANIFEST_SUFFIX)) as f:
                self.reexpand_epoch = json.load(f).get("epoch")
        except (OSError, ValueError) as e:
            self.errors.append("unreadable manifest for the re-expand "
                               "commit %s: %s" % (rec.get("agreed"), e))
            return
        self._note("reexpanded", agreed=rec["agreed"],
                   epoch=self.reexpand_epoch,
                   replicated=rec.get("replicated"))


#: shared ring audit (chaos_common — scan_commits is the one source
#: of truth for what counts as a commit)
_validate_ring = cc.validate_ring


def _run_pod(argv, workdir, prefix, args, host_extras=None,
             hang_seconds=None, hosts=None, loss_window_s=None,
             stale_after_ms=None):
    from veles_tpu.services.podmaster import PodMaster
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    master = PodMaster(
        argv, n_hosts=hosts or args.hosts, workdir=workdir,
        prefix=prefix, host_extras=host_extras,
        devices_per_host=args.devices_per_host, env=env,
        hang_seconds=hang_seconds or args.hang_seconds,
        stale_after_ms=stale_after_ms or 15000,
        kill_grace_ms=args.kill_grace_ms,
        backoff_base_ms=50, backoff_max_ms=1000, seed=args.seed,
        loss_window_s=loss_window_s)
    master.start()
    return master


def _setup_workload(args, tmp_prefix):
    """Workdir + workflow/config/prefix/extra-config for one flavor —
    the built-in digits MLP is written into the workdir when no
    --workflow is given."""
    workdir = args.workdir or tempfile.mkdtemp(prefix=tmp_prefix)
    os.makedirs(workdir, exist_ok=True)
    workflow, config, prefix = args.workflow, args.config, args.prefix
    extra = list(args.config_list)
    if workflow is None:
        workflow = cc.write_digits_workflow(
            os.path.join(workdir, "pod_workflow.py"),
            ns="chaos_pod", name="chaos-pod", default_epochs=10)
        extra += ["root.chaos_pod.max_epochs=%d" % args.epochs]
        prefix = "chaos-pod"
    return workdir, workflow, config, prefix, extra


def run_chaos(args):
    workdir, workflow, config, prefix, extra = _setup_workload(
        args, "pod_chaos_")
    report = {"workdir": workdir, "prefix": prefix, "seed": args.seed,
              "hosts": args.hosts}

    # ---- golden: one un-chaosed pod run -----------------------------
    # under the pod master too: the sandbox XLA startup can abort
    # spuriously (classified env-flake, respawned unbounded), and a
    # transient environment crash must cost a pod restart + exact
    # resume — the property under test — never the golden reference
    t0 = time.time()
    golden_argv = build_argv(workflow, config, args.seed, extra)
    print("[pod-chaos] golden pod: %s" % " ".join(golden_argv),
          flush=True)
    golden = _run_pod(golden_argv, os.path.join(workdir, "golden"),
                      prefix, args, hang_seconds=args.timeout)
    rc = golden.wait(args.timeout)
    if rc is None:
        golden.stop()
        golden.wait(30)
    report["golden_rc"] = rc
    report["golden_wall_s"] = round(time.time() - t0, 2)
    report["golden_restarts"] = [h["cause"] for h in golden.history]
    golden_finals = {
        h: _current_target(golden.host_snapshot_dir(h), prefix)[0]
        for h in range(args.hosts)}
    report["golden_finals"] = golden_finals
    if rc != 0 or any(v is None for v in golden_finals.values()):
        report["error"] = "golden pod failed — see %s/golden/" % workdir
        return report

    # ---- chaos: same seed + the chaos driver ------------------------
    stall_file = os.path.join(workdir, "stall")
    host_extras = {1: [
        # armed only while the stall file exists — the driver switches
        # the freeze on mid-run and off again before the respawn
        "root.common.chaos.unit_delay_ms=600000",
        "root.common.chaos.unit_delay_file=%r" % stall_file,
    ]}
    t0 = time.time()
    chaos_argv = build_argv(workflow, config, args.seed, extra)
    print("[pod-chaos] chaos pod: %s" % " ".join(chaos_argv),
          flush=True)
    master = _run_pod(chaos_argv, os.path.join(workdir, "chaos"),
                      prefix, args, host_extras=host_extras)
    driver = ChaosDriver(master, prefix, stall_file,
                         random.Random(args.seed),
                         timeout=args.timeout / 2)
    driver.start()
    rc = master.wait(args.timeout)
    if rc is None:
        master.stop()
        master.wait(30)
        report["error"] = "chaos pod exceeded --timeout %ds" \
            % args.timeout
    driver.join(timeout=10)
    report["chaos_rc"] = rc
    report["chaos_wall_s"] = round(time.time() - t0, 2)
    report["chaos_events"] = driver.events
    report["driver_errors"] = driver.errors
    report["torn_name"] = driver.torn_name
    status = master.status()
    report["fence_refusals"] = status["fence_refusals"]
    report["incarnations"] = status["incarnation"]
    report["restarts"] = [
        {"cause": h["cause"], "agreed": h["agreed"],
         "rejected": h["rejected"], "counted": h["counted"],
         "env_flake": h["env_flake"], "progressed": h["progressed"],
         "exits": {k: v.get("kind") for k, v in h["exits"].items()}}
        for h in master.history]

    # ---- audits ------------------------------------------------------
    report["quarantined"] = {}
    report["ring_valid"] = {}
    report["ring_invalid"] = {}
    chaos_finals = {}
    for h in range(args.hosts):
        d = master.host_snapshot_dir(h)
        chaos_finals[h] = _current_target(d, prefix)[0]
        try:
            report["quarantined"][h] = sorted(
                n for n in os.listdir(d) if n.endswith(".corrupt"))
        except OSError:
            report["quarantined"][h] = []
        n_valid, invalid = _validate_ring(d, prefix)
        report["ring_valid"][h] = n_valid
        report["ring_invalid"][h] = invalid
    report["chaos_finals"] = chaos_finals

    if chaos_finals.get(0) and golden_finals.get(0):
        from veles_tpu.scripts.compare_snapshots import diff_report
        try:
            report["exactness"] = diff_report(
                golden_finals[0], chaos_finals[0], threshold=0.0)
        except Exception as e:   # noqa: BLE001 — report, gate fails
            report["exactness"] = {"identical": False, "error": str(e)}
        # cross-host: every host of the chaos pod agreed on (and
        # holds) the same final state
        if chaos_finals.get(1):
            try:
                report["cross_host"] = diff_report(
                    chaos_finals[0], chaos_finals[1], threshold=0.0)
            except Exception as e:   # noqa: BLE001
                report["cross_host"] = {"identical": False,
                                        "error": str(e)}
    return report


def gates(report):
    """Audit the report; returns the list of failed-gate strings."""
    fails = []
    if report.get("error"):
        fails.append(report["error"])
    for leg in ("golden_rc", "chaos_rc"):
        if report.get(leg) != 0:
            fails.append("%s=%s" % (leg, report.get(leg)))
    if report.get("driver_errors"):
        fails.append("chaos driver errors: %s"
                     % report["driver_errors"])
    restarts = report.get("restarts", [])
    counted = [r for r in restarts if r.get("counted")]
    flakes = [r for r in restarts if r.get("env_flake")]
    # the snapshotter's non-finite valve turning the sandbox's
    # transient memory corruption into a loud death + exact replay is
    # working as designed — tolerated (reported), not planned
    absorbed = [r for r in counted
                if r["cause"] == "worker-exit:crash:"
                                 "SnapshotNonFiniteError"]
    planned = [r for r in counted if r not in absorbed]
    if len(planned) != 3:
        fails.append("expected exactly 3 planned pod restarts (kill + "
                     "hang + torn), got %d: %s"
                     % (len(planned), [r["cause"] for r in planned]))
    causes = [r["cause"] for r in planned]
    if not any(c == "worker-exit:killed:SIGKILL" for c in causes):
        fails.append("no coordinated restart from the worker SIGKILL "
                     "(causes: %s)" % causes)
    if not any(c == "collective-hang" for c in causes):
        fails.append("the forged hang never latched a coordinated "
                     "restart (causes: %s)" % causes)
    torn = report.get("torn_name")
    if not torn:
        fails.append("torn-commit injection never happened")
    else:
        rolled = [r for r in restarts
                  if any(torn in n for n in (r.get("rejected") or {}))]
        if not rolled:
            fails.append("no restart rejected the torn commit %s in "
                         "its agreement" % torn)
        elif any(r.get("agreed") and torn in r["agreed"]
                 for r in rolled):
            fails.append("agreement picked the torn commit %s" % torn)
        q = report.get("quarantined", {})
        hosts_q = [h for h, names in q.items()
                   if any(torn in n for n in names)]
        if len(hosts_q) < len(q):
            fails.append("torn commit %s was not quarantined on every "
                         "host (rolled back pod-wide): %s" % (torn, q))
    if not any(f.get("reason") == "stale-incarnation"
               for f in report.get("fence_refusals", [])):
        fails.append("stale-incarnation registration was never refused "
                     "(zombie fencing not exercised)")
    for h, invalid in (report.get("ring_invalid") or {}).items():
        if invalid:
            fails.append("invalid checkpoints left on host %s: %s"
                         % (h, invalid))
    exact = report.get("exactness")
    if not exact:
        fails.append("no exactness verdict (missing final checkpoint)")
    elif not exact.get("identical"):
        detail = exact.get("error") or exact.get("diffs", [])[:5]
        fails.append("final state NOT bit-identical to golden: %s"
                     % (detail,))
    # a host with no final checkpoint must fail loudly — otherwise the
    # cross-host verdict is silently skipped and "bit-identical on
    # every host" never gets checked for that host
    missing = [h for h, v in sorted(
        (report.get("chaos_finals") or {}).items()) if not v]
    if missing:
        fails.append("no final checkpoint on host(s) %s" % missing)
    cross = report.get("cross_host")
    if cross is not None and not cross.get("identical"):
        fails.append("chaos pod hosts disagree on the final state: %s"
                     % (cross.get("error")
                        or cross.get("diffs", [])[:5],))
    if flakes:
        # informational, never fatal: the sandbox startup flake is
        # expected to cost uncounted respawns occasionally
        print("[pod-chaos] note: %d env-flake respawn(s) absorbed"
              % len(flakes), flush=True)
    if absorbed:
        print("[pod-chaos] note: %d non-finite-valve restart(s) "
              "absorbed (transient sandbox corruption refused at "
              "commit, replayed exactly)" % len(absorbed), flush=True)
    return fails


def _commit_with_epoch(directory, prefix, epoch):
    """Path of the (valid) commit whose manifest records ``epoch`` —
    names carry a metric suffix, so the manifest is the match key."""
    from veles_tpu.services.snapshotter import scan_commits
    for name, entry in sorted(scan_commits(directory, prefix).items()):
        if entry.get("epoch") == epoch and \
                entry.get("valid") is not False:
            return entry["path"]
    return None


def run_host_loss(args):
    """The ``--host-loss`` flavor: permanent host loss → degraded
    continuation on the survivors → capacity re-expansion, gated by a
    golden run at the degraded size from the same resharded
    checkpoint."""
    workdir, workflow, config, prefix, extra = _setup_workload(
        args, "pod_hostloss_")
    epochs_key = args.epochs_key or "root.chaos_pod.max_epochs"
    victim = args.hosts - 1
    # a FIXED full-size mesh: the degraded respawn must refit it onto
    # the survivors (pod.elastic_mesh), not just adapt a -1 wildcard
    mesh = "data=%d" % (args.hosts * (args.devices_per_host or 1))
    report = {"workdir": workdir, "prefix": prefix, "seed": args.seed,
              "hosts": args.hosts, "victim": victim,
              "flavor": "host-loss"}

    # ---- chaos leg: loss -> degrade -> progress -> revive -> expand -
    t0 = time.time()
    argv = build_argv(workflow, config, args.seed, extra, mesh=mesh)
    print("[pod-chaos] host-loss pod: %s" % " ".join(argv), flush=True)
    master = _run_pod(argv, os.path.join(workdir, "chaos"), prefix,
                      args, loss_window_s=args.loss_window,
                      stale_after_ms=8000)
    driver = HostLossDriver(master, prefix, random.Random(args.seed),
                            seed_dir=os.path.join(workdir, "seed"),
                            victim=victim,
                            progress_epochs=args.progress_epochs,
                            timeout=args.timeout / 2)
    driver.start()
    rc = master.wait(args.timeout)
    if rc is None:
        master.stop()
        master.wait(30)
        report["error"] = "host-loss pod exceeded --timeout %ds" \
            % args.timeout
    driver.join(timeout=10)
    status = master.status()
    report.update(
        chaos_rc=rc, chaos_wall_s=round(time.time() - t0, 2),
        chaos_events=driver.events, driver_errors=driver.errors,
        degrade_epoch=driver.degrade_epoch,
        reexpand_epoch=driver.reexpand_epoch,
        resize_restarts=status["resize_restarts"],
        final_degraded=status["degraded"],
        final_lost_hosts=status["lost_hosts"],
        incarnations=status["incarnation"])
    report["restarts"] = [
        {"cause": h["cause"], "agreed": h["agreed"],
         "resize": h.get("resize"), "verdict": h.get("verdict"),
         "counted": h["counted"], "env_flake": h["env_flake"],
         "progressed": h["progressed"],
         "replicated": h.get("replicated"),
         "exits": {k: v.get("kind") for k, v in h["exits"].items()}}
        for h in master.history]

    # ---- audits: full-size completion, every ring valid -------------
    report["ring_valid"], report["ring_invalid"] = {}, {}
    finals = {}
    for h in range(args.hosts):
        d = master.host_snapshot_dir(h)
        finals[h] = _current_target(d, prefix)[0]
        n_valid, invalid = _validate_ring(d, prefix)
        report["ring_valid"][h] = n_valid
        report["ring_invalid"][h] = invalid
    report["chaos_finals"] = finals
    if finals.get(0) and finals.get(victim):
        from veles_tpu.scripts.compare_snapshots import diff_report
        try:
            report["cross_host"] = diff_report(
                finals[0], finals[victim], threshold=0.0)
        except Exception as e:   # noqa: BLE001 — report, gate fails
            report["cross_host"] = {"identical": False,
                                    "error": str(e)}

    # ---- golden-degraded leg: same checkpoint, degraded size --------
    if driver.degrade_commit and driver.reexpand_ring and \
            driver.reexpand_epoch is not None:
        from veles_tpu.services.snapshotter import MANIFEST_SUFFIX
        g_work = os.path.join(workdir, "golden_degraded")
        seed_snap = os.path.join(g_work, "snapshots", "host0")
        os.makedirs(seed_snap, exist_ok=True)
        name = os.path.basename(driver.degrade_commit)
        for fname in (name, name + MANIFEST_SUFFIX):
            shutil.copy2(
                os.path.join(os.path.dirname(driver.degrade_commit),
                             fname),
                os.path.join(seed_snap, fname))
        os.symlink(name,
                   os.path.join(seed_snap, "%s_current" % prefix))
        # the reference epoch is A-1, not A: the agreed commit A may
        # be a mid-sweep preemption snapshot (the re-expand SIGTERM
        # lands mid-epoch and PR 8's preempt-exact collect overwrites
        # the same epoch name), which a clean run cannot reproduce by
        # epoch count — A-1 is an ordinary end-of-epoch commit on both
        # legs; running the golden to A keeps it mid-run there too
        ref_epoch = driver.reexpand_epoch - 1
        report["compare_epoch"] = ref_epoch
        g_extra = extra + ["%s=%d" % (epochs_key,
                                      driver.reexpand_epoch)]
        g_argv = build_argv(workflow, config, args.seed, g_extra,
                            mesh=mesh)
        print("[pod-chaos] golden-degraded pod (1 host, from %s): %s"
              % (name, " ".join(g_argv)), flush=True)
        t0 = time.time()
        golden = _run_pod(g_argv, g_work, prefix, args, hosts=1)
        grc = golden.wait(args.timeout)
        if grc is None:
            golden.stop()
            golden.wait(30)
        report["golden_degraded_rc"] = grc
        report["golden_degraded_wall_s"] = round(time.time() - t0, 2)
        report["golden_degraded_restarts"] = [
            h["cause"] for h in golden.history]
        ref_chaos = _commit_with_epoch(driver.reexpand_ring, prefix,
                                       ref_epoch)
        ref_golden = _commit_with_epoch(golden.host_snapshot_dir(0),
                                        prefix, ref_epoch)
        report["compare_commits"] = {"chaos": ref_chaos,
                                     "golden": ref_golden}
        if ref_chaos and ref_golden:
            from veles_tpu.scripts.compare_snapshots import diff_report
            try:
                report["exactness"] = diff_report(
                    ref_chaos, ref_golden, threshold=0.0)
            except Exception as e:   # noqa: BLE001 — gate fails
                report["exactness"] = {"identical": False,
                                       "error": str(e)}
        else:
            report["exactness"] = {
                "identical": False,
                "error": "no epoch-%d commit to compare (chaos=%s, "
                         "golden=%s)" % (ref_epoch, ref_chaos,
                                         ref_golden)}
    return report


def host_loss_gates(report):
    """Audit the host-loss report; returns failed-gate strings."""
    fails = []
    if report.get("error"):
        fails.append(report["error"])
    for leg in ("chaos_rc", "golden_degraded_rc"):
        if report.get(leg) != 0:
            fails.append("%s=%s" % (leg, report.get(leg)))
    if report.get("driver_errors"):
        fails.append("chaos driver errors: %s"
                     % report["driver_errors"])
    restarts = report.get("restarts", [])
    degrades = [r for r in restarts if r.get("resize") == "degrade"]
    reexpands = [r for r in restarts if r.get("resize") == "reexpand"]
    if len(degrades) != 1:
        fails.append("expected exactly ONE degraded restart, got %d "
                     "(%s)" % (len(degrades),
                               [r["cause"] for r in degrades]))
    if len(reexpands) != 1:
        fails.append("expected exactly ONE re-expand restart, got %d"
                     % len(reexpands))
    if report.get("resize_restarts") != 2:
        fails.append("resize valve bucket counted %s restarts, "
                     "expected 2 (degrade + re-expand)"
                     % report.get("resize_restarts"))
    for r in degrades + reexpands:
        if r.get("counted"):
            fails.append("planned resize %r consumed the crash-loop "
                         "budget" % r["cause"])
    victim = report.get("victim")
    if reexpands and reexpands[0].get("replicated") != [victim]:
        fails.append("the agreed commit was not replicated to the "
                     "returning host %s (replicated=%s)"
                     % (victim, reexpands[0].get("replicated")))
    # no valve fired, and at most the kill-detection round itself may
    # count against the crash-loop window (a pod-verified fallback
    # respawn before the strikes land); the resizes live in their own
    # bucket, checked above
    bad = [r for r in restarts
           if r.get("verdict") not in (None, "respawn")]
    if bad:
        fails.append("a valve fired: %s"
                     % [(r["cause"], r["verdict"]) for r in bad])
    absorbed = [r for r in restarts
                if r["cause"] == "worker-exit:crash:"
                                 "SnapshotNonFiniteError"]
    counted = [r for r in restarts
               if r.get("counted") and r not in absorbed]
    if len(counted) > 1 or any(r["cause"] != "stale-heartbeat"
                               for r in counted):
        fails.append("unexpected counted restarts consumed the "
                     "crash-loop budget: %s"
                     % [r["cause"] for r in counted])
    de, ae = report.get("degrade_epoch"), report.get("reexpand_epoch")
    if de is None or ae is None or ae < de + 2:
        fails.append("survivors did not keep training while degraded: "
                     "degrade epoch %s -> re-expand epoch %s"
                     % (de, ae))
    if report.get("final_degraded") or report.get("final_lost_hosts"):
        fails.append("pod never returned to full size: degraded=%s "
                     "lost=%s" % (report.get("final_degraded"),
                                  report.get("final_lost_hosts")))
    exact = report.get("exactness")
    if not exact:
        fails.append("no reshard-exactness verdict (golden-degraded "
                     "leg never ran)")
    elif not exact.get("identical"):
        fails.append("degraded-era state NOT bit-identical to the "
                     "golden run at the degraded size: %s"
                     % (exact.get("error")
                        or exact.get("diffs", [])[:5],))
    missing = [h for h, v in sorted(
        (report.get("chaos_finals") or {}).items()) if not v]
    if missing:
        fails.append("no final checkpoint on host(s) %s" % missing)
    cross = report.get("cross_host")
    if cross is None or not cross.get("identical"):
        fails.append("pod hosts disagree on the final full-size "
                     "state: %s"
                     % ((cross or {}).get("error")
                        or (cross or {}).get("diffs", [])[:5],))
    for h, invalid in (report.get("ring_invalid") or {}).items():
        if invalid:
            fails.append("invalid checkpoints left on host %s: %s"
                         % (h, invalid))
    return fails


def main(argv=None):
    p = argparse.ArgumentParser(
        description="chaos gate for the pod survival tier "
        "(docs/distributed_training.md \"Pod orchestration\")")
    p.add_argument("--workflow", default=None,
                   help="workflow .py (default: self-contained digits "
                   "MLP over the cross-process data mesh)")
    p.add_argument("--config", default=None, help="config .py")
    p.add_argument("--config-list", nargs="*", default=[],
                   help="extra inline config statements for BOTH legs")
    p.add_argument("--prefix", default=None,
                   help="snapshot prefix (required with --workflow)")
    p.add_argument("--epochs", type=int, default=10,
                   help="epochs for the default digits workload")
    p.add_argument("--hosts", type=int, default=2)
    p.add_argument("--devices-per-host", type=int, default=2)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--hang-seconds", type=float, default=45.0,
                   help="the master's collective-hang latch window "
                   "for the chaos leg")
    p.add_argument("--kill-grace-ms", type=float, default=4000)
    p.add_argument("--host-loss", action="store_true",
                   help="run the ELASTIC flavor instead: permanent "
                   "host loss -> degraded continuation on the "
                   "survivors -> capacity re-expansion (see module "
                   "docstring)")
    p.add_argument("--loss-window", type=float, default=8.0,
                   help="host-loss: per-round agreement window for an "
                   "agent-dead host (pod.loss_window_s) — two windows "
                   "strike the loss permanent")
    p.add_argument("--progress-epochs", type=int, default=2,
                   help="host-loss: degraded-era epochs the survivors "
                   "must commit before the lost host is revived")
    p.add_argument("--epochs-key", default=None,
                   help="host-loss: config key bounding max_epochs — "
                   "stops the golden-degraded leg one epoch past the "
                   "reference commit (default: the built-in digits "
                   "workload's root.chaos_pod.max_epochs; REQUIRED "
                   "with --workflow)")
    p.add_argument("--timeout", type=float, default=1200.0)
    p.add_argument("--workdir", default=None,
                   help="working directory (default: fresh tempdir; "
                   "kept on failure, removed on success unless given)")
    p.add_argument("--json", default=None, metavar="FILE")
    p.add_argument("--artifacts", default=None, metavar="DIR",
                   help="collect agent logs + crashdumps + the flight "
                   "dump here (CI upload)")
    args = p.parse_args(argv)
    if args.workflow is not None and args.prefix is None:
        p.error("--workflow needs --prefix")
    if args.host_loss and args.workflow is not None \
            and args.epochs_key is None:
        p.error("--host-loss with --workflow needs --epochs-key")

    if args.host_loss:
        report = run_host_loss(args)
        fails = host_loss_gates(report)
    else:
        report = run_chaos(args)
        fails = gates(report)
    report["gates_failed"] = fails

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print("[pod-chaos] report -> %s" % args.json)
    if args.artifacts:
        os.makedirs(args.artifacts, exist_ok=True)
        workdir = report.get("workdir")
        for leg in ("golden", "chaos", "golden_degraded"):
            for sub in ("dumps",) + tuple(
                    "agent%d" % h for h in range(args.hosts)):
                src = os.path.join(workdir, leg, sub)
                if os.path.isdir(src):
                    shutil.copytree(
                        src, os.path.join(args.artifacts, leg, sub),
                        dirs_exist_ok=True)
        from veles_tpu.telemetry import flight
        flight.dump(directory=args.artifacts, reason="pod-chaos")
        print("[pod-chaos] artifacts -> %s" % args.artifacts)

    keys = (("chaos_rc", "golden_degraded_rc", "chaos_wall_s",
             "golden_degraded_wall_s", "degrade_epoch",
             "reexpand_epoch", "resize_restarts", "incarnations")
            if args.host_loss else
            ("golden_rc", "chaos_rc", "golden_wall_s",
             "chaos_wall_s", "incarnations", "torn_name"))
    print(json.dumps({k: report.get(k) for k in keys}, default=str))
    if fails:
        print("[pod-chaos] GATES FAILED:", flush=True)
        for f in fails:
            print("  - %s" % f)
        print("[pod-chaos] workdir kept: %s" % report.get("workdir"))
        return 1
    exact = report.get("exactness", {})
    if args.host_loss:
        print("[pod-chaos] ALL GATES PASSED: permanent host loss cost "
              "ONE degraded restart, survivors trained epochs %s->%s "
              "degraded, the resharded restore is bit-identical to a "
              "golden run at the degraded size (%d leaves), and "
              "revival cost ONE re-expand restart back to full size"
              % (report.get("degrade_epoch"),
                 report.get("reexpand_epoch"),
                 exact.get("n_leaves", 0)))
    else:
        print("[pod-chaos] ALL GATES PASSED: kill + hang + torn-host "
              "commit each cost ONE coordinated pod restart, zombie "
              "fenced, final state bit-identical to golden on every "
              "host (%d leaves)" % exact.get("n_leaves", 0))
    if args.workdir is None:
        shutil.rmtree(report["workdir"], ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
