#!/usr/bin/env python3
"""Diagnose the impossible lm_large timing from the 2026-08-01 window.

``BENCH`` measured lm-124M at 1.8 ms/step (MFU 3748%) — physically
impossible (roofline floor ~62 ms/step at 197 TF/s), while gemm/alexnet
in the same run were plausible.  The lm harness times N async fused
dispatches and blocks ONCE on the final loss; gemm blocks after EVERY
dispatch.  Hypothesis: on the axon tunnel backend,
``jax.block_until_ready`` on a chained-dispatch output returns early
(ack-on-enqueue), so only per-dispatch-blocked timing can be trusted.

Experiment A — same jitted matmul chain, two timing disciplines:
  final-block:  enqueue K dispatches, block once at the end
  each-block:   block after every dispatch
If final-block reports much less wall time than each-block for the
same work, block-on-final is broken on this backend and every
multi-dispatch timed region in bench.py must block per dispatch.

Experiment B — the ground truth lm_large number: the real 124M
flagship, timing each fused 4-step sweep with an explicit block, plus
a loss device_get so the value itself proves the step ran.
"""

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def experiment_a():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    n, iters, k = 4096, 10, 5
    a = jnp.asarray(np.random.RandomState(0).rand(n, n).astype(np.float32))
    a = a / jnp.linalg.norm(a)  # keep the chain finite

    def body(y, _):
        return jnp.dot(y, a), None

    f = jax.jit(lambda y: lax.scan(body, y, None, length=iters)[0],
                donate_argnums=(0,))
    y = jax.block_until_ready(f(jnp.copy(a)))

    t0 = time.perf_counter()
    for _ in range(k):
        y = f(y)
    jax.block_until_ready(y)
    dt_final = time.perf_counter() - t0

    y = jax.block_until_ready(f(y))
    t0 = time.perf_counter()
    for _ in range(k):
        y = jax.block_until_ready(f(y))
    dt_each = time.perf_counter() - t0

    flops = 2.0 * n ** 3 * iters * k
    print("A: final-block %.1f ms (%.1f GF/s) | each-block %.1f ms "
          "(%.1f GF/s) | ratio %.2fx"
          % (dt_final * 1e3, flops / dt_final / 1e9,
             dt_each * 1e3, flops / dt_each / 1e9,
             dt_each / dt_final), flush=True)
    return dt_each / dt_final


def experiment_b():
    import jax
    from tools.profile_capture import build_flagship
    from veles_tpu.ops.flops import lm_train_flops_per_token

    wf = build_flagship(remat="dots", batch=16)
    # compile + warmup: 2 fused sweeps, fully blocked
    for _ in range(8):
        wf.loader.run()
        wf.trainer.run()
    wf.trainer.flush()
    jax.block_until_ready(wf.trainer.class_stats[2])

    times = []
    for rep in range(4):
        t0 = time.perf_counter()
        for _ in range(4):     # one fused sweep = 4 steps
            wf.loader.run()
            wf.trainer.run()
        wf.trainer.flush()
        jax.block_until_ready(wf.trainer.class_stats[2])
        times.append(time.perf_counter() - t0)
    loss = float(jax.device_get(wf.trainer.class_stats[2]["loss"]))
    cnt = float(jax.device_get(wf.trainer.class_stats[2]["count"]))
    ms_step = sorted(times)[1] / 4 * 1e3
    tok_s = 16 * 1024 / (ms_step / 1e3)
    fpt = lm_train_flops_per_token(768, 12, 1024, 50304, n_heads=12)
    mfu = tok_s * fpt / 197e12
    print("B: lm-124M per-sweep-blocked: %.1f ms/step, %.0f tok/s, "
          "MFU %.1f%% (sweep times %s) loss/count %.3f/%.0f"
          % (ms_step, tok_s, mfu * 100,
             ["%.0fms" % (t * 1e3) for t in times], loss, cnt),
          flush=True)


def main():
    import jax
    print("devices:", jax.devices(), flush=True)
    ratio = experiment_a()
    if ratio > 3.0:
        print("VERDICT: block-on-final is BROKEN on this backend "
              "(ratio %.1fx) — bench must block per dispatch" % ratio,
              flush=True)
    else:
        print("VERDICT: chained-dispatch blocking is sound "
              "(ratio %.2fx)" % ratio, flush=True)
    experiment_b()
    return 0


if __name__ == "__main__":
    sys.exit(main())
