#!/usr/bin/env python3
"""lm_large MFU attribution sweep — run ON CHIP inside an uptime window.

VERDICT r3 #4: the 124M flagship has a >=40% single-chip MFU target and
has never produced a hardware number.  If the tunnel's host->device
dispatch latency is the blocker, fusing more steps per dispatch
(lax.scan inside the jitted sweep) amortizes it; if HBM or the MXU is
the blocker, spd changes nothing and batch might.  This sweep separates
those hypotheses in one run: for each (batch, steps_per_dispatch) it
reports tokens/sec + MFU side by side.

Usage (defaults are the sensible grid):
    python tools/lm_mfu_sweep.py
    python tools/lm_mfu_sweep.py --batch 8,16 --spd 1,4,16 --steps 8
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", default="16,8",
                    help="comma list of batch sizes (first that fits wins "
                    "per spd)")
    ap.add_argument("--spd", default="1,4,8,16",
                    help="comma list of steps_per_dispatch values")
    ap.add_argument("--remat", default="dots",
                    choices=("dots", "true", "false"),
                    help="remat mode (dots = dots_saveable selective "
                    "remat — no recompute FLOPs burned, the "
                    "MFU-preserving default)")
    ap.add_argument("--steps", type=int, default=8,
                    help="timed host-loop iterations per config")
    ap.add_argument("--seq", type=int, default=1024)
    args = ap.parse_args()

    import bench

    remat = {"dots": "dots", "true": True, "false": False}[
        args.remat.lower()]
    cfg = dict(d_model=768, n_heads=12, n_layers=12, dropout=0.0,
               impl="flash", pos="rope", solver="adamw", lr=6e-4,
               remat=remat, tie_embeddings=True)
    rows = []
    for spd in [int(s) for s in args.spd.split(",")]:
        for batch in [int(b) for b in args.batch.split(",")]:
            tag = "lm-124M[b%d,spd%d,remat=%s]" % (batch, spd, remat)
            t0 = time.monotonic()
            try:
                r = bench._run_lm(tag, cfg, batch=batch, seq=args.seq,
                                  steps=args.steps,
                                  steps_per_dispatch=spd, vocab=50304)
            except Exception as e:  # noqa: BLE001 — OOM at this batch
                if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                    print("%-22s OOM" % tag, flush=True)
                    continue
                raise
            rows.append(dict(r, batch=batch, spd=spd, remat=str(remat),
                             wall_s=round(time.monotonic() - t0, 1)))
            print("%-22s %8.0f tok/s  %5.1f ms/step  MFU %5.1f%%"
                  % (tag, r["tokens_per_sec"], r["ms_per_step"],
                     r["mfu"] * 100), flush=True)
            break   # first batch that fits at this spd
    print(json.dumps({"sweep": rows}), flush=True)


if __name__ == "__main__":
    main()
