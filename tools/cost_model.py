#!/usr/bin/env python3
"""Offline roofline cost model for every ``bench.py`` phase.

Chip uptime is the scarcest resource this repo has (round 3: one
16-minute window in ~12 h; round 4: zero).  This module converts the
numbers already captured on silicon into an *analytical* per-phase model
— MXU FLOPs, HBM bytes, kernel-launch floors, dispatch overhead — so
that the next uptime window CONFIRMS predictions instead of exploring:
the lm_large remat ladder and the flashtune block grid are pre-ranked by
predicted payoff, and ``bench.py`` emits predicted-vs-measured for every
phase it runs.  This is the reference's autotune-DB idea (measurement
turned into a reusable model, ref ``veles/backends.py:672-731``) applied
at the roofline level.

Method
------
Every phase workload is decomposed into
  t_step = max(t_compute, t_hbm) + n_kernels * T_KERNEL      (device)
         + H_STEP                  (host python loop work, if any)
         + T_DISPATCH / steps_per_dispatch                    (tunnel)
with
  t_compute = padded_matmul_flops / (PEAK * eff)
  t_hbm     = bytes / (HBM_BW * EFF_BW)
Matmul dims are padded to the (8, 128) tile / 128x128 MXU grid before
counting FLOPs, which is what prices the reference workloads' unfriendly
shapes (3001^2 gemm -> 3072, AlexNet conv1 k=363 -> 384).

Calibration vs postdiction
--------------------------
The device constants below are calibrated ONCE, each against a single
named on-chip anchor from the 2026-08-01 window — the first with
fetch-synced honest timing (bench.py `_fetch_sync`; the round-2/3
lm/mlp/alexnet numbers were enqueue-biased and are not comparable).
Each constant's own comment names its anchor.  The honest validation
is the held-out rows no constant was fit to:

  lm-25M ms/step       pred 26.0  meas 26.4   (-1.5%)
  lm-124M T=2048       pred 220   meas 215.5  (+2.2%)
  beam ms/pos          pred 0.115 meas 0.111  (+3.3%)
  serve bf16 d=1536    pred 1.48  meas 1.553  (-4.7%)
(flash T=8192 moved to an ANCHOR: its B*H=8 grid-underfill regime has
its own calibrated efficiency, FLASH_LONG_EFF.)
(the serve int8 rows are ANCHORS — the width-dependent effective
B/param curve was fit to those measurements, so they cannot count as
holdouts.)

Run ``python tools/cost_model.py`` for the postdiction table; the
assertions in ``tests/test_cost_model.py`` pin the tolerances
(anchors 5%, postdicts 20%).

v5e single-chip roofline: 197 TF/s bf16 (PEAK_BF16_TFLOPS table in
bench.py), 819 GB/s HBM.
"""

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
# the MFU / attention-FLOP conventions and the lm_large ladder are the
# SAME objects bench.py uses — predicted-vs-measured stays comparable
from veles_tpu.ops.flops import (  # noqa: E402
    LM_LARGE_LADDER as _BENCH_LADDER, causal_attn_flops as
    _causal_attn_flops, dtype_nbytes as _dtype_nbytes,
    lm_train_flops_per_token as _lm_train_flops_per_token)

# byte-per-element pricing rides the same table the sharding/memory
# auditor (analysis/sharding_audit) uses — the two accountings cannot
# silently diverge
_BF16 = _dtype_nbytes("bfloat16")
_F32 = _dtype_nbytes("float32")

# ---------------------------------------------------------------------------
# Device model (v5e unless overridden)
# ---------------------------------------------------------------------------

PEAK_BF16 = 197e12          # FLOP/s, v5e MXU
HBM_BW = 819e9              # B/s spec
#: SERIAL-dependency MXU efficiency — what a train step's chained
#: matmuls actually achieve (2026-08-01 gemmtune: serial 44.0%,
#: independent pairs 58.5%; the gap is dependency stalls, so a step,
#: which IS a serial chain, inherits the serial number).  Round 3's
#: 0.606 was a different, faster chip-day — anchors must follow the
#: window they were measured in.
EFF_MXU = 0.440
#: chained 3001^2 (pad 3072) matmuls are LATENCY-bound, not
#: throughput-bound — bf16 runs 15.5 TF/s there vs 86.7 at 8192^2
#: (each ~0.6 ms multiply leaves the serial chain mostly stalled).
#: Shape-specific anchors; the f32-highest slowdown at that shape is
#: their measured ratio, NOT a pass count.
EFF_MXU_3001_BF16 = 0.0844  # calibrated: gemm 3001^2 bf16 anchor
                            # (padded-3072 flops; unpadded rate 15.5 TF/s)
F32_OVER_BF16_3001 = 1.452  # calibrated: f32-highest / bf16 at 3001^2
EFF_BW = 0.8                # a-priori achieved-bandwidth fraction
#: conv-vs-gemm efficiency: 2026-08-01 honest alexnet (9,584 samples/s,
#: slope-timed) shows XLA's implicit-gemm convs run near the serial
#: gemm rate — the old 0.6 guess was fit to enqueue-biased numbers
CONV_DERATE = 0.975
#: flash-kernel MXU efficiency, fit on the lm-124M step anchor and
#: VALIDATED on three holdouts it was not fit to (2026-08-01 window):
#: lm-25M 27.6 vs 28.0 ms (-1.4%), lm-124M@T2048 241.0 vs 215.5
#: (+11.8%), lm-124M spd1..16 flat (measured flat).  The a-priori
#: 0.45 guess overpredicted MFU 55.8% vs the measured 35.0%; the
#: kernel's measured causal-effective rate is 3.1 TF/s at T=1024 and
#: 33 TF/s at T=8192 (flashtune), i.e. eff 0.016-0.17.  0.13 is the
#: flagship-regime fit AFTER the d<=64 (1024,1024) block default
#: landed (0.10 fit the pre-tune 189.8 ms step).
FLASH_EFF = 0.13
FLASH_BWD_EFF = 0.13
#: the T=8192 d=128 long-context shape runs the (512,512)-block kernel
#: at a LOWER effective rate than the flagship regime (16.8 TF/s
#: measured = eff 0.085 — B*H=8 underfills the grid vs the flagship's
#: 192); calibrated on the flash T=8192 anchor
FLASH_LONG_EFF = 0.085
#: XLA-naive attention's long-context fusion cliff (see predict_flash.
#: naive_ms): calibrated on the measured T=8192 XLA anchor, 237.49 ms
XLA_NAIVE_LONG_FACTOR = 36.0
T_KERNEL = 4.3e-6           # calibrated: kohonen step anchor (2026-08-01 final run: 0.050 ms)
#: per-kernel floor INSIDE a lax.scan body (decode loops): XLA fuses
#: scan-body kernels far tighter than dispatch-level ones — fit on the
#: serve bf16 anchor (0.558 ms/tok = weight+KV stream at EFF_BW plus
#: ~154 in-scan kernels; 3.5 us/kernel would alone exceed the total)
T_KERNEL_SCAN = 1.0e-6
H_STEP = 67e-6              # calibrated: mlp fused-step anchor
#: honest per-dispatch cost through the tunnel (2026-08-01 slope-timed
#: mlp: per-step 4.255 ms minus fused 0.356 ms; the old 1.26 ms came
#: from enqueue-biased timing the window's forensics invalidated)
T_DISPATCH = 4.09e-3

#: on-chip anchors, 2026-08-01 window (fetch-synced slope timing —
#: .watcher/bench_fixed_0921.log; prior rounds' lm/mlp/alexnet numbers
#: were enqueue-biased and are not comparable)
ANCHORS = {
    "gemm_f32_gflops": 10667.7,
    "gemm_bf16_tf": 86.7,
    "gemm_bf16_3001_gflops": 15493.9,
    "gemm_bf16_pairs_tf": 115.2,
    "mlp_step_ms": 4.463,
    "mlp_step_fused_ms": 0.378,
    "alexnet_samples_per_sec": 9608.3,
    "lm_large_ms_per_step": 180.0,   # with the d64 (1024,1024) flash blocks
    "lm_ms_per_step": 26.4,          # d_head=64: same block win applies
    "lm_large_t2048_ms_per_step": 215.5,  # measured pre-d64-blocks
    "beam_ms_per_pos_t4096": 0.111,
    "kohonen_ms_per_step": 0.050,
    "flash_t8192_ms": 8.18,
    "flash_t8192_xla_ms": 237.49,
    # run-to-run serve spread this window: bf16 0.526-0.637,
    # int8 0.541-0.562 — anchored at the mid-window pair
    "serve_ms_per_tok_int8": 0.541,
    "serve_ms_per_tok_bf16": 0.558,
    # d=1536 scaling check (.watcher/serve_d1536.log): int8 wins x1.80
    # once weights dominate — see _int8_eff_bytes for the fitted
    # width-dependent effective-B/param curve
    "serve_d1536_ms_per_tok_bf16": 1.553,
    "serve_d1536_ms_per_tok_int8": 0.862,
}


def device_constants():
    """The calibrated device model as one dict — the contract
    ``veles_tpu.telemetry.mfu`` consumes to price a live workflow's
    staged step with the SAME constants this module's phase predictions
    use (its baked-in fallback mirrors these values for installs
    without tools/)."""
    return {"name": "tpu-v5e", "peak_flops": PEAK_BF16,
            "eff_mxu": EFF_MXU, "hbm_bw": HBM_BW, "eff_bw": EFF_BW,
            "t_kernel": T_KERNEL, "h_step": H_STEP,
            "t_dispatch": T_DISPATCH}


def anatomy_floors(steps_per_dispatch=1, kernels=8):
    """Per-component predicted floors (ms) of one staged step — the
    pricing side of the step-anatomy attribution
    (``veles_tpu.telemetry.anatomy``): each measured component of a
    regressed step is judged against ITS floor here, so ledger drift
    is attributed to a component instead of "step got slower".
    ``compile``/``collective`` floor at 0 (steady-state single host
    pays neither); ``compute`` here is only the kernel-launch floor —
    workload compute rides on top and is priced per-phase by the
    ``predict_*`` family."""
    spd = max(int(steps_per_dispatch), 1)
    return {"compile_ms": 0.0,
            "host_ms": H_STEP * 1e3,
            "dispatch_ms": T_DISPATCH / spd * 1e3,
            "collective_ms": 0.0,
            "compute_ms": kernels * T_KERNEL * 1e3}


def _pad(x, m=128):
    return int(math.ceil(x / m)) * m


def t_matmul(m, k, n, eff=None, passes=1):
    """Seconds for one (m,k)@(k,n) on the MXU, dims padded to 128."""
    eff = EFF_MXU if eff is None else eff
    flops = 2.0 * _pad(m) * _pad(k) * _pad(n) * passes
    return flops / (PEAK_BF16 * eff)


def t_hbm(nbytes):
    return nbytes / (HBM_BW * EFF_BW)


def conv_mk(h, w, cin, cout, kh, kw, stride=1, pad=0):
    """im2col mapping of a conv: returns (out_h, out_w, m_per_sample,
    k, n) for the equivalent matmul."""
    ho = (h + 2 * pad - kh) // stride + 1
    wo = (w + 2 * pad - kw) // stride + 1
    return ho, wo, ho * wo, cin * kh * kw, cout


# ---------------------------------------------------------------------------
# Phase models.  Each returns a dict whose keys mirror bench.py's JSON.
# ---------------------------------------------------------------------------

def predict_gemm():
    """Calibration anchors re-emitted (self-consistency, not evidence).
    The precision-level overhead at the reference's 3001^2 shape is the
    ratio of two shape-specific anchors — the old flat-efficiency model
    predicted ~+657% against a measured +45% because it priced f32 as
    extra MXU passes at a throughput the latency-bound 3001^2 chain
    never reaches."""
    n = 3001
    t16 = t_matmul(n, n, n, eff=EFF_MXU_3001_BF16)
    t32 = t16 * F32_OVER_BF16_3001
    t8192 = t_matmul(8192, 8192, 8192)
    return {
        "gflops": 2.0 * n ** 3 / t32 / 1e9,
        "bf16_gflops": 2.0 * 8192 ** 3 / t8192 / 1e9,
        "bf16_mfu": (2.0 * 8192 ** 3 / t8192) / PEAK_BF16,
        "precision_overhead_pct": (F32_OVER_BF16_3001 - 1.0) * 100.0,
    }


def predict_mlp():
    """784-100-10, batch 100.  Device time is kernel-floor dominated
    (~22 fused kernels: 2 dense layers x (fwd 2 + bwd 3 + update 2) +
    loss/stats ~8); compute and optimizer bytes are sub-microsecond."""
    b, i, h, o = 100, 784, 100, 10
    compute = 3 * (t_matmul(b, i, h) + t_matmul(b, h, o))
    params = i * h + h * o + h + o
    opt_bytes = params * _F32 * 5     # w rd/wr, m rd/wr, grad rd (f32)
    dev = max(compute, t_hbm(opt_bytes)) + 22 * T_KERNEL
    step = dev + H_STEP
    return {"step_ms": (step + T_DISPATCH) * 1e3,
            "step_fused_ms": (step + T_DISPATCH / 20) * 1e3}


#: AlexNet conv/fc walk for 227x227x3 (zoo.alexnet, single tower):
#: (h, w, cin, cout, k, stride, pad) per conv; pools shrink the grid.
_ALEXNET_CONVS = [
    (227, 227, 3, 96, 11, 4, 0),     # conv1 -> 55x55
    (27, 27, 96, 256, 5, 1, 2),      # conv2 (after pool1 3x3/2)
    (13, 13, 256, 384, 3, 1, 1),     # conv3 (after pool2)
    (13, 13, 384, 384, 3, 1, 1),     # conv4
    (13, 13, 384, 256, 3, 1, 1),     # conv5
]
_ALEXNET_FCS = [(9216, 4096), (4096, 4096), (4096, 1000)]


def predict_alexnet(batch=256):
    """Per-layer roofline walk.  fwd+bwd = 3x matmul FLOPs at
    CONV_DERATE x gemm efficiency; plus AdamW-free SGD-momentum
    optimizer traffic (62M params x 20 B) and the LRN/pool/activation
    elementwise streams."""
    t = 0.0
    act_elts = 0
    for h, w, cin, cout, k, s, p in _ALEXNET_CONVS:
        ho, wo, m, kk, n = conv_mk(h, w, cin, cout, k, k, s, p)
        t += 3 * t_matmul(batch * m, kk, n, eff=EFF_MXU * CONV_DERATE)
        act_elts += ho * wo * cout
    for fi, fo in _ALEXNET_FCS:
        t += 3 * t_matmul(batch, fi, fo)
    params = sum(cin * cout * k * k for _, _, cin, cout, k, _, _
                 in _ALEXNET_CONVS) + sum(a * b for a, b in _ALEXNET_FCS)
    t += t_hbm(params * _F32 * 5)                  # sgd-momentum f32
    # LRN (2 sites, window-5 cross-channel) + pools + relu grads: ~6
    # passes over the big early activations, bf16
    t += t_hbm(batch * act_elts * _BF16 * 6)
    t += 80 * T_KERNEL + H_STEP + T_DISPATCH / 10  # ~80 kernels/step
    return {"samples_per_sec": batch / t}


def _lm_predict(d_model, n_layers, seq, vocab, batch, n_heads,
                n_kv_heads=None, d_ff=None, steps_per_dispatch=4,
                recompute_frac=0.0, solver_bytes=28, tied=True):
    """Transformer-LM training step roofline.  ``recompute_frac`` is the
    extra forward recomputed in the backward (full remat = 1.0, dots
    remat = 0.0 for matmul-FLOP purposes); recompute time counts toward
    the step but NOT toward MFU (bench.py's MFU uses analytic 3x-fwd
    FLOPs only).  ``solver_bytes``: AdamW f32 = w rd/wr + m rd/wr +
    v rd/wr + grad rd = 28 B/param/step."""
    d_ff = d_ff or 4 * d_model
    kv = (n_kv_heads or n_heads) / n_heads
    toks = batch * seq
    # per-layer matmul time (fwd), padded shapes, m = batch*seq
    proj = (t_matmul(toks, d_model, d_model) * 2            # q, o
            + t_matmul(toks, d_model, int(d_model * kv)) * 2  # k, v
            + t_matmul(toks, d_model, d_ff) + t_matmul(toks, d_ff, d_model))
    attn_flops = _causal_attn_flops(batch, n_heads, seq,
                                    d_model // n_heads)
    attn = attn_flops / (PEAK_BF16 * FLASH_EFF)
    fwd = n_layers * (proj + attn) + t_matmul(toks, d_model, vocab)
    bwd = 2 * fwd + recompute_frac * fwd
    params = n_layers * ((2 + 2 * kv) * d_model ** 2 + 2 * d_ff * d_model) \
        + vocab * d_model * (1 if tied else 2)
    opt = t_hbm(params * solver_bytes)
    kernels = n_layers * 25 + 15                   # fused region count
    step = fwd + bwd + opt + kernels * T_KERNEL + H_STEP \
        + T_DISPATCH / steps_per_dispatch
    tps = toks / step
    # MFU numerator is bench.py's own convention, imported not copied
    fpt = _lm_train_flops_per_token(d_model, n_layers, seq, vocab,
                                    d_ff=d_ff, n_heads=n_heads,
                                    n_kv_heads=n_kv_heads or n_heads)
    return {"tokens_per_sec": tps, "ms_per_step": step * 1e3,
            "mfu": tps * fpt / PEAK_BF16, "n_params": params,
            # components for composed models (pipeline prediction):
            # pure fwd+bwd compute vs the once-per-step constants
            "compute_ms": (fwd + bwd) * 1e3, "opt_ms": opt * 1e3,
            "overhead_ms": (kernels * T_KERNEL + H_STEP
                            + T_DISPATCH / steps_per_dispatch) * 1e3}


def predict_lm():
    return _lm_predict(512, 8, 1024, 8192, batch=8, n_heads=8,
                       n_kv_heads=2, steps_per_dispatch=5, tied=False)


def predict_lm_large_ladder():
    """Predicted MFU per ladder rung — the rungs ARE bench.py's
    (veles_tpu/ops/flops.py:LM_LARGE_LADDER, single source of truth).
    The ranking is the pre-decided uptime-window order: confirm the top
    rung, only descend on OOM."""
    out = []
    for remat, batch, _steps, rec in _BENCH_LADDER:
        p = _lm_predict(768, 12, 1024, 50304, batch=batch, n_heads=12,
                        recompute_frac=rec, steps_per_dispatch=4)
        p.update(remat=str(remat), batch=batch)
        out.append(p)
    return sorted(out, key=lambda r: -r["mfu"])


def predict_flash():
    """Flash vs XLA-naive head-to-head, (4,8,1024,128) bf16 and the
    T=8192 long-context shape.  XLA naive materializes the T^2 score /
    prob tensors: ~4 full passes of b*h*T^2 bf16 traffic on top of the
    same matmul FLOPs."""
    def flash_ms(b, h, t, d, window=None, eff=FLASH_EFF, x=1.0):
        fl = _causal_attn_flops(b, h, t, d) * x
        if window and window < t:
            fl *= (window * t - window ** 2 / 2) / (t ** 2 / 2)
        return fl / (PEAK_BF16 * eff) * 1e3

    def naive_ms(b, h, t, d):
        fl = _causal_attn_flops(b, h, t, d)
        mm = fl / (PEAK_BF16 * EFF_MXU)
        hbm = t_hbm(b * h * t * t * _BF16 * 4)
        if t >= 4096:
            # fusion cliff: XLA's materialized-T^2 path measured
            # 237.49 ms at T=8192 vs the 8.1 ms a linear bytes model
            # gives — multiple T^2 temporaries with transposes/reduces
            # defeat streaming.  One calibrated factor on that anchor.
            hbm *= XLA_NAIVE_LONG_FACTOR
        return (mm + hbm) * 1e3

    # fwd+bwd: dq/dk/dv + in-kernel recompute ~= 2.5x fwd FLOPs on top
    return {
        "ms_bf16": flash_ms(4, 8, 1024, 128),
        "ms_bf16_xla": naive_ms(4, 8, 1024, 128),
        "ms_bwd": flash_ms(4, 8, 1024, 128, eff=FLASH_BWD_EFF, x=3.5),
        "ms_bwd_xla": naive_ms(4, 8, 1024, 128) * 3.5,
        "ms_long_t8192": flash_ms(1, 8, 8192, 128,
                                  eff=FLASH_LONG_EFF),
        "ms_long_t8192_xla": naive_ms(1, 8, 8192, 128),
        "ms_long_t8192_w1024": flash_ms(1, 8, 8192, 128, window=1024,
                                        eff=FLASH_LONG_EFF),
    }


def predict_flashtune_order():
    """Ranked (block_q, block_k) candidates for phase_flashtune, best
    predicted first.  Model: larger blocks amortize the softmax/rescale
    bookkeeping between inner matmuls (fewer k-steps) and keep the MXU
    on longer accumulate runs; all 9 grid points fit VMEM at d=128
    (q/k/v slabs <= 512*128*2 B = 128 KB each, f32 scores <= 1 MB,
    double-buffered well under the ~16 MB budget), so the ordering is
    bookkeeping-overhead-per-FLOP, ascending.  Causal block skipping
    makes bq=bk preferable at equal area (cleaner diagonal masks)."""
    cands = []
    for bq in (512, 256, 128):
        for bk in (512, 256, 128):
            # per-(bq,bk)-tile bookkeeping ~ O(bq) rescale + O(1)
            # launch, amortized over 2*bq*bk*d MACs
            overhead = (bq * 4 + 200) / (2.0 * bq * bk * 128)
            cands.append(((bq, bk), overhead + (0 if bq == bk else 1e-9)))
    return [c for c, _ in sorted(cands, key=lambda t: t[1])]


def predict_beam(t_max=4096, beam=8, d_model=256, n_layers=2,
                 n_heads=8, n_kv_heads=2, vocab=512):
    """Per-position beam-8 decode: ~3.5 HBM passes over the KV pool —
    the reorder's gather read + write (2) plus the attention's own
    K/V streams (~1.5 with causal masking) — plus weight streaming
    and ~20 in-scan kernels."""
    d_kv = d_model // n_heads * n_kv_heads
    cache = n_layers * 2 * beam * t_max * d_kv * _BF16  # bf16 bytes
    params = n_layers * ((2 + 2 * n_kv_heads / n_heads) * d_model ** 2
                         + 8 * d_model ** 2) + 2 * vocab * d_model
    # ~3.5 cache passes/position: reorder gather read + write (2) plus
    # the attention's own K and V streams (~1.5 with causal masking)
    step = t_hbm(cache * 3.5) + t_hbm(params * _BF16) \
        + 20 * T_KERNEL_SCAN
    return {"ms_per_pos_beam8": step * 1e3}


def _int8_eff_bytes(d):
    """Measured effective B/param of the int8 matmul path vs model
    width, with the embedding modeled separately at its real int8 size
    (1.25 B/row-element incl. scales): 2.19 at d=768 — yes, WORSE than
    bf16's 2.0, the per-matmul quant bookkeeping costs more than the
    streaming saves on small weights (int8 only won 3% there because
    the embedding shrank) — down to 0.97 =~ true-1B streaming at
    d>=1536 (the measured x1.80 over bf16).  Two-anchor linear
    interpolation (2026-08-01, .watcher/serve_d1536.log); a mid-size
    measurement would refine the crossover."""
    if d <= 768:
        return 2.19
    if d >= 1536:
        return 0.97
    return 2.19 + (d - 768) * (0.97 - 2.19) / (1536 - 768)


def predict_serve(d=768, n_layers=12, vocab=50304, t_max=512):
    """Weight-bound greedy decode, batch 1: ms/token = streamed weight
    bytes / BW + KV traffic + per-layer kernel floors.  f32 and bf16
    tie (the policy cast is hoisted; both stream 2 B/param); int8
    streams ``_int8_eff_bytes(d)`` per matmul param and 1.25 B per
    embedding element (int8 rows + per-row scales)."""
    mm_params = n_layers * 12 * d * d
    emb = vocab * d                                  # tied head table
    cache = n_layers * 2 * t_max * d * _BF16
    floors = (n_layers * 12 + 10) * T_KERNEL_SCAN
    out = {}
    for name, wbytes, ebytes in (("f32", 2, 2), ("bf16", 2, 2),
                                 ("int8", _int8_eff_bytes(d), 1.25)):
        step = t_hbm(mm_params * wbytes + emb * ebytes + cache) + floors
        out["ms_per_tok_" + name] = step * 1e3
    return out


def serve_request_costs(d=768, n_layers=12, vocab=50304, t_max=512):
    """Per-token request pricing for the fleet router's cost-weighted
    placement (``services.costing``): a serving request is predicted
    as  prompt_len x prefill_ms_per_tok + max_new x decode_ms_per_tok.

    * prefill is COMPUTE-bound — the prompt chunk rides one MXU-fed
      parallel pass, so a token costs its matmul flops at the
      calibrated efficiency (plus a share of the per-pass kernel
      floors);
    * decode is WEIGHT-STREAMING-bound — per-token cost is
      ``predict_serve``'s bf16 ms/tok, anchored by the measured
      ``serve_ms_per_tok_bf16`` last-known-good.

    The router CALIBRATES both against the fleet's live measured
    decode ms/tok (the same ratio rescales prefill — the two share
    the device).  The absolute numbers only matter relative to each
    other: placement ranks replicas by predicted outstanding work."""
    mm_params = n_layers * 12 * d * d
    prefill_ms = (2.0 * mm_params / (PEAK_BF16 * EFF_MXU)
                  + (n_layers * 12 + 10) * T_KERNEL_SCAN / 128.0) * 1e3
    decode_ms = predict_serve(d, n_layers, vocab, t_max)[
        "ms_per_tok_bf16"]
    return {"prefill_ms_per_tok": prefill_ms,
            "decode_ms_per_tok": decode_ms,
            "measured_decode_ms_per_tok":
                ANCHORS["serve_ms_per_tok_bf16"]}


def predict_kohonen():
    """512x784 @ 784x256 distance matmul + argmax + weight update."""
    comp = t_matmul(512, 784, 256)
    upd = t_hbm(784 * 256 * 4 * 3)
    return {"ms_per_step": (comp + upd + 10 * T_KERNEL) * 1e3}


#: ContinuousEngine anchors, 2026-08-01 on-chip servecont (84M-class,
#: 8 streams x 128 new tokens, chunked prefill interleaved): solo
#: 328 tok/s, dense pool 521 tok/s (x1.59), paged(16) pool 420 tok/s.
#: The a-priori "weights shared -> 3-8x" model was WRONG on silicon:
#: the engine tick is per-slot-cost dominated (prefill chunks ride the
#: same ticks as decode, and each slot pays its own attention/gather),
#: so the tick decomposes as  tick(slots) = a + slots*b  with
#: a =~ the solo per-token cost (engine + dispatch + weight stream,
#: identical solo vs pooled) and b fit at the measured 8-slot tick.
SERVECONT_SOLO_MS = 3.05          # anchor: 1e3/328
SERVECONT_TICK8_MS = 15.35        # anchor: 8e3/521 (dense)
SERVECONT_TICK8_PAGED_MS = 19.05  # anchor: 8e3/420 (paged GATHER tick)


def predict_servecont(slots=8, paged=False, fused=True):
    """Pool-vs-solo throughput ratio at ``slots`` concurrent streams,
    from the measured tick decomposition above.  At the measured
    8-slot point this reproduces the anchors by construction; other
    slot counts are the prediction.

    ``paged + fused`` is a PRE-REGISTERED prediction (no on-chip
    anchor yet): the fused tick deletes the gather/scatter
    re-materialization — the entire measured paged-vs-dense tick gap
    (19.05 - 15.35 ms at 8 slots) is that copy traffic, and the fused
    kernel's extra cost vs the dense einsum is only the table-indexed
    DMA pattern over the SAME bytes, so the prediction is the dense
    tick.  The first window's three-way servecont A/B
    (.watcher playbook: dense / paged-fused / paged-gather) confirms
    or refutes exactly this number."""
    a = SERVECONT_SOLO_MS
    tick8 = (SERVECONT_TICK8_MS if (not paged or fused)
             else SERVECONT_TICK8_PAGED_MS)
    b = (tick8 - a) / 8.0
    tick = a + slots * b
    pool_tps = slots / tick * 1e3
    solo_tps = 1e3 / a
    return {"pool_tokens_per_sec": pool_tps,
            "solo_tokens_per_sec": solo_tps,
            "pool_vs_solo": pool_tps / solo_tps}


def predict_pipeline_lm_large(s=4, m=16, v=2):
    """Multi-chip pipeline prediction for the 124M flagship: step time
    under plain vs interleaved 1F1B from the verified schedule tables
    (parallel.interleave) x the roofline per-chunk compute time, plus
    the once-per-step constants (optimizer sweep over this chip's 1/s
    of the params, dispatch/host overhead).  No chip pod exists to
    measure against yet — this is the pre-registered prediction the
    first multi-chip window confirms."""
    from veles_tpu.parallel.interleave import build_schedule

    base = _lm_predict(768, 12, 1024, 50304, batch=m, n_heads=12,
                       steps_per_dispatch=4)
    # one microbatch through one chunk (1/(s*v) of the blocks), fwd
    # only — compute time only; bwd sub-ticks cost ~2x fwd
    t_chunk_fwd = base["compute_ms"] / 1e3 / (3 * m * v * s)
    const = (base["opt_ms"] / s + base["overhead_ms"]) / 1e3
    ticks_plain = (m + 2 * (s - 1)) * v      # superstage = v chunks
    ticks_inter = build_schedule(s, v, m)["n_ticks"]
    step_plain = ticks_plain * 3 * t_chunk_fwd + const
    step_inter = ticks_inter * 3 * t_chunk_fwd + const
    ideal = m * v * 3 * t_chunk_fwd + const  # zero-bubble bound
    return {
        "s": s, "m": m, "v": v,
        "step_ms_plain_1f1b": round(step_plain * 1e3, 1),
        "step_ms_interleaved": round(step_inter * 1e3, 1),
        "step_ms_zero_bubble_bound": round(ideal * 1e3, 1),
        "interleaved_speedup": round(step_plain / step_inter, 3),
        "bubble_plain": round(1 - ideal / step_plain, 3),
        "bubble_interleaved": round(1 - ideal / step_inter, 3),
    }


# ---------------------------------------------------------------------------
# Postdiction + bench integration
# ---------------------------------------------------------------------------

def postdiction_table():
    """(name, predicted, measured, ratio, kind) rows.  kind='anchor'
    rows calibrated a constant (self-consistency only); kind='postdict'
    rows are the honest validation."""
    g = predict_gemm()
    mlp = predict_mlp()
    alex = predict_alexnet()
    beam = predict_beam()
    koh = predict_kohonen()
    sv = predict_serve()
    fl = predict_flash()
    lm_big = _lm_predict(768, 12, 1024, 50304, batch=16, n_heads=12,
                         steps_per_dispatch=4)
    lm_small = _lm_predict(512, 8, 1024, 8192, batch=8, n_heads=8,
                           n_kv_heads=2, steps_per_dispatch=5,
                           tied=False)
    lm_t2048 = _lm_predict(768, 12, 2048, 50304, batch=8, n_heads=12,
                           steps_per_dispatch=4)
    rows = [
        # anchors: each calibrated one constant on the 2026-08-01
        # window (EFF_MXU, the 3001^2 pair, H_STEP/T_DISPATCH, T_KERNEL,
        # CONV_DERATE, FLASH_EFF, T_KERNEL_SCAN respectively)
        ("gemm f32 GFLOP/s", g["gflops"], ANCHORS["gemm_f32_gflops"],
         "anchor"),
        ("gemm bf16 TF/s", g["bf16_gflops"] / 1e3, ANCHORS["gemm_bf16_tf"],
         "anchor"),
        ("gemm bf16 3001^2 GFLOP/s",
         2.0 * 3001 ** 3 / t_matmul(3001, 3001, 3001,
                                    eff=EFF_MXU_3001_BF16) / 1e9,
         ANCHORS["gemm_bf16_3001_gflops"], "anchor"),
        ("mlp step ms", mlp["step_ms"], ANCHORS["mlp_step_ms"], "anchor"),
        ("mlp fused ms", mlp["step_fused_ms"], ANCHORS["mlp_step_fused_ms"],
         "anchor"),
        ("kohonen ms/step", koh["ms_per_step"],
         ANCHORS["kohonen_ms_per_step"], "anchor"),
        ("alexnet samples/s", alex["samples_per_sec"],
         ANCHORS["alexnet_samples_per_sec"], "anchor"),
        ("lm-124M ms/step", lm_big["ms_per_step"],
         ANCHORS["lm_large_ms_per_step"], "anchor"),
        ("serve bf16 ms/tok", sv["ms_per_tok_bf16"],
         ANCHORS["serve_ms_per_tok_bf16"], "anchor"),
        # postdicts: holdouts no constant was fit to — the honest
        # validation rows
        ("lm-25M ms/step", lm_small["ms_per_step"],
         ANCHORS["lm_ms_per_step"], "postdict"),
        ("lm-124M T=2048 ms/step", lm_t2048["ms_per_step"],
         ANCHORS["lm_large_t2048_ms_per_step"], "postdict"),
        ("beam ms/pos", beam["ms_per_pos_beam8"],
         ANCHORS["beam_ms_per_pos_t4096"], "postdict"),
        ("serve int8 ms/tok", sv["ms_per_tok_int8"],
         ANCHORS["serve_ms_per_tok_int8"], "anchor"),
        ("flash T=8192 ms", fl["ms_long_t8192"],
         ANCHORS["flash_t8192_ms"], "anchor"),
        ("flash T=8192 XLA ms", fl["ms_long_t8192_xla"],
         ANCHORS["flash_t8192_xla_ms"], "anchor"),
        ("serve bf16 d=1536 ms/tok",
         predict_serve(d=1536)["ms_per_tok_bf16"],
         ANCHORS["serve_d1536_ms_per_tok_bf16"], "postdict"),
        ("serve int8 d=1536 ms/tok",
         predict_serve(d=1536)["ms_per_tok_int8"],
         ANCHORS["serve_d1536_ms_per_tok_int8"], "anchor"),
    ]
    return [(n, p, m, p / m if m else 0.0, k) for n, p, m, k in rows]


def predictions_for_bench():
    """Flat predicted-value dict keyed like bench.py's JSON line — the
    orchestrator attaches this under ``"predicted"`` so every uptime
    window ships its own predicted-vs-measured record."""
    g = predict_gemm()
    mlp = predict_mlp()
    lm = predict_lm()
    ladder = predict_lm_large_ladder()
    fl = predict_flash()
    sv = predict_serve()
    return {
        "value": round(g["gflops"], 1),
        "gemm_bf16_gflops": round(g["bf16_gflops"], 1),
        "gemm_bf16_mfu": round(g["bf16_mfu"], 3),
        "gemm_precision_overhead_pct": round(
            g["precision_overhead_pct"], 1),
        "mlp_step_ms": round(mlp["step_ms"], 3),
        "mlp_step_fused_ms": round(mlp["step_fused_ms"], 3),
        "alexnet_samples_per_sec": round(
            predict_alexnet()["samples_per_sec"], 1),
        "lm_tokens_per_sec": round(lm["tokens_per_sec"], 1),
        "lm_mfu": round(lm["mfu"], 3),
        "lm_large_tokens_per_sec": round(ladder[0]["tokens_per_sec"], 1),
        "lm_large_mfu": round(ladder[0]["mfu"], 3),
        "lm_large_ladder": [
            {"remat": r["remat"], "batch": r["batch"],
             "mfu": round(r["mfu"], 3)} for r in ladder],
        "flash_ms_bf16": round(fl["ms_bf16"], 3),
        "flash_ms_bf16_xla": round(fl["ms_bf16_xla"], 3),
        "flash_ms_bwd": round(fl["ms_bwd"], 3),
        "flash_ms_bwd_xla": round(fl["ms_bwd_xla"], 3),
        "flash_ms_long_t8192": round(fl["ms_long_t8192"], 2),
        "flash_ms_long_t8192_xla": round(fl["ms_long_t8192_xla"], 2),
        "beam_ms_per_pos_t4096": round(
            predict_beam()["ms_per_pos_beam8"], 3),
        "serve_ms_per_tok_bf16": round(sv["ms_per_tok_bf16"], 3),
        "serve_ms_per_tok_int8": round(sv["ms_per_tok_int8"], 3),
        "kohonen_ms_per_step": round(
            predict_kohonen()["ms_per_step"], 3),
        "flashtune_order": [list(c) for c in predict_flashtune_order()],
    }


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--json", action="store_true",
                   help="dump predictions_for_bench() as JSON")
    args = p.parse_args()
    if args.json:
        print(json.dumps(predictions_for_bench(), indent=1))
        return
    print("Roofline postdiction vs the 2026-08-01 on-chip anchors")
    print("%-22s %10s %10s %7s  %s" % ("phase", "predicted", "measured",
                                       "ratio", "kind"))
    for name, pred, meas, ratio, kind in postdiction_table():
        print("%-22s %10.3f %10.3f %6.2fx  %s"
              % (name, pred, meas, ratio, kind))
    print("\nPredictions for never-measured phases "
          "(the uptime window confirms these):")
    for k, v in sorted(predictions_for_bench().items()):
        print("  %-28s %s" % (k, v))


if __name__ == "__main__":
    main()

