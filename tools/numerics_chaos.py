#!/usr/bin/env python
"""Chaos gate for the NUMERIC-fault survival tier (services.sentinel,
docs/distributed_training.md "Numeric-fault survival") — the numerics
twin of tools/train_chaos.py.

Three legs over the same seeded workload (default: the self-contained
digits MLP), all supervised (the sandbox startup flake must cost a
respawn, not the gate):

* **golden-skip** — an un-chaosed run whose sentinel is told to
  policy-skip the target step (``root.common.sentinel.force_skip_steps``):
  the reference trajectory for "that batch's update never applied".
* **transient injection** — the same seed with NaN injected into the
  gradient tree at exactly that step
  (``root.common.chaos.nan_grads_step``).  The in-jit probes must
  catch it (rung 1 skip keeps params finite), the sentinel must roll
  back to the last HEALTHY commit **exactly once** and replay with the
  poisoned minibatch skipped, and the final checkpoint must be
  **bit-identical** (threshold 0) to the golden-skip run — rollback
  and replay proven an exactness-preserving recovery, not a lossy one.
* **persistent injection** — NaN on every step from the target onward
  (``root.common.chaos.nan_grads_from``): the rollback ladder cannot
  outrun it, so the run must escalate with a ``numerics:<kind>`` crash
  class and the supervisor must trip its numerics give-up valve WITH a
  diagnosis — bounded lives, checkpoints intact, no crash loop.

Exit 0 iff every gate passes; ``--json`` writes the report,
``--artifacts`` collects crashdumps + per-attempt logs for CI (the
``numerics-chaos`` job runs this on synthetic MNIST).

    python tools/numerics_chaos.py --epochs 6 --json report.json
"""

import argparse
import json
import os
import re
import shutil
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools import chaos_common as cc   # noqa: E402 — path set above

#: the sentinel's rollback log marker (HealthSentinel._rollback) — one
#: line per rollback in the owning attempt's log
ROLLBACK_RE = re.compile(r"sentinel rollback #(\d+):")


def build_argv(workflow, config, snap_dir, seed, extra_config=()):
    argv = [sys.executable, "-m", "veles_tpu", workflow]
    if config:
        argv.append(config)
    cl = ["root.common.dirs.snapshots=%r" % str(snap_dir)]
    cl += list(extra_config)
    argv += ["--config-list"] + cl
    argv += ["--backend", "cpu", "--random-seed", str(seed),
             "--snapshot-every", "1", "--snapshot", "auto"]
    return argv


def run_supervised(argv, env, snap_dir, logs_dir, dumps_dir, seed,
                   timeout, max_restarts=6, deterministic_limit=3):
    """One leg under the respawn Supervisor; returns (rc, sup)."""
    from veles_tpu.services.supervisor import Supervisor
    sup = Supervisor(argv, env=env, max_restarts=max_restarts,
                     window_seconds=max(timeout, 600),
                     backoff_base_ms=50, backoff_max_ms=1000,
                     deterministic_limit=deterministic_limit,
                     blackbox_dir=dumps_dir, progress_paths=[snap_dir],
                     log_dir=logs_dir, install_signals=False, seed=seed)
    result = {}
    runner = threading.Thread(
        target=lambda: result.update(rc=sup.run()), daemon=True)
    runner.start()
    runner.join(timeout=timeout)
    if runner.is_alive():
        sup.stop()
        runner.join(timeout=60)
    return result.get("rc"), sup


def count_rollbacks(logs_dir):
    """Rollback markers across every attempt log of one leg."""
    total, per_attempt = 0, {}
    try:
        names = sorted(os.listdir(logs_dir))
    except OSError:
        return 0, {}
    for name in names:
        if not name.startswith("attempt-"):
            continue
        try:
            with open(os.path.join(logs_dir, name), "rb") as f:
                text = f.read().decode("utf-8", "replace")
        except OSError:
            continue
        n = len(ROLLBACK_RE.findall(text))
        if n:
            per_attempt[name] = n
            total += n
    return total, per_attempt


def run_chaos(args):
    workdir = args.workdir or tempfile.mkdtemp(prefix="numerics_chaos_")
    os.makedirs(workdir, exist_ok=True)
    dirs = {}
    for leg in ("golden", "transient", "persistent"):
        dirs[leg] = {
            "snap": os.path.join(workdir, leg, "snapshots"),
            "logs": os.path.join(workdir, leg, "logs"),
        }
        for d in dirs[leg].values():
            os.makedirs(d, exist_ok=True)
    dumps_dir = os.path.join(workdir, "dumps")
    os.makedirs(dumps_dir, exist_ok=True)

    workflow, config, prefix = args.workflow, args.config, args.prefix
    extra = list(args.config_list)
    if workflow is None:
        workflow = cc.write_digits_workflow(
            os.path.join(workdir, "chaos_workflow.py"),
            ns="numerics_chaos", name="numerics-chaos",
            default_epochs=args.epochs)
        extra += ["root.numerics_chaos.max_epochs=%d" % args.epochs]
        prefix = "numerics-chaos"
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    common = extra + [
        "root.common.snapshot.keep_last=%d" % args.keep_last,
        "root.common.blackbox.dir=%r" % dumps_dir,
        # the gate pins the LADDER's shape, so the knobs are explicit
        # instead of riding defaults
        "root.common.sentinel.strikes_to_rollback=1",
        "root.common.sentinel.rollbacks_to_escalate=%d"
        % args.rollbacks_to_escalate,
    ]
    step = args.nan_step
    report = {"workdir": workdir, "prefix": prefix, "seed": args.seed,
              "nan_step": step, "epochs": args.epochs}

    # ---- leg 1: golden-skip ----------------------------------------
    t0 = time.time()
    golden_argv = build_argv(
        workflow, config, dirs["golden"]["snap"], args.seed,
        common + ["root.common.sentinel.force_skip_steps=(%d,)" % step])
    print("[numerics-chaos] golden-skip run: %s" % " ".join(golden_argv),
          flush=True)
    rc, sup = run_supervised(golden_argv, env, dirs["golden"]["snap"],
                             dirs["golden"]["logs"], dumps_dir,
                             args.seed, args.timeout)
    report["golden"] = {"rc": rc, "spawns": sup.spawn_count,
                        "wall_s": round(time.time() - t0, 2)}
    golden_final, _ = cc.current_target(dirs["golden"]["snap"], prefix)
    report["golden"]["final"] = golden_final
    if rc != 0 or golden_final is None:
        report["error"] = "golden-skip run failed — see golden/logs/"
        return report

    # ---- leg 2: transient injection --------------------------------
    t0 = time.time()
    transient_argv = build_argv(
        workflow, config, dirs["transient"]["snap"], args.seed,
        common + ["root.common.chaos.nan_grads_step=%d" % step])
    print("[numerics-chaos] transient run: %s"
          % " ".join(transient_argv), flush=True)
    rc, sup = run_supervised(
        transient_argv, env, dirs["transient"]["snap"],
        dirs["transient"]["logs"], dumps_dir, args.seed, args.timeout)
    rollbacks, per_attempt = count_rollbacks(dirs["transient"]["logs"])
    transient_final, _ = cc.current_target(dirs["transient"]["snap"],
                                           prefix)
    n_valid, invalid = cc.validate_ring(dirs["transient"]["snap"],
                                        prefix)
    report["transient"] = {
        "rc": rc, "spawns": sup.spawn_count,
        "wall_s": round(time.time() - t0, 2),
        "rollbacks": rollbacks, "rollbacks_per_attempt": per_attempt,
        "final": transient_final,
        "quarantined": sorted(
            n for n in os.listdir(dirs["transient"]["snap"])
            if n.endswith(".corrupt")),
        "ring_valid": n_valid, "ring_invalid": invalid,
    }
    if transient_final and golden_final:
        from veles_tpu.scripts.compare_snapshots import diff_report
        try:
            report["transient"]["exactness"] = diff_report(
                golden_final, transient_final, threshold=0.0)
        except Exception as e:   # noqa: BLE001 — report; gate fails
            report["transient"]["exactness"] = {"identical": False,
                                                "error": str(e)}

    # ---- leg 3: persistent injection -------------------------------
    t0 = time.time()
    persistent_argv = build_argv(
        workflow, config, dirs["persistent"]["snap"], args.seed,
        common + ["root.common.chaos.nan_grads_from=%d" % step])
    print("[numerics-chaos] persistent run: %s"
          % " ".join(persistent_argv), flush=True)
    rc, sup = run_supervised(
        persistent_argv, env, dirs["persistent"]["snap"],
        dirs["persistent"]["logs"], dumps_dir, args.seed, args.timeout,
        max_restarts=args.deterministic_limit + 6,
        deterministic_limit=args.deterministic_limit)
    n_valid, invalid = cc.validate_ring(dirs["persistent"]["snap"],
                                        prefix)
    persistent_final, _ = cc.current_target(dirs["persistent"]["snap"],
                                            prefix)
    current_imports = None
    if persistent_final:
        from veles_tpu.services.snapshotter import SnapshotterBase
        try:
            SnapshotterBase.import_(persistent_final)
            current_imports = True
        except Exception as e:   # noqa: BLE001 — the audit itself
            current_imports = False
            report.setdefault("errors", []).append(
                "persistent _current unimportable: %s" % e)
    report["persistent"] = {
        "rc": rc, "spawns": sup.spawn_count,
        "wall_s": round(time.time() - t0, 2),
        "giveup_reason": sup.giveup_reason,
        "giveup_diagnosis": sup.giveup_diagnosis,
        "history_kinds": [h["kind"] for h in sup.history],
        "final": persistent_final, "current_imports": current_imports,
        "ring_valid": n_valid, "ring_invalid": invalid,
    }
    return report


def gates(report, args):
    fails = []
    if report.get("error"):
        fails.append(report["error"])
        return fails
    if report.get("golden", {}).get("rc") != 0:
        fails.append("golden-skip rc=%s" % report["golden"].get("rc"))

    t = report.get("transient", {})
    if t.get("rc") != 0:
        fails.append("transient run rc=%s (must recover and finish)"
                     % t.get("rc"))
    if t.get("rollbacks") != 1:
        fails.append("transient injection cost %s rollbacks, expected "
                     "exactly 1" % t.get("rollbacks"))
    if not t.get("quarantined"):
        fails.append("the poisoned (unhealthy) commit was never "
                     "quarantined on rollback")
    if t.get("ring_invalid"):
        fails.append("transient ring has invalid commits: %s"
                     % t["ring_invalid"])
    exact = t.get("exactness")
    if not exact:
        fails.append("no exactness verdict (missing final checkpoint)")
    elif not exact.get("identical"):
        detail = exact.get("error") or exact.get("diffs", [])[:5]
        fails.append("rollback+replay final state NOT bit-identical "
                     "to the golden skip-batch run: %s" % (detail,))

    p = report.get("persistent", {})
    if not p.get("rc"):
        fails.append("persistent injection exited rc=%s — it must "
                     "give up, not succeed" % p.get("rc"))
    if p.get("giveup_reason") != "numerics":
        fails.append("supervisor give-up reason %r, expected "
                     "'numerics' (the deterministic numeric-fault "
                     "valve)" % p.get("giveup_reason"))
    if not p.get("giveup_diagnosis"):
        fails.append("numerics give-up carried no diagnosis")
    kinds = p.get("history_kinds", [])
    if not any(str(k).startswith("numerics:") for k in kinds):
        fails.append("no numerics:<kind> exit classified (history: %s)"
                     % kinds)
    if p.get("spawns", 0) > args.deterministic_limit + 4:
        fails.append("persistent injection crash-looped: %d spawns "
                     "for deterministic_limit=%d"
                     % (p.get("spawns", 0), args.deterministic_limit))
    if p.get("ring_invalid"):
        fails.append("persistent ring has invalid commits (data NOT "
                     "intact): %s" % p["ring_invalid"])
    if p.get("ring_valid", 0) < 1:
        fails.append("persistent give-up left no valid checkpoint")
    if p.get("current_imports") is False:
        fails.append("persistent _current does not import")
    return fails


def main(argv=None):
    p = argparse.ArgumentParser(
        description="chaos gate for the numeric-fault survival tier "
        "(docs/distributed_training.md)")
    p.add_argument("--workflow", default=None,
                   help="workflow .py (default: self-contained digits "
                   "MLP)")
    p.add_argument("--config", default=None, help="config .py")
    p.add_argument("--config-list", nargs="*", default=[],
                   help="extra inline config statements for ALL legs")
    p.add_argument("--prefix", default=None,
                   help="snapshot prefix (required with --workflow)")
    p.add_argument("--epochs", type=int, default=6,
                   help="epochs for the default digits workload")
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--nan-step", type=int, default=30,
                   help="staged train step to poison (must land after "
                   "the first epoch's commit so a healthy rollback "
                   "target exists)")
    p.add_argument("--rollbacks-to-escalate", type=int, default=1,
                   help="sentinel rollbacks before rung-3 escalation "
                   "(per life)")
    p.add_argument("--deterministic-limit", type=int, default=2,
                   help="supervisor numerics valve: identical "
                   "numeric-fault give-ups before giving up for good")
    p.add_argument("--keep-last", type=int, default=6,
                   help="checkpoint ring size for all legs")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--workdir", default=None,
                   help="working directory (default: fresh tempdir; "
                   "kept on failure, removed on success unless given)")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="write the full report here")
    p.add_argument("--artifacts", default=None, metavar="DIR",
                   help="collect crashdumps + attempt logs + a flight "
                   "dump here (CI upload)")
    args = p.parse_args(argv)
    if args.workflow is not None and args.prefix is None:
        p.error("--workflow needs --prefix")

    report = run_chaos(args)
    fails = gates(report, args)
    report["gates_failed"] = fails

    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2, default=str)
        print("[numerics-chaos] report -> %s" % args.json)
    if args.artifacts:
        os.makedirs(args.artifacts, exist_ok=True)
        workdir = report.get("workdir")
        for leg in ("golden", "transient", "persistent"):
            src = os.path.join(workdir, leg, "logs")
            if os.path.isdir(src):
                shutil.copytree(
                    src, os.path.join(args.artifacts, leg + "-logs"),
                    dirs_exist_ok=True)
        src = os.path.join(workdir, "dumps")
        if os.path.isdir(src):
            shutil.copytree(src, os.path.join(args.artifacts, "dumps"),
                            dirs_exist_ok=True)
        from veles_tpu.telemetry import flight
        flight.dump(directory=args.artifacts, reason="numerics-chaos")
        print("[numerics-chaos] artifacts -> %s" % args.artifacts)

    summary = {
        "golden_rc": report.get("golden", {}).get("rc"),
        "transient_rc": report.get("transient", {}).get("rc"),
        "transient_rollbacks": report.get("transient",
                                          {}).get("rollbacks"),
        "persistent_rc": report.get("persistent", {}).get("rc"),
        "persistent_giveup": report.get("persistent",
                                        {}).get("giveup_reason"),
    }
    print(json.dumps(summary, default=str))
    if fails:
        print("[numerics-chaos] GATES FAILED:", flush=True)
        for f in fails:
            print("  - %s" % f)
        print("[numerics-chaos] workdir kept: %s"
              % report.get("workdir"))
        return 1
    exact = report.get("transient", {}).get("exactness", {})
    print("[numerics-chaos] ALL GATES PASSED: transient NaN recovered "
          "with exactly one rollback, final state bit-identical to the "
          "golden skip-batch run (%d leaves); persistent NaN tripped "
          "the numerics give-up valve with checkpoints intact"
          % exact.get("n_leaves", 0))
    if args.workdir is None:
        shutil.rmtree(report["workdir"], ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
