#!/usr/bin/env python3
"""Part 2 of the axon-tunnel timing audit: can a SINGLE dispatch +
``block_until_ready`` be trusted (the gemm/flash in-scan pattern), or
does only a host ``device_get`` prove completion?

Pattern: one jitted scan of K matmuls, then
  t_block   = time(block_until_ready(out))
  t_fetch   = time(device_get(out[0,0])) right after the block
If the block is honest, the fetch is pure RTT (~tens of ms).  If the
block acks early, the fetch absorbs the remaining compute and
t_fetch ~ t_compute — and every single-dispatch bench number must be
re-measured with a fetch barrier.
"""

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    print("devices:", jax.devices(), flush=True)
    n, iters = 8192, 10
    a = np.random.RandomState(0).rand(n, n).astype(np.float32)
    a /= np.linalg.norm(a)
    a = jnp.asarray(a).astype(jnp.bfloat16)

    def body(y, _):
        return jnp.dot(y, a), None

    f = jax.jit(lambda y: lax.scan(body, y, None, length=iters)[0],
                donate_argnums=(0,))
    y = jax.block_until_ready(f(jnp.copy(a)))
    _ = jax.device_get(y[0, 0])          # drain any stragglers

    flops = 2.0 * n ** 3 * iters
    for rep in range(3):
        t0 = time.perf_counter()
        y = f(y)
        t_enq = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.block_until_ready(y)
        t_blk = time.perf_counter() - t0
        t0 = time.perf_counter()
        v = jax.device_get(y[0, 0])
        t_fetch = time.perf_counter() - t0
        print("rep%d: enqueue %6.1f ms | block %7.1f ms (%5.1f TF/s) | "
              "fetch-after-block %7.1f ms | v=%s"
              % (rep, t_enq * 1e3, t_blk * 1e3,
                 flops / max(t_blk, 1e-9) / 1e12, t_fetch * 1e3, v),
              flush=True)
    # bare-RTT reference: fetch a tiny READY array
    z = jax.block_until_ready(jnp.zeros((1,)))
    t0 = time.perf_counter()
    jax.device_get(z)
    print("bare fetch RTT: %.1f ms" % ((time.perf_counter() - t0) * 1e3),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
