#!/usr/bin/env python3
"""One-shot on-silicon profiler capture of the flagship training step.

Round-4 verdict item #7: "one jax.profiler capture around a lm_large
fused dispatch, artifact committed, so where TPU time goes stops being
inference."  This runs the same 124M GPT-2-small-class model as
``bench.py --phase lm_large`` (top ladder rung first, stepping down on
OOM), wraps a few fused dispatches in ``jax.profiler.trace``, then
parses the chrome-trace dump into a top-ops-by-device-time table.

The trace artifact (``*.trace.json.gz``, loadable in Perfetto) is
copied under ``artifacts/`` for the repo; the summary prints to stdout
for BENCH_SESSION.md.  Mirrors the reference's measured-evidence
standard (its device DB is benchmark output from real silicon,
ref ``veles/backends.py:672-731``).

Usage:  python tools/profile_capture.py [--steps 3] [--outdir artifacts/profile_r05]
"""

import argparse
import collections
import glob
import gzip
import json
import os
import shutil
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def build_flagship(remat="dots", batch=16):
    """The bench lm_large flagship: 124M params, T=1024, flash attn."""
    import numpy as np
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_lm

    prng.seed_all(5)
    vocab, seq = 50304, 1024
    n = batch * 4
    toks = np.random.RandomState(0).randint(
        0, vocab, (n, seq)).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=batch,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(
        layers=transformer_lm(
            vocab_size=vocab, d_model=768, n_heads=12, n_layers=12,
            dropout=0.0, impl="flash", pos="rope", solver="adamw",
            lr=6e-4, tie_embeddings=True, remat=remat),
        loader=loader, loss="lm", gd_defaults={"clip_norm": 1.0},
        decision_config={"max_epochs": 1000},
        steps_per_dispatch=4, name="profile-lm-124M")
    wf.initialize()
    return wf


def summarize_trace(trace_path, top=18):
    """Top device ops by total duration from the chrome-trace dump.

    Groups complete events ("ph":"X") by op name within TPU lanes
    (pids whose process_name mentions TPU / device), so host python
    rows don't drown the device timeline."""
    with gzip.open(trace_path, "rb") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    # map pid -> process name from metadata events
    pnames = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pnames[ev.get("pid")] = ev.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in pnames.items()
                   if any(k in name.lower()
                          for k in ("tpu", "device", "/device:"))}
    tot = collections.Counter()
    cnt = collections.Counter()
    t_lo, t_hi = float("inf"), 0.0
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in device_pids:
            continue
        dur = float(ev.get("dur", 0.0))
        ts = float(ev.get("ts", 0.0))
        name = ev.get("name", "?")
        tot[name] += dur
        cnt[name] += 1
        t_lo, t_hi = min(t_lo, ts), max(t_hi, ts + dur)
    rows = tot.most_common(top)
    total = sum(tot.values())
    span = (t_hi - t_lo) / 1e3 if t_hi > t_lo else 0.0
    lines = ["device ops by total time (%d lanes, %.1f ms summed op "
             "time, %.1f ms device-activity span):"
             % (len(device_pids), total / 1e3, span)]
    for name, us in rows:
        lines.append("  %7.2f ms  %5.1f%%  x%-5d %s"
                     % (us / 1e3, 100.0 * us / total if total else 0.0,
                        cnt[name], name[:90]))
    return "\n".join(lines), {"total_device_op_ms": total / 1e3,
                              "device_span_ms": span,
                              "top": [(n, round(u / 1e3, 3))
                                      for n, u in rows]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3,
                    help="fused dispatches inside the trace window")
    ap.add_argument("--outdir", default=os.path.join(
        ROOT, "artifacts", "profile_r05"))
    args = ap.parse_args()

    import gc

    import jax
    try:
        devices = jax.devices()
    except Exception as e:  # noqa: BLE001 — tunnel down / no accelerator
        # fail soft: the capture tool runs on a cadence from watchers
        # and CI boxes where the accelerator is usually absent — that is
        # an expected outcome, not a traceback
        print("no accelerator available (%s: %s) — nothing to profile"
              % (type(e).__name__, e), flush=True)
        return 0
    print("devices:", devices, flush=True)
    from veles_tpu.telemetry import flight

    # the same rung order phase_lm_large walks (single source of truth)
    from veles_tpu.ops.flops import LM_LARGE_LADDER
    wf = None
    for remat, batch in [(r, b) for r, b, _, _ in LM_LARGE_LADDER]:
        try:
            wf = build_flagship(remat=remat, batch=batch)
            # compile + warmup outside the trace window
            for _ in range(8):
                wf.loader.run()
                wf.trainer.run()
            wf.trainer.flush()
            jax.device_get(wf.trainer.class_stats[2]["loss"])
            break
        except Exception as e:  # noqa: BLE001 — OOM ladder
            if "RESOURCE_EXHAUSTED" not in str(e) and \
                    "Out of memory" not in str(e):
                raise
            print("remat=%s b%d OOM — next rung" % (remat, batch),
                  flush=True)
            wf = None
            gc.collect()
    if wf is None:
        print("all ladder rungs OOM", flush=True)
        return 1

    tmpdir = os.path.join(ROOT, ".watcher", "profile_raw")
    shutil.rmtree(tmpdir, ignore_errors=True)
    # the capture window joins the flight ring: a post-mortem of this
    # process shows profiler-on/off bracketing the training steps
    flight.record("profile.capture.start", outdir=args.outdir,
                  steps=args.steps)
    t0 = time.perf_counter()
    with jax.profiler.trace(tmpdir):
        for _ in range(args.steps):
            wf.loader.run()
            wf.trainer.run()
        wf.trainer.flush()
        # fetch, not block: block_until_ready acks early on the tunnel
        # backend (tools/diag_async.py) and would close the trace
        # window before the device work ran
        jax.device_get(wf.trainer.class_stats[2]["loss"])
    wall = time.perf_counter() - t0
    flight.record("profile.capture.stop", outdir=args.outdir,
                  dur_s=wall)
    print("traced %d fused dispatches (4 train steps each) in %.1f ms"
          % (args.steps, wall * 1e3), flush=True)

    paths = sorted(glob.glob(os.path.join(
        tmpdir, "plugins", "profile", "*", "*.trace.json.gz")))
    if not paths:
        print("no trace.json.gz produced under", tmpdir, flush=True)
        return 1
    os.makedirs(args.outdir, exist_ok=True)
    dest = os.path.join(args.outdir, "lm_large_step.trace.json.gz")
    shutil.copy(paths[-1], dest)
    summary, stats = summarize_trace(paths[-1])
    print(summary, flush=True)
    stats["wall_ms_traced"] = round(wall * 1e3, 1)
    stats["steps_traced"] = args.steps * 4
    with open(os.path.join(args.outdir, "summary.json"), "w") as f:
        json.dump(stats, f, indent=1)
    print("artifact:", os.path.relpath(dest, ROOT), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
