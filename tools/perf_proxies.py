"""CPU-proxy performance metrics for the perf-ledger CI job.

The real numbers live on silicon (bench.py, banked into the repo's
PERF_LEDGER.jsonl), but two properties are measurable anywhere and
worth guarding every merge:

* **ratios** — segmented-vs-unsegmented decode-stall behaviour is a
  scheduling property of the engine, not of the chip; the segmented
  run must beat the unsegmented one on a laptop exactly as on a v5e.
* **host-side overheads** — the tuner's launch-time lookup and the
  perf ledger's own append are pure host code on the dispatch path;
  a regression there is a regression everywhere.

Each proxy appends to the target ledger (``--out``, default the
process ledger) through the same ``telemetry.ledger`` plumbing the
real harnesses use, so ``veles-tpu-perf report`` / ``gate`` read CI
runs and silicon runs identically — the keys differ only on the
backend axis.

Usage:  python tools/perf_proxies.py --out /tmp/perf_ledger.jsonl \
            --repeat 4
"""

import argparse
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def tuner_lookup_us(n=2000):
    """Mean launch-time lookup cost (µs) against a warm 64-winner
    cache — hits and misses both ride the dispatch path."""
    from veles_tpu import tuner as tn
    with tempfile.TemporaryDirectory() as d:
        t = tn.KernelTuner(path=os.path.join(d, "winners.json"))
        for i in range(64):
            t.record("flash", "t%d_d64" % (128 << (i % 8)), "float32",
                     {"block_q": 128, "block_k": 128}, 1.0 + i)
        keys = [("flash", "t%d_d64" % (128 << (i % 8)), "float32")
                for i in range(n)]
        t0 = time.perf_counter()
        for kernel, shape, dtype in keys:
            t.lookup(kernel, shape, dtype)
        return (time.perf_counter() - t0) / n * 1e6


def ledger_append_us(n=500):
    """Mean cost (µs) of one ledger append — the price every banked
    step/gate/bench row pays; it must stay negligible next to even a
    sub-millisecond step."""
    from veles_tpu.telemetry import ledger
    with tempfile.TemporaryDirectory() as d:
        book = ledger.PerfLedger(os.path.join(d, "led.jsonl"))
        t0 = time.perf_counter()
        for i in range(n):
            book.append("proxy_overhead_probe", float(i), unit="us",
                        source="perf_proxies", assess=False)
        return (time.perf_counter() - t0) / n * 1e6


def seg_stall_ratio():
    """Segmented-vs-unsegmented p99 decode-stall ratio from one small
    mixed storm (tools/serve_loadtest.run_mixed) — must stay well
    under 1.0 on any box.  Returns (ratio, seg_p99, unseg_p99) or
    None when the storm could not run."""
    from tools import serve_loadtest as lt
    report = lt.run_mixed(prefill_segment=8, long_len=64,
                          stream_new=16, long_new=2, seed=7,
                          streamers=2, long_clients=2, short_len=5,
                          slots=2)
    seg = (report.get("segmented") or {}).get("p99_decode_stall_ms")
    unseg = (report.get("unsegmented") or {}).get(
        "p99_decode_stall_ms")
    if not seg or not unseg:
        return None
    return round(seg / unseg, 3), seg, unseg


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="CPU-proxy perf metrics -> performance ledger "
                    "(telemetry.ledger; docs/perf.md)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="ledger JSONL to append to (default: the "
                         "process ledger)")
    ap.add_argument("--repeat", type=int, default=1,
                    help="measurement rounds — >=4 gives the "
                         "sentinel a band (min_history priors) to judge the last one")
    ap.add_argument("--skip-storm", action="store_true",
                    help="skip the mixed-storm ratio proxy (engine "
                         "spin-up; the host-overhead proxies are "
                         "cheap)")
    args = ap.parse_args(argv)

    from veles_tpu.telemetry import ledger
    book = ledger.PerfLedger(args.out) if args.out else ledger.default()
    rc = 0
    for round_i in range(max(args.repeat, 1)):
        rec = book.append("tuner_lookup_us", tuner_lookup_us(),
                          workload="cpu-proxy", unit="us",
                          better="lower", source="perf_proxies")
        print("tuner_lookup_us: %s" % ((rec or {}).get("value"),))
        rec = book.append("ledger_append_us", ledger_append_us(),
                          workload="cpu-proxy", unit="us",
                          better="lower", source="perf_proxies")
        print("ledger_append_us: %s" % ((rec or {}).get("value"),))
        if not args.skip_storm:
            try:
                got = seg_stall_ratio()
            except Exception as e:  # noqa: BLE001 — proxy best-effort
                print("mixed-storm proxy failed: %s" % e,
                      file=sys.stderr)
                got, rc = None, 1
            if got:
                ratio, seg, unseg = got
                book.append("serve_stall_seg_vs_unseg_x", ratio,
                            workload="cpu-proxy", unit="x",
                            better="lower", source="perf_proxies",
                            seg_p99_ms=seg, unseg_p99_ms=unseg)
                print("serve_stall_seg_vs_unseg_x: %s "
                      "(seg %.3f ms vs unseg %.3f ms)"
                      % (ratio, seg, unseg))
    print("ledger: %s (%d records)"
          % (book.path, len(book.records())))
    return rc


if __name__ == "__main__":
    sys.exit(main())
