#!/usr/bin/env python3
"""Flash block sweep at the FLAGSHIP's own attention shape.

phase_flashtune sweeps (B,H,T,d) = (4,8,T,128); the 124M flagship runs
(16,12,1024,64).  At d=64 the VMEM slabs are half the d=128 case, so
blocks up to the full T=1024 fit — and at T=1024 the kernel is
bookkeeping-bound (measured 3.1 TF/s vs 33 at T=8192), so fewer,
larger blocks are the predicted win.  Sweeps fwd and fused bwd
head-to-head with XLA-naive on the same shape, using bench.py's
chained in-jit timing (single dispatch + block: the honest pattern
per tools/diag_sync2.py).
"""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main():
    import jax
    import jax.numpy as jnp

    import bench
    from veles_tpu.ops.attention import attention as naive
    from veles_tpu.ops.flops import causal_attn_flops
    from veles_tpu.ops.pallas.flash import flash_attention

    print("devices:", jax.devices(), flush=True)
    b, h, t, d = 16, 12, 1024, 64
    key = jax.random.key(5)
    q, k, v = (jax.random.normal(kk, (b, h, t, d), jnp.bfloat16) * 0.1
               for kk in jax.random.split(key, 3))
    flops = causal_attn_flops(b, h, t, d)

    def report(tag, ms, ms_bwd):
        print("%-18s fwd %7.3f ms (%5.1f TF/s)  fwd+bwd %7.3f ms"
              % (tag, ms, flops / (ms / 1e3) / 1e12, ms_bwd), flush=True)

    ms = bench._chain_attn(
        lambda q_, k_, v_: naive(q_, k_, v_, causal=True), q, k, v, 10)
    ms_bwd = bench._chain_attn(
        lambda q_, k_, v_: naive(q_, k_, v_, causal=True), q, k, v, 5,
        grad=True)
    report("xla-naive", ms, ms_bwd)

    for bq, bk in ((1024, 1024), (1024, 512), (512, 1024), (512, 512),
                   (512, 256), (256, 512), (256, 256)):
        fn = lambda q_, k_, v_: flash_attention(   # noqa: E731
            q_, k_, v_, causal=True, block_q=bq, block_k=bk)
        try:
            ms = bench._chain_attn(fn, q, k, v, 10)
            ms_bwd = bench._chain_attn(fn, q, k, v, 5, grad=True)
        except Exception as e:  # noqa: BLE001 — VMEM overflow etc.
            print("bq=%d bk=%d failed: %s" % (bq, bk, str(e)[:100]),
                  flush=True)
            continue
        report("flash %dx%d" % (bq, bk), ms, ms_bwd)
    return 0


if __name__ == "__main__":
    sys.exit(main())
