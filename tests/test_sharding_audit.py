"""Sharding & memory auditor suite (VS2xx/VM3xx, docs/static_analysis.md):
one seeded defect per rule caught from a PURELY ABSTRACT lowering (no
computation dispatched, no device array created — asserted), the
silent-replication fallback recording in parallel/sharding.py, the VM300
peak-HBM estimate within 2x of XLA's own compiled-buffer accounting on a
real workflow, and the CLI surfaces (`--mesh`, `--fsdp`, `--fail-on`)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.analysis import (audit_sharded_step, has_errors,
                                lint_workflow)
from veles_tpu.analysis.sharding_audit import (activation_highwater,
                                               collective_stats,
                                               estimate_peak_hbm)
from veles_tpu.parallel import MeshConfig, make_mesh, sharding


def rules(findings):
    return {f.rule for f in findings}


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


def mc22(fsdp=False):
    return MeshConfig(make_mesh({"data": 2, "model": 2}), fsdp=fsdp)


# --------------------------------------------------------------------------
# satellite: the divisibility fallback paths in parallel/sharding.py now
# RECORD (layer, dim, axis, reason) instead of silently returning P()
# --------------------------------------------------------------------------
class TestFallbackRecording:
    def test_model_axis_non_dividing_records(self):
        mc = mc22()
        assert sharding.param_spec((64, 7), mc, ("l00_dense",
                                                 "weights")) == P()
        (fb,) = mc.sharding_fallbacks
        assert fb["layer"] == "l00_dense" and fb["param"] == "weights"
        assert fb["dim"] == 1 and fb["axis"] == "model"
        assert "not divisible" in fb["reason"]
        assert fb["shape"] == (64, 7)

    def test_dividing_dims_record_nothing(self):
        mc = mc22()
        assert sharding.param_spec((64, 32), mc) == P(None, "model")
        assert mc.sharding_fallbacks == []

    def test_fsdp_data_axis_non_dividing_records(self):
        mc = mc22(fsdp=True)
        # last dim shards over model; first dim 7 % data=2 falls back
        assert sharding.param_spec((7, 32), mc, ("l", "w")) == \
            P(None, "model")
        (fb,) = mc.sharding_fallbacks
        assert fb["axis"] == "data" and fb["dim"] == 0
        assert "fsdp" in fb["reason"]

    def test_fsdp_skip_when_model_axis_took_dim0(self):
        """1-D params: the model axis takes dim 0 (bias follows its
        weights), so fsdp cannot also shard it — recorded, not silent."""
        mc = mc22(fsdp=True)
        assert sharding.param_spec((32,), mc, ("l", "bias")) == \
            P("model")
        (fb,) = mc.sharding_fallbacks
        assert "already carries the model axis" in fb["reason"]
        # still sharded on the model axis — informational, NOT a
        # silent replication (VS201 reports it as info severity)
        assert fb["replicated"] is False

    def test_override_longer_than_shape_records(self):
        mc = mc22()
        assert sharding._safe_spec((8,), P(None, "model"), mc,
                                   ("l", "w")) == P()
        (fb,) = mc.sharding_fallbacks
        assert "names 2 dims" in fb["reason"]

    def test_override_non_dividing_axis_records(self):
        mc = mc22()
        assert sharding._safe_spec((8, 9), P(None, "model"), mc,
                                   ("l", "w")) == P()
        (fb,) = mc.sharding_fallbacks
        assert fb["dim"] == 1 and fb["axis"] == "model"

    def test_shard_params_plumbs_layer_and_param_names(self):
        mc = mc22()
        params = {"l03_dense": {"weights": np.zeros((64, 7),
                                                    np.float32)}}
        sharding.shard_params(params, mc)
        (fb,) = mc.sharding_fallbacks
        assert fb["layer"] == "l03_dense" and fb["param"] == "weights"

    def test_optimizer_slots_dedupe_to_one_record(self):
        """slot1/l/w and slot2/l/w are the SAME fallback as l/w — the
        slot prefix is stripped and the entry deduplicated."""
        mc = mc22()
        params = {"l00": {"w": np.zeros((64, 7), np.float32)}}
        sharding.shard_params(params, mc)
        sharding.shard_params({"slot1": params, "slot2": params}, mc)
        assert len(mc.sharding_fallbacks) == 1

    def test_clear_fallbacks(self):
        mc = mc22()
        sharding.param_spec((64, 7), mc)
        mc.clear_fallbacks()
        assert mc.sharding_fallbacks == []


# --------------------------------------------------------------------------
# parsers / estimators
# --------------------------------------------------------------------------
class TestCollectiveStats:
    HLO = """
  %ar = f32[128,256]{1,0} all-reduce(f32[128,256]{1,0} %dot), to_apply=%add
  %ag.1 = bf16[64,64]{1,0} all-gather(bf16[32,64]{1,0} %p0), dimensions={0}
  %rs = f32[16]{0} reduce-scatter(f32[32]{0} %x), dimensions={0}
  %use = f32[128,256]{1,0} fusion(f32[128,256]{1,0} %ar), kind=kLoop
"""

    def test_counts_and_bytes(self):
        stats = collective_stats(self.HLO)
        assert stats["all-reduce"] == {"count": 1,
                                       "bytes": 128 * 256 * 4}
        assert stats["all-gather"] == {"count": 1,
                                       "bytes": 64 * 64 * 2}
        assert stats["reduce-scatter"] == {"count": 1, "bytes": 16 * 4}

    def test_operand_references_not_double_counted(self):
        """A later instruction consuming %ar must not count again."""
        assert collective_stats(self.HLO)["all-reduce"]["count"] == 1

    def test_async_start_counts_result_shape_only(self):
        """Async def lines carry an (operand, result) tuple shape — only
        the result token is traffic; -done carries no new bytes."""
        hlo = """
  %ags = (f32[32,64]{1,0}, f32[64,64]{1,0}) all-gather-start(f32[32,64]{1,0} %p0)
  %agd = f32[64,64]{1,0} all-gather-done((f32[32,64]{1,0}, f32[64,64]{1,0}) %ags)
"""
        stats = collective_stats(hlo)
        assert stats["all-gather"] == {"count": 1,
                                       "bytes": 64 * 64 * 4}


class TestActivationHighwater:
    def test_chain_peaks_at_live_intermediates(self):
        def f(x):
            y = x * 2.0        # intermediate: live until z
            z = y + 1.0        # jaxpr output: excluded
            return z

        closed = jax.make_jaxpr(f)(
            jax.ShapeDtypeStruct((1024,), jnp.float32))
        assert activation_highwater(closed.jaxpr) == 1024 * 4

    def test_recurses_into_pjit(self):
        def f(x):
            y = x * 2.0
            return (y * y).sum()

        closed = jax.make_jaxpr(jax.jit(f))(
            jax.ShapeDtypeStruct((1024,), jnp.float32))
        assert activation_highwater(closed.jaxpr) >= 1024 * 4


# --------------------------------------------------------------------------
# seeded defects: one synthetic broken step per rule, audited from pure
# ShapeDtypeStructs — nothing to execute even by accident
# --------------------------------------------------------------------------
def synth_spec(mc, dtype=jnp.float32, donate=(0,), upcast=False,
               n=256, mb=8):
    """A DP-shaped train step with REPLICATED params (the seeded VS200/
    VS202 defect: gradients psum at full parameter size every step)."""
    repl = NamedSharding(mc.mesh, P())
    batch_sh = NamedSharding(mc.mesh, P("data"))
    params = {"w": jax.ShapeDtypeStruct((n, n), dtype, sharding=repl)}
    x = jax.ShapeDtypeStruct((mb, n), jnp.float32, sharding=batch_sh)

    def step(p, xx):
        w = p["w"]
        if upcast:
            w = w.astype(jnp.float32)
        loss = lambda q: (xx @ (q["w"].astype(jnp.float32)
                                if upcast else q["w"])).sum()  # noqa: E731
        g = jax.grad(loss)(p)
        return {"w": (p["w"] - 0.01 * g["w"].astype(p["w"].dtype))}

    fn = jax.jit(step, donate_argnums=donate,
                 out_shardings={"w": repl})
    return {"fn": fn, "args": (params, x), "mesh_config": mc,
            "donate_argnums": donate, "carry_argnums": (0,),
            "params_argnums": (0,), "opt_argnums": (),
            "minibatch_bytes": mb * n * 4, "name": "synth.step"}


class TestSeededDefects:
    def test_vs200_full_param_psum_exceeds_minibatch(self):
        fs = audit_sharded_step(synth_spec(mc22()))
        hits = by_rule(fs, "VS200")
        assert hits and hits[0].severity == "warning"
        assert "ICI" in hits[0].message

    def test_vs201_reports_recorded_fallback(self):
        mc = mc22()
        sharding.param_spec((64, 7), mc, ("l00_dense", "weights"))
        fs = audit_sharded_step(synth_spec(mc))
        hits = by_rule(fs, "VS201")
        assert hits and "l00_dense.weights" in hits[0].message
        assert "not divisible" in hits[0].message

    def test_vs202_fsdp_psum_instead_of_reduce_scatter(self):
        """Replicated params under fsdp=True: gradients all-reduce at
        full parameter size with no reduce-scatter — ZeRO-3's memory
        win silently lost."""
        fs = audit_sharded_step(synth_spec(mc22(fsdp=True)))
        hits = by_rule(fs, "VS202")
        assert hits and "reduce-scatter" in hits[0].message

    def test_vs202_silent_on_proper_fsdp_trainer(self):
        """The real StagedTrainer under fsdp shards params properly and
        pins the update's out_shardings — GSPMD scatters the gradient
        reduction and VS202 stays silent (the positive case above only
        fires on the seeded replicated-params defect).  The routine
        bias fsdp-skip records surface as info-severity VS201, so a
        clean fsdp config has no VS201 warnings either (the --fail-on
        warning CI gate passes)."""
        pytest.importorskip("sklearn")
        wf = build_digits_wf(mc22(fsdp=True), hidden=64,
                             name="digits-fsdp-clean")
        fs = lint_workflow(wf)
        assert "VS202" not in rules(fs)
        vs201 = by_rule(fs, "VS201")
        assert vs201   # the bias skips ARE reported...
        assert all(f.severity == "info" for f in vs201)  # ...as info

    def test_vs203_bf16_param_upcast_in_step(self):
        fs = audit_sharded_step(synth_spec(mc22(), dtype=jnp.bfloat16,
                                           upcast=True))
        hits = by_rule(fs, "VS203")
        assert hits and "upcast to f32" in hits[0].message

    def test_vs203_silent_without_upcast(self):
        fs = audit_sharded_step(synth_spec(mc22()))
        assert "VS203" not in rules(fs)

    def test_vm301_missing_donation(self):
        fs = audit_sharded_step(synth_spec(mc22(), donate=()))
        hits = by_rule(fs, "VM301")
        assert hits and "not donated" in hits[0].message

    def test_vm301_silent_when_donated(self):
        fs = audit_sharded_step(synth_spec(mc22()))
        assert "VM301" not in rules(fs)

    def test_vm300_predicts_oom_against_tiny_capacity(self):
        fs = audit_sharded_step(synth_spec(mc22()), hbm_gib=1e-5)
        hits = by_rule(fs, "VM300")
        assert hits and hits[0].severity == "error"
        assert "predicted OOM" in hits[0].message

    def test_vm300_info_estimate_always_reported(self):
        fs = audit_sharded_step(synth_spec(mc22()))
        hits = by_rule(fs, "VM300")
        assert hits and hits[0].severity == "info"
        assert "params" in hits[0].message

    def test_audit_is_purely_abstract_no_device_arrays(self):
        """The acceptance gate: the whole audit runs off
        ShapeDtypeStructs — no computation dispatched, no device array
        allocated."""
        import gc
        spec = synth_spec(mc22())
        for leaf in jax.tree_util.tree_leaves(spec["args"]):
            assert isinstance(leaf, jax.ShapeDtypeStruct)
        gc.collect()
        before = len(jax.live_arrays())
        fs = audit_sharded_step(spec)
        assert fs   # it did find things (VS200 + VM300 at least)
        # the audit allocates NOTHING (collection can only shrink it)
        assert len(jax.live_arrays()) <= before

    def test_untraceable_step_reports_vj100(self):
        spec = synth_spec(mc22())

        def bad(p, x):
            if float(x.sum()) > 0:   # concretizes a tracer
                return p
            return p

        spec["fn"] = bad
        fs = audit_sharded_step(spec)
        assert "VJ100" in rules(fs) and has_errors(fs)


# --------------------------------------------------------------------------
# the real StagedTrainer under a mesh: hook + lint_workflow + VM300
# accuracy against XLA's own buffer accounting
# --------------------------------------------------------------------------
def build_digits_wf(mc, hidden=64, name="digits-audit"):
    from sklearn.datasets import load_digits
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    prng.seed_all(7)
    d = load_digits()
    loader = FullBatchLoader(
        None, data=(d.data / 16.0).astype(np.float32),
        labels=d.target.astype(np.int32), minibatch_size=64,
        class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[{"type": "all2all_tanh", "output_sample_shape": hidden},
                {"type": "softmax", "output_sample_shape": 10}],
        loader=loader, decision_config={"max_epochs": 1},
        mesh_config=mc, name=name)
    wf.initialize()
    return wf


@pytest.fixture(scope="module")
def digits_wf():
    pytest.importorskip("sklearn")
    return build_digits_wf(mc22())


class TestEstimateAccounting:
    def test_aliased_args_count_once(self):
        """The autoencoder passes its dataset as BOTH data and targets —
        one physical buffer, counted once (review finding: a ~9 GiB
        dataset must not become a spurious 18 GiB predicted OOM)."""
        mc = mc22()
        repl = NamedSharding(mc.mesh, P())
        data = jax.ShapeDtypeStruct((1024, 64), jnp.float32,
                                    sharding=repl)

        def step(d, t):
            return (d - t).sum()

        spec = {"fn": jax.jit(step), "args": (data, data),
                "mesh_config": mc, "donate_argnums": (),
                "carry_argnums": (), "params_argnums": (),
                "opt_argnums": (), "minibatch_bytes": 0,
                "name": "alias.step"}
        est = estimate_peak_hbm(spec)
        one_copy = 1024 * 64 * 4
        assert est["other_args"] == one_copy
        distinct = jax.ShapeDtypeStruct((1024, 64), jnp.float32,
                                        sharding=repl)
        spec["args"] = (data, distinct)
        assert estimate_peak_hbm(spec)["other_args"] == 2 * one_copy

    def test_autoencoder_trainer_spec_shares_target_mirror(self):
        """StagedTrainer's hook preserves the data/targets aliasing in
        its abstract mirrors (same ShapeDtypeStruct object)."""
        pytest.importorskip("sklearn")
        from sklearn.datasets import load_digits
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        prng.seed_all(7)
        d = load_digits()
        loader = FullBatchLoader(
            None, data=(d.data / 16.0).astype(np.float32),
            labels=d.target.astype(np.int32), minibatch_size=64,
            class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[{"type": "all2all_tanh", "output_sample_shape": 32},
                    {"type": "all2all", "output_sample_shape": 64}],
            loss="mse", loader=loader,
            decision_config={"max_epochs": 1},
            mesh_config=mc22(), name="digits-ae")
        wf.initialize()
        spec = wf.trainer.lint_sharding_spec()
        assert spec["args"][4] is spec["args"][6]   # data IS targets

    def test_act_bytes_override_wins_over_heuristic(self):
        """The auditor feeds XLA's per-device temp bytes in as the
        activation term (exact, includes replicated DP gradients the
        //data_size heuristic undercounts)."""
        spec = synth_spec(mc22())
        est_h = estimate_peak_hbm(spec)
        est_o = estimate_peak_hbm(spec, act_bytes=12345)
        assert est_o["activations"] == 12345
        assert est_o["peak"] - est_h["peak"] == \
            12345 - est_h["activations"]


class TestStagedTrainerAudit:
    def test_hook_exposes_sharded_spec(self, digits_wf):
        spec = digits_wf.trainer.lint_sharding_spec()
        assert spec is not None
        # params, velocity, class-stat acc, sentinel health
        assert spec["carry_argnums"] == (0, 1, 2, 3)
        assert spec["donate_argnums"] == (0, 1, 2, 3)
        assert spec["minibatch_bytes"] > 0
        for leaf in jax.tree_util.tree_leaves(spec["args"]):
            assert isinstance(leaf, jax.ShapeDtypeStruct)

    def test_staging_hook_defers_to_sharding_hook_under_mesh(self,
                                                            digits_wf):
        assert digits_wf.trainer.lint_staging_spec() is None
        assert digits_wf.trainer.lint_sharding_spec() is not None

    def test_lint_workflow_reports_vm300_no_dispatch(self, digits_wf):
        import gc
        gc.collect()   # flush earlier tests' dead workflows first
        before = len(jax.live_arrays())
        fs = lint_workflow(digits_wf)
        # the audit allocates NOTHING (collection can only shrink it)
        assert len(jax.live_arrays()) <= before
        assert by_rule(fs, "VM300")
        assert not has_errors(fs)

    def test_vm300_estimate_within_2x_of_xla_accounting(self, digits_wf):
        """Acceptance gate: the params+opt+activation estimate lands
        within 2x of XLA's own per-device buffer stats for the compiled
        step (argument + output + temp - aliased)."""
        spec = digits_wf.trainer.lint_sharding_spec()
        est = estimate_peak_hbm(spec)
        ma = spec["fn"].lower(*spec["args"]).compile().memory_analysis()
        measured = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                    + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
        assert measured > 0
        ratio = est["peak"] / measured
        assert 0.5 <= ratio <= 2.0, (est, measured)

    def test_fallbacks_surface_through_lint_workflow(self):
        """A layer whose output dim doesn't divide the model axis is
        reported by name through the full lint pipeline (the seeded
        VS201 defect on a real workflow)."""
        pytest.importorskip("sklearn")
        # 63 % model=2 != 0 — the hidden layer's sharding falls back
        wf = build_digits_wf(mc22(), hidden=63, name="digits-fallback")
        fs = lint_workflow(wf)
        hits = by_rule(fs, "VS201")
        assert hits and any("l00_all2all_tanh" in f.message
                            for f in hits)


# --------------------------------------------------------------------------
# CLI surfaces
# --------------------------------------------------------------------------
class TestCLI:
    def test_parse_mesh_dxm(self):
        from veles_tpu.analysis.cli import parse_mesh
        assert parse_mesh("2x2") == {"data": 2, "model": 2}
        assert parse_mesh("4X1") == {"data": 4, "model": 1}
        assert parse_mesh("data=4,model=2") == {"data": 4, "model": 2}
        with pytest.raises(SystemExit):
            parse_mesh("2x2x2")
        with pytest.raises(SystemExit):
            parse_mesh("axb")

    def test_fsdp_without_mesh_is_usage_error(self, tmp_path):
        from veles_tpu.analysis.cli import main
        wf = tmp_path / "wf.py"
        wf.write_text("def run(load, main):\n    pass\n")
        with pytest.raises(SystemExit):
            main([str(wf), "--fsdp"])

    def test_mesh_lint_reports_sharding_findings(self, capsys):
        """Acceptance gate: `veles-tpu-lint --mesh 2x2` on a sample
        workflow reports VS2xx/VM3xx findings and exits 0 (warnings
        don't fail by default)."""
        pytest.importorskip("sklearn")
        import os
        from veles_tpu.analysis.cli import main
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        rc = main([os.path.join(repo, "samples", "digits_mlp.py"),
                   os.path.join(repo, "samples", "digits_config.py"),
                   "--mesh", "2x2"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "VM300" in out

    def test_main_cli_lint_composes_with_mesh(self, capsys,
                                              monkeypatch):
        """`python -m veles_tpu WF CFG --lint --mesh data=2,model=2`:
        the lint path initializes under the virtual CPU mesh and the
        sharding findings ride the normal --lint exit semantics."""
        pytest.importorskip("sklearn")
        import os
        monkeypatch.setenv("VELES_COMPILE_CACHE", "off")
        from veles_tpu.__main__ import Main
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        m = Main(argv=[os.path.join(repo, "samples", "digits_mlp.py"),
                       os.path.join(repo, "samples",
                                    "digits_config.py"),
                       "--lint", "--mesh", "data=2,model=2"])
        rc = m.run()
        out = capsys.readouterr().out
        assert rc == 0
        assert "VM300" in out
        assert m.workflow._initialized   # mesh lint initializes...
        # ...but the trainer never stepped
        assert m.workflow.trainer._step_counter == 0

    def test_fail_on_warning_gates(self, capsys):
        """--fail-on warning turns the sample's VS200 warning into a
        non-zero exit; the default (error) does not."""
        pytest.importorskip("sklearn")
        import os
        from veles_tpu.analysis.cli import main
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        argv = [os.path.join(repo, "samples", "digits_mlp.py"),
                os.path.join(repo, "samples", "digits_config.py"),
                "--mesh", "2x2", "--fail-on", "warning"]
        rc = main(argv)
        out = capsys.readouterr().out
        assert "warning" in out
        assert rc == 1
