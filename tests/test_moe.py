"""MoE feed-forward: routing invariants, expert-parallel all_to_all path
vs single-device, gradients, and the trainable MoE transformer."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from veles_tpu.ops import moe  # noqa: E402
from veles_tpu.parallel.mesh import make_mesh  # noqa: E402


class _Rng:
    def __init__(self, seed):
        self.r = np.random.RandomState(seed)

    def normal(self, mean, std, shape):
        return self.r.normal(mean, std, shape)


def _setup(b=8, t=4, d=16, d_ff=32, e=8, seed=0):
    params = moe.moe_init(_Rng(seed), d, d_ff, e)
    x = jnp.asarray(np.random.RandomState(seed + 1).randn(b, t, d)
                    .astype(np.float32))
    return params, x


class TestRouting:
    def test_dispatch_one_slot_per_choice(self):
        params, x = _setup()
        x2d = np.asarray(x).reshape(-1, 16)
        dispatch, combine, aux = moe._routing(
            jnp.asarray(x2d), params["router"], 8, capacity=16, top_k=2)
        d = np.asarray(dispatch)
        # each token occupies exactly top_k slots (capacity not exceeded)
        assert (d.sum(axis=(1, 2)) == 2).all()
        # no slot is used twice
        assert (d.sum(axis=0) <= 1).all()
        assert float(aux) > 0

    def test_capacity_drops_overflow(self):
        params, x = _setup()
        x2d = jnp.asarray(np.asarray(x).reshape(-1, 16))
        dispatch, _, _ = moe._routing(x2d, params["router"], 8,
                                      capacity=1, top_k=1)
        assert (np.asarray(dispatch).sum(axis=0) <= 1).all()


class TestExpertParallel:
    def test_sharded_matches_single_device(self):
        params, x = _setup()
        # capacity_factor high enough that NO token is dropped on either
        # path: slot positions then differ but per-token outputs agree
        y_ref, aux_ref = moe.moe_forward(params, x, top_k=2,
                                         capacity_factor=8.0)
        mesh = make_mesh({"expert": 8})
        y_sh, aux_sh = moe.moe_forward_sharded(params, x, mesh, top_k=2,
                                               capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(y_sh), np.asarray(y_ref),
                                   rtol=2e-4, atol=2e-4)

    def test_rejects_indivisible_batch_and_experts(self):
        params, x = _setup()
        mesh = make_mesh({"expert": 8})
        with pytest.raises(ValueError, match="batch"):
            moe.moe_forward_sharded(params, x[:6], mesh, top_k=2)
        bad = dict(params)
        for k in ("w1", "b1", "w2", "b2"):
            bad[k] = jnp.concatenate([params[k], params[k][:1]], axis=0)
        bad["router"] = jnp.concatenate(
            [params["router"], params["router"][:, :1]], axis=1)
        with pytest.raises(ValueError, match="n_experts"):
            moe.moe_forward_sharded(bad, x, mesh, top_k=2)

    def test_gradients_flow_through_sharded_path(self):
        params, x = _setup()
        mesh = make_mesh({"expert": 8})

        def loss(p):
            y, aux = moe.moe_forward_sharded(p, x, mesh, top_k=2)
            return jnp.sum(y ** 2) + 0.01 * aux

        g = jax.jit(jax.grad(loss))(params)
        for k in ("router", "w1", "w2"):
            assert bool(jnp.isfinite(g[k]).all()), k
        assert float(jnp.abs(g["w1"]).max()) > 0


class TestMoETraining:
    def _train(self, mesh_axes=None, epochs=2):
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        from veles_tpu.models.zoo import transformer_classifier
        from veles_tpu.parallel import MeshConfig, make_mesh
        prng.seed_all(44)
        n = 16
        x = np.random.RandomState(0).rand(2 * n, 8, 4).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 3, 2 * n).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=8,
                                 class_lengths=[0, n, n])
        mc = MeshConfig(make_mesh(mesh_axes)) if mesh_axes else None
        wf = StandardWorkflow(
            layers=transformer_classifier(n_classes=3, d_model=16,
                                          n_heads=4, n_layers=1,
                                          dropout=0.0, n_experts=8,
                                          lr=0.01),
            loader=loader, decision_config={"max_epochs": epochs},
            mesh_config=mc, name="moe-train")
        wf.initialize()
        wf.run()
        return wf

    def test_moe_transformer_trains_single_device(self):
        wf = self._train()
        res = wf.gather_results()
        assert res["epochs"] == 2 and res["best_metric"] is not None

    def test_moe_transformer_trains_expert_parallel(self):
        wf = self._train({"data": 1, "expert": 8})
        res = wf.gather_results()
        assert res["epochs"] == 2 and res["best_metric"] is not None

    def test_standalone_moe_layer_in_stack(self):
        from veles_tpu.models.layers import make_layer
        layer = make_layer({"type": "moe", "n_experts": 4, "d_ff": 32,
                            "top_k": 1})
        assert layer.setup((8, 16)) == (8, 16)
        params = layer.init_params(_Rng(3))
        x = jnp.asarray(np.random.RandomState(2).randn(2, 8, 16)
                        .astype(np.float32))
        y = layer.apply(params, x)
        assert y.shape == (2, 8, 16)
        assert layer.last_aux is not None
