"""LoRA end-to-end serving (r4 verdict #8): an int8-quantized base with
f32 rank-r adapters serves over REST through the continuous-batching
engine, adapters ship as a tiny standalone package with sha256 base
lineage, and merge-at-export folds them away for zero-overhead serving.

Slow-tier (conftest.SLOW_MODULES): two small LM trainings (~40 s on
the 1-core box) — the budget cost is documented there."""

import json
import urllib.request

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.generate import LMGenerator
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.services.export import (apply_lora_adapters,
                                       export_lora_adapters,
                                       export_workflow,
                                       load_lora_adapters,
                                       merge_lora_params)

N, T, VOCAB = 128, 12, 11


def _tokens(shift):
    """The +shift ramp task: next token = (cur + shift) %% VOCAB."""
    r = np.random.RandomState(3)
    return ((np.arange(T)[None, :] * shift + r.randint(0, 3, N)[:, None])
            % VOCAB).astype(np.int32)


def _train(layers, toks, name, epochs=8, warm=None):
    prng.seed_all(23)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=32,
                             class_lengths=[0, 32, 96])
    wf = StandardWorkflow(layers=layers, loader=loader, loss="lm",
                          decision_config={"max_epochs": epochs},
                          name=name)
    wf.initialize()
    if warm is not None:
        n_restored, _ = wf.warm_start({"params": warm})
        assert n_restored > 0
    wf.run()
    return wf


@pytest.fixture(scope="module")
def adapted():
    """Base LM trained on the +1 ramp, then rank-2 q/v adapters
    fine-tuned on the +2 ramp with the base frozen (the r4 CLI drill,
    in-process)."""
    base = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                     n_heads=2, n_layers=1, lr=5e-3,
                                     dropout=0.0),
                  _tokens(1), "lora-base")
    base_host = base.trainer.host_params()
    wf = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                   n_heads=2, n_layers=1, lr=5e-2,
                                   dropout=0.0, lora_rank=2),
                _tokens(2), "lora-adapted", warm=base_host)
    return base, wf


def test_int8_base_f32_adapters_over_rest(adapted):
    """The quant allowlist passes the lora subtree through — proven
    over REST: the continuous engine serves the int8-base adapted
    model, output == the float adapted generator's continuation and
    != the base model's (it learned a different task)."""
    from veles_tpu.ops.quant import QuantWeight
    from veles_tpu.services.restful import RESTfulAPI

    base, wf = adapted
    gen_q = LMGenerator(wf.trainer, max_len=T, weights="int8")
    block = next(k for k in gen_q.params if "transformer" in k)
    assert isinstance(gen_q.params[block]["mha"]["wq"], QuantWeight)
    lora = gen_q.params[block]["mha"]["lora"]
    assert not isinstance(lora["qa"], QuantWeight)  # adapters stay f32

    gen_f = LMGenerator(wf.trainer, max_len=T)
    gen_b = LMGenerator(base.trainer, max_len=T)
    prompt = _tokens(2)[0, :6]
    api = RESTfulAPI(lambda x: x, (T,), port=0, generator=gen_q,
                     continuous_slots=2)
    api.start()
    try:
        req = urllib.request.Request(
            "http://127.0.0.1:%d/service" % api.port,
            json.dumps({"input": prompt.tolist(),
                        "generate": {"max_new": 4}}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req).read())["result"]
    finally:
        api.stop()
    want = gen_f.generate(prompt[None], max_new=4)[0].tolist()
    base_out = gen_b.generate(prompt[None], max_new=4)[0].tolist()
    assert out[0] == want                     # int8+adapters == float
    assert out[0][6:] != base_out[6:]         # adapters changed the task


def test_adapter_package_roundtrip_lineage_and_size(adapted, tmp_path):
    import os

    base, wf = adapted
    ap = str(tmp_path / "adapters.zip")
    meta = export_lora_adapters(wf, ap)
    assert meta["kind"] == "lora_adapters" and meta["layers"]
    full = str(tmp_path / "full.zip")
    export_workflow(wf, full)
    assert os.path.getsize(ap) < os.path.getsize(full) / 4

    tree, meta2 = load_lora_adapters(ap)
    assert meta2["base_sha256"] == meta["base_sha256"]
    blk = next(iter(tree))
    got = tree[blk]["mha"]["lora"]["qb"]
    want = np.asarray(
        wf.trainer.host_params()[blk]["mha"]["lora"]["qb"])
    np.testing.assert_array_equal(got, want)
    assert np.abs(want).max() > 0             # the adapters DID train

    # graft onto a fresh same-base model: outputs == the adapted model
    fresh = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                      n_heads=2, n_layers=1,
                                      dropout=0.0, lora_rank=2),
                   _tokens(2), "lora-fresh", epochs=1,
                   warm=base.trainer.host_params())
    # un-train fresh's own adapter attempt back to the base weights
    fresh.warm_start({"params": base.trainer.host_params()})
    with pytest.raises(ValueError, match="different base"):
        # fresh's 1-epoch run nudged nothing base (frozen) — but ITS
        # sha is computed over base leaves, which warm_start restored;
        # the strict check must still reject a truly different base:
        other = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                          n_heads=2, n_layers=1,
                                          dropout=0.0, lora_rank=2),
                       _tokens(1), "lora-other", epochs=1)
        apply_lora_adapters(other, ap)
    meta3 = apply_lora_adapters(fresh, ap)    # same base: accepted
    assert meta3["base_sha256"] == meta["base_sha256"]
    gen_g = LMGenerator(fresh.trainer, max_len=T)
    gen_f = LMGenerator(wf.trainer, max_len=T)
    prompt = _tokens(2)[1, :6]
    np.testing.assert_array_equal(
        gen_g.generate(prompt[None], max_new=4),
        gen_f.generate(prompt[None], max_new=4))


def test_merge_at_export_drops_adapters_exactly(adapted):
    """W + A·B folded into the base: merged rank-0 model == adapted
    model.  Exact in f32 numpy (x·W + (x·A)·B == x·(W + A·B)); the
    live bf16 forwards agree to bf16 rounding with identical argmax."""
    _, wf = adapted
    host = wf.trainer.host_params()
    merged = merge_lora_params(host)
    blk = next(k for k in merged
               if isinstance(merged[k], dict) and "mha" in merged[k])
    assert "lora" not in merged[blk]["mha"]
    # algebraic exactness in f32 numpy at the projection level
    x = np.random.RandomState(0).randn(5, 16).astype(np.float32)
    lora = host[blk]["mha"]["lora"]
    adapted_q = x @ np.asarray(host[blk]["mha"]["wq"], np.float32) \
        + (x @ np.asarray(lora["qa"], np.float32)) \
        @ np.asarray(lora["qb"], np.float32)
    merged_q = x @ np.asarray(merged[blk]["mha"]["wq"], np.float32)
    np.testing.assert_allclose(merged_q, adapted_q, rtol=1e-5,
                               atol=1e-6)
    # end-to-end through the live (bf16-policy) forward
    plain = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                      n_heads=2, n_layers=1,
                                      dropout=0.0),
                   _tokens(2), "lora-merged", epochs=1)
    plain.trainer.load_params(merged)
    toks = _tokens(2)[:4]
    out_m = np.asarray(plain.forward_fn()(plain.trainer.params, toks))
    out_a = np.asarray(wf.forward_fn()(wf.trainer.params, toks))
    np.testing.assert_allclose(out_m, out_a, rtol=5e-2, atol=5e-2)
    np.testing.assert_array_equal(out_m.argmax(-1), out_a.argmax(-1))


def test_serve_time_adapter_loading_via_config(adapted, tmp_path):
    """root.common.serve.lora_adapters=PATH: the serve path grafts an
    adapters package onto the (base) workflow before the generator
    snapshots params — serving base checkpoint + MB-scale adapters
    reproduces the adapted model exactly."""
    from veles_tpu.__main__ import Main
    from veles_tpu.config import root

    base, wf = adapted
    ap = str(tmp_path / "serve_adapters.zip")
    export_lora_adapters(wf, ap)
    # a same-base workflow with FRESH (random) adapters, as a restart
    # from the base snapshot would produce
    fresh = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                      n_heads=2, n_layers=1,
                                      dropout=0.0, lora_rank=2),
                   _tokens(2), "serve-fresh", epochs=1,
                   warm=base.trainer.host_params())
    fresh.warm_start({"params": base.trainer.host_params()})
    prev = root.common.serve.get("lora_adapters", None)
    root.common.serve.lora_adapters = ap
    try:
        gen = Main._make_generator(fresh)
    finally:
        root.common.serve.lora_adapters = prev
    assert gen is not None
    want = LMGenerator(wf.trainer, max_len=T)
    prompt = _tokens(2)[2, :6]
    np.testing.assert_array_equal(
        gen.generate(prompt[None], max_new=4),
        want.generate(prompt[None], max_new=4))


# --------------------------------------------------------------------------
# Multi-LoRA serving: one slot pool, per-request adapter routing
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def two_adapters(adapted):
    """The module's base + its +2-ramp adapter, plus a SECOND adapter
    fine-tuned on the +3 ramp — three behaviors one pool must route."""
    base, wf2 = adapted
    base_host = base.trainer.host_params()
    wf3 = _train(zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                    n_heads=2, n_layers=1, lr=5e-2,
                                    dropout=0.0, lora_rank=2),
                 _tokens(3), "lora-adapted-3", warm=base_host)
    return base, wf2, wf3


def _bank_generator(base, adapters, max_len=12):
    from veles_tpu.models.generate import LMGenerator
    gen = LMGenerator(base.trainer, max_len=max_len)
    n = gen.load_adapter_bank([wf.trainer.host_params()
                               for wf in adapters])
    assert n == len(adapters)
    return gen


@pytest.mark.parametrize("mode", ["dense", "paged", "paged_gather"])
def test_pool_routes_adapters_per_request(two_adapters, mode,
                                          f32_precision):
    """One pool serving base + two adapters interleaved: every stream
    must equal the SOLO generation of its own model (base wf / adapted
    wf with the adapter's params) — adapter routing can neither leak
    across slots nor drift from single-model decoding."""
    from veles_tpu.models.generate import (ContinuousBatcher,
                                           LMGenerator,
                                           PagedContinuousBatcher)
    base, wf2, wf3 = two_adapters
    gen = _bank_generator(base, [wf2, wf3])
    if mode == "dense":
        cb = ContinuousBatcher(gen, slots=3)
    else:
        cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                    pool_tokens=48,
                                    fused=(mode == "paged"))
    prompt = _tokens(1)[0, :4].tolist()
    rids = [cb.submit(prompt, 6, adapter=a) for a in (0, 1, 2)]
    cb.run_all()
    solo = {0: LMGenerator(base.trainer, max_len=12),
            1: LMGenerator(wf2.trainer, max_len=12),
            2: LMGenerator(wf3.trainer, max_len=12)}
    for a, rid in zip((0, 1, 2), rids):
        want = solo[a].generate(
            np.asarray([prompt], np.int32), 6)[0].tolist()
        assert cb.pop_result(rid) == want, "adapter %d (%s)" % (a,
                                                                mode)
    # adapters genuinely distinct behaviors, or routing proved nothing
    outs = [solo[a].generate(np.asarray([prompt], np.int32),
                             6)[0].tolist() for a in (0, 1, 2)]
    assert len({tuple(o) for o in outs}) >= 2


def test_adapter_id_validation(two_adapters, f32_precision):
    from veles_tpu.models.generate import ContinuousBatcher, LMGenerator
    base, wf2, _ = two_adapters
    gen = _bank_generator(base, [wf2])
    cb = ContinuousBatcher(gen, slots=2)
    with pytest.raises(ValueError, match="outside the loaded bank"):
        cb.submit([1, 2], 4, adapter=2)
    bare = ContinuousBatcher(LMGenerator(base.trainer, max_len=12),
                             slots=2)
    with pytest.raises(ValueError, match="outside the loaded bank"):
        bare.submit([1, 2], 4, adapter=1)


def test_bank_rejects_single_lora_params(two_adapters, f32_precision):
    """A generator whose params already carry a live 'lora' subtree
    must not silently double-apply — banks demand explicit members."""
    from veles_tpu.models.generate import LMGenerator
    base, wf2, _ = two_adapters
    gen = LMGenerator(wf2.trainer, max_len=12)    # adapted params
    with pytest.raises(ValueError, match="single 'lora'"):
        gen.load_adapter_bank([wf2.trainer.host_params()])


def test_bank_load_is_atomic_on_bad_adapter(two_adapters,
                                            f32_precision):
    """A mid-list failure (adapter without lora) must leave params
    untouched — never a half-banked generator."""
    from veles_tpu.models.generate import LMGenerator
    base, wf2, _ = two_adapters
    gen = LMGenerator(base.trainer, max_len=12)
    bad = {k: v for k, v in base.trainer.host_params().items()}
    with pytest.raises(ValueError, match="no lora subtree"):
        gen.load_adapter_bank([wf2.trainer.host_params(), bad])
    assert not any("lora_bank" in gen.params[l.name].get("mha", {})
                   for l in gen._blocks)
    assert getattr(gen, "_n_adapters", 0) == 0


def test_engine_blocking_submit_routes_adapter(two_adapters,
                                               f32_precision):
    """ContinuousEngine.submit(..., adapter=k) must actually route —
    the silent-base-model regression."""
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.services.restful import ContinuousEngine
    base, wf2, _ = two_adapters
    gen = _bank_generator(base, [wf2])
    eng = ContinuousEngine(gen, slots=2)
    try:
        prompt = _tokens(1)[0, :4].tolist()
        got = list(map(int, eng.submit(prompt, 6, adapter=1)))
        want = LMGenerator(wf2.trainer, max_len=12).generate(
            np.asarray([prompt], np.int32), 6)[0].tolist()
        assert got == want
        with pytest.raises(ValueError, match="outside the loaded"):
            eng.submit(prompt, 6, adapter=9)
    finally:
        eng.stop()


def test_prefix_cache_keys_include_adapter(two_adapters,
                                           f32_precision):
    """Same prompt, different adapters -> different prefix K/V: the
    prefix cache must NOT share blocks across adapters, and each
    stream still matches its solo model."""
    from veles_tpu.models.generate import (LMGenerator,
                                           PagedContinuousBatcher)
    base, wf2, _ = two_adapters
    gen = _bank_generator(base, [wf2])
    cb = PagedContinuousBatcher(gen, slots=2, block=4, pool_tokens=48,
                                prefix_cache=True)
    prompt = _tokens(1)[0, :9].tolist()           # 2 shareable blocks
    free0 = cb.free_blocks()
    r0 = cb.submit(prompt, 3, adapter=0)
    r1 = cb.submit(prompt, 3, adapter=1)
    cb.tick()
    # 3 + 3 blocks (12 tokens each), ZERO shared across adapters
    assert free0 - cb.free_blocks() == 6
    cb.run_all()
    assert cb.pop_result(r0) == LMGenerator(
        base.trainer, max_len=12).generate(
            np.asarray([prompt], np.int32), 3)[0].tolist()
    assert cb.pop_result(r1) == LMGenerator(
        wf2.trainer, max_len=12).generate(
            np.asarray([prompt], np.int32), 3)[0].tolist()
    # and WITHIN one adapter sharing still works
    free1 = cb.free_blocks()
    r2 = cb.submit(prompt, 3, adapter=1)
    r3 = cb.submit(prompt, 3, adapter=1)
    cb.tick()
    assert free1 - cb.free_blocks() == 4          # 2 shared
    cb.run_all()
    assert cb.pop_result(r2) == cb.pop_result(r3)
