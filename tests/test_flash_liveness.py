"""Flash-kernel block-liveness helpers vs a dense reference mask
(satellite of the numerics-auditor PR): ``_block_live`` must never skip
a tile containing a valid (query, key) pair, and the shrunken
``_k_span``/``_q_span`` windows must cover every live block — across
odd sequence lengths, causal and sliding-window.  A liveness bug here
is silent wrong attention output, so the reference is the dense mask
itself, not another helper."""

import numpy as np
import pytest

from veles_tpu.ops.pallas.flash import (_block_live, _k_lo, _k_span,
                                        _q_lo, _q_span)


def dense_mask(t, causal, window):
    """valid[q, k] exactly as _masked_scores defines it (tq == tk)."""
    q = np.arange(t)[:, None]
    k = np.arange(t)[None, :]
    valid = np.ones((t, t), bool)
    if causal:
        valid &= q >= k
        if window is not None:
            valid &= (q - k) < window
    return valid


def padded_blocks(t, block):
    return -(-t // block)


GEOMETRIES = [
    # (t, block_q, block_k, causal, window)
    (17, 8, 8, False, None),
    (17, 8, 8, True, None),
    (33, 16, 8, True, None),
    (57, 16, 16, True, 9),
    (57, 8, 16, True, 17),
    (129, 32, 16, True, 32),
    (65, 16, 32, True, 8),
    (31, 32, 32, True, 5),     # single padded block
    # the tuner's split backward grids: dq and dkv run the SAME
    # helpers at their own (block_q, block_k) pairs, decoupled from
    # the forward — strongly asymmetric pairs over odd T must still
    # cover the band (a liveness bug here is a silently wrong dq/dkv)
    (67, 8, 64, True, None),   # dq-style: wide k per q tile
    (67, 64, 8, True, None),   # dkv-style: wide q per k tile
    (193, 16, 128, True, 24),
    (193, 128, 16, True, 24),
]


@pytest.mark.parametrize("t,block_q,block_k,causal,window", GEOMETRIES)
def test_block_live_covers_every_valid_tile(t, block_q, block_k,
                                            causal, window):
    """Soundness: any tile holding >= 1 valid in-range (q, k) cell must
    be live — a dead-but-needed tile silently zeroes attention."""
    valid = dense_mask(t, causal, window)
    nq, nk = padded_blocks(t, block_q), padded_blocks(t, block_k)
    for qi in range(nq):
        for ki in range(nk):
            tile = valid[qi * block_q:(qi + 1) * block_q,
                         ki * block_k:(ki + 1) * block_k]
            if tile.any():
                assert bool(_block_live(qi, ki, block_q, block_k,
                                        causal, window)), \
                    "tile (%d, %d) has valid cells but was skipped" \
                    % (qi, ki)


@pytest.mark.parametrize("t,block_q,block_k,causal,window", GEOMETRIES)
def test_dead_tiles_have_no_valid_cells(t, block_q, block_k, causal,
                                        window):
    """Precision on in-range tiles: a tile _block_live declares dead
    must contain zero valid cells (it is skipped entirely)."""
    valid = dense_mask(t, causal, window)
    nq, nk = padded_blocks(t, block_q), padded_blocks(t, block_k)
    for qi in range(nq):
        for ki in range(nk):
            if not bool(_block_live(qi, ki, block_q, block_k, causal,
                                    window)):
                tile = valid[qi * block_q:(qi + 1) * block_q,
                             ki * block_k:(ki + 1) * block_k]
                assert not tile.any(), \
                    "tile (%d, %d) was skipped but has valid cells" \
                    % (qi, ki)


@pytest.mark.parametrize("t,block_q,block_k,causal,window",
                         [g for g in GEOMETRIES if g[4] is not None])
def test_k_span_covers_live_blocks(t, block_q, block_k, causal,
                                   window):
    """The shrunken inner grid [k_lo, k_lo + span) must contain every
    k block with a valid cell for its q block — an undersized span
    drops contributions from in-window keys."""
    valid = dense_mask(t, causal, window)
    nq, nk = padded_blocks(t, block_q), padded_blocks(t, block_k)
    span = _k_span(block_q, block_k, window, nk)
    for qi in range(nq):
        lo = int(_k_lo(qi, block_q, block_k, window))
        live_ks = [ki for ki in range(nk)
                   if valid[qi * block_q:(qi + 1) * block_q,
                            ki * block_k:(ki + 1) * block_k].any()]
        for ki in live_ks:
            assert lo <= ki < lo + span, \
                "q block %d: live k block %d outside span [%d, %d)" \
                % (qi, ki, lo, lo + span)


@pytest.mark.parametrize("t,block_q,block_k,causal,window",
                         [g for g in GEOMETRIES if g[4] is not None])
def test_q_span_covers_live_blocks(t, block_q, block_k, causal,
                                   window):
    """dK/dV walks q blocks per k block: [q_lo, q_lo + span) must
    contain every q block attending to that k block."""
    valid = dense_mask(t, causal, window)
    nq, nk = padded_blocks(t, block_q), padded_blocks(t, block_k)
    span = _q_span(block_q, block_k, window, nq)
    for ki in range(nk):
        lo = int(_q_lo(ki, block_q, block_k))
        live_qs = [qi for qi in range(nq)
                   if valid[qi * block_q:(qi + 1) * block_q,
                            ki * block_k:(ki + 1) * block_k].any()]
        for qi in live_qs:
            assert lo <= qi < lo + span, \
                "k block %d: live q block %d outside span [%d, %d)" \
                % (ki, qi, lo, lo + span)


def test_non_causal_is_all_live():
    assert _block_live(0, 7, 8, 8, causal=False, window=None) is True
    assert _k_span(8, 8, None, 9) == 9
    assert _q_span(8, 8, None, 9) == 9
