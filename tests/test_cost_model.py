"""Offline roofline cost model (tools/cost_model.py): postdiction
tolerances vs the 2026-08-01 on-chip anchors, prediction coverage of the
bench JSON schema, and the pre-ranked knob ladders bench.py consumes.

The model exists so a short chip-uptime window confirms predictions
instead of exploring (the reference's autotune-DB idea, ref
veles/backends.py:672-731, lifted to the roofline level)."""

from tools import cost_model as cm


def test_anchor_self_consistency():
    """Calibrated constants must reproduce their own anchors within 5%
    (drift here means someone changed a constant without re-deriving)."""
    for name, pred, meas, ratio, kind in cm.postdiction_table():
        if kind == "anchor":
            assert 0.95 <= ratio <= 1.05, (name, pred, meas)


def test_postdiction_within_20pct():
    """The honest validation: rows NOT used for calibration postdict
    within the judge's ~20% band (2026-08-01 holdouts: lm-25M,
    lm-124M@T2048, beam, flash T=8192)."""
    post = [(n, r) for n, _, _, r, k in cm.postdiction_table()
            if k == "postdict"]
    assert len(post) >= 2
    for name, ratio in post:
        assert 0.8 <= ratio <= 1.2, (name, ratio)


def test_predictions_cover_bench_keys():
    """Every flagship key the verdict demands a live number for has a
    prediction riding alongside it."""
    p = cm.predictions_for_bench()
    for key in ("lm_large_mfu", "flash_ms_bf16", "flash_ms_bwd",
                "serve_ms_per_tok_int8", "gemm_precision_overhead_pct",
                "alexnet_samples_per_sec", "lm_mfu",
                "beam_ms_per_pos_t4096"):
        assert key in p and p[key] != 0, key


def test_lm_large_ladder_ranking_matches_bench_order():
    """The model must rank the dots-remat rung first — bench.py's
    ladder tries it first, so disagreement means the pre-decided
    uptime plan no longer follows the model."""
    ladder = cm.predict_lm_large_ladder()
    assert ladder[0]["remat"] == "dots" and ladder[0]["batch"] == 16
    mfus = [r["mfu"] for r in ladder]
    assert mfus == sorted(mfus, reverse=True)
    # full remat burns ~1/3 more step time for the same counted FLOPs
    assert ladder[0]["mfu"] > ladder[1]["mfu"] * 1.15


def test_flashtune_order_complete_and_big_blocks_first():
    order = cm.predict_flashtune_order()
    assert len(order) == 9 and len(set(order)) == 9
    assert order[0] == (512, 512)
    assert order[-1][1] == 128          # smallest k-blocks last


def test_flash_predicted_to_beat_xla():
    """The model predicts the Pallas kernel wins the head-to-head at
    both shapes; if the chip says otherwise the kernel loses its keep."""
    f = cm.predict_flash()
    assert f["ms_bf16"] < f["ms_bf16_xla"]
    assert f["ms_long_t8192"] < f["ms_long_t8192_xla"]
    assert f["ms_long_t8192_w1024"] < f["ms_long_t8192"]


def test_serve_int8_predicted_faster():
    s = cm.predict_serve()
    assert s["ms_per_tok_int8"] < s["ms_per_tok_bf16"]


def test_servecont_pool_speedup_band():
    """2026-08-01 on-chip anchors: dense pool x1.59, paged x1.26 at 8
    slots — the model must reproduce those and predict monotone
    (diminishing) gains in slot count."""
    s = cm.predict_servecont()
    assert 1.5 < s["pool_vs_solo"] < 1.7
    p = cm.predict_servecont(paged=True, fused=False)
    assert 1.15 < p["pool_vs_solo"] < 1.4
    # fused paged (pre-registered, no anchor yet): the gather tax is
    # the whole gap, so the prediction equals the dense tick
    f = cm.predict_servecont(paged=True, fused=True)
    assert f["pool_vs_solo"] == s["pool_vs_solo"]
    r4 = cm.predict_servecont(slots=4)["pool_vs_solo"]
    r16 = cm.predict_servecont(slots=16)["pool_vs_solo"]
    assert 1.0 < r4 < s["pool_vs_solo"] < r16


def test_pipeline_prediction_interleaving_wins():
    """Pre-registered multi-chip prediction: interleaved 1F1B beats
    plain on the 124M flagship, more so at larger S, and both stay
    above the zero-bubble bound."""
    for s, min_speedup in ((4, 1.02), (8, 1.08)):
        p = cm.predict_pipeline_lm_large(s=s)
        assert p["interleaved_speedup"] >= min_speedup, p
        assert p["bubble_interleaved"] < p["bubble_plain"]
        assert p["step_ms_interleaved"] > p["step_ms_zero_bubble_bound"]
