"""Attention stack: naive vs blockwise vs Pallas flash, and the two
sequence-parallel strategies (ring, Ulysses) on the virtual 8-device mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veles_tpu.ops import attention as att
from veles_tpu.parallel import ring
from veles_tpu.parallel.mesh import make_mesh


def _qkv(b=2, h=4, t=64, d=16, seed=0, dtype=np.float32):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(b, h, t, d).astype(dtype))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t", [64, 57])       # 57: exercises padding
def test_blockwise_matches_naive(causal, t):
    q, k, v = _qkv(t=t)
    ref = att.attention(q, k, v, causal=causal)
    out = att.blockwise_attention(q, k, v, causal=causal, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_matches_naive(causal):
    q, k, v = _qkv(t=128, d=32)
    ref = att.attention(q, k, v, causal=causal)
    out = att.flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_pallas_differentiable(causal):
    """flash impl must be trainable: grads match the naive reference."""
    q, k, v = _qkv(t=32, d=16)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v, causal=causal) ** 2)

    g_ref = jax.grad(loss(att.attention), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(lambda *a, **kw: att.flash_attention(
        *a, block_q=16, block_k=16, **kw)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_pallas_padding():
    q, k, v = _qkv(t=100, d=16)
    ref = att.attention(q, k, v)
    out = att.flash_attention(q, k, v, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal,t", [(False, 64), (True, 64),
                                      (False, 57), (True, 57)])
def test_flash_fused_backward_matches_naive(causal, t):
    """The Pallas dQ / dK-dV kernels (backward='fused', the default) must
    reproduce the naive reference gradients — incl. ragged T padding."""
    q, k, v = _qkv(t=t, d=16, seed=3)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 3)

    g_ref = jax.grad(loss(lambda q, k, v: att.attention(
        q, k, v, causal=causal)), argnums=(0, 1, 2))(q, k, v)
    g_fused = jax.grad(loss(lambda q, k, v: att.flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16,
        backward="fused")), argnums=(0, 1, 2))(q, k, v)
    g_rec = jax.grad(loss(lambda q, k, v: att.flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16,
        backward="recompute")), argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg="d%s (vs naive)" % name)
    for name, a, b in zip("qkv", g_fused, g_rec):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg="d%s (vs recompute)" % name)


def test_flash_fused_backward_cross_attention():
    """tq != tk (non-causal cross attention) through the fused backward."""
    r = np.random.RandomState(9)
    q = jnp.asarray(r.randn(2, 2, 48, 16).astype(np.float32))
    k = jnp.asarray(r.randn(2, 2, 80, 16).astype(np.float32))
    v = jnp.asarray(r.randn(2, 2, 80, 16).astype(np.float32))

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(att.attention), argnums=(0, 1, 2))(q, k, v)
    g_fused = jax.grad(loss(lambda *a: att.flash_attention(
        *a, block_q=16, block_k=32)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_fused_backward_bf16():
    """bf16 storage dtype: fused grads stay within bf16 tolerance of the
    f32 naive reference and carry the input dtype."""
    q, k, v = _qkv(t=64, d=16, seed=4)
    q16, k16, v16 = (x.astype(jnp.bfloat16) for x in (q, k, v))

    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        att.attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g16 = jax.grad(lambda q, k, v: jnp.sum(
        att.flash_attention(q, k, v, causal=True, block_q=16,
                            block_k=16).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q16, k16, v16)
    for a, b in zip(g16, g_ref):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b), rtol=0.1, atol=0.15)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention(causal):
    mesh = make_mesh({"seq": 8})
    q, k, v = _qkv(b=1, h=2, t=64, d=8)
    ref = att.attention(q, k, v, causal=causal)
    out = ring.ring_attention_sharded(q, k, v, mesh, causal=causal,
                                      block_k=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_grad():
    mesh = make_mesh({"seq": 4})
    q, k, v = _qkv(b=1, h=2, t=32, d=8)

    def loss_ring(q):
        return jnp.sum(ring.ring_attention_sharded(
            q, k, v, mesh, causal=True, block_k=8) ** 2)

    def loss_ref(q):
        return jnp.sum(att.attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring)(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               rtol=1e-3, atol=1e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention(causal):
    mesh = make_mesh({"seq": 4})
    q, k, v = _qkv(b=1, h=8, t=64, d=8)
    ref = att.attention(q, k, v, causal=causal)
    out = ring.ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_mha_forward_and_grad():
    from veles_tpu import prng
    prng.seed_all(7)
    rng = prng.get("mha-test")
    d_model, n_heads = 32, 4
    params = att.mha_init(rng, d_model, n_heads)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 16, d_model)
                    .astype(np.float32))
    y = att.mha_forward(params, x, n_heads, causal=True)
    assert y.shape == x.shape
    y_naive = att.mha_forward(params, x, n_heads, causal=True, impl="naive")
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_naive),
                               rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda p: jnp.sum(
        att.mha_forward(p, x, n_heads, causal=True) ** 2))(params)
    assert jnp.all(jnp.isfinite(g["wq"]))


def test_flash_bf16_inputs_match_f32_reference():
    """Mixed precision: bf16 q/k/v multiply on the MXU at native rate
    while softmax stats and the output accumulator stay f32 — results
    must track the f32 reference within bf16 tolerance."""
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.attention import attention
    from veles_tpu.ops.pallas.flash import flash_attention

    key = jax.random.key(4)
    q, k, v = (jax.random.normal(kk, (2, 2, 256, 64), jnp.float32) * 0.3
               for kk in jax.random.split(key, 3))
    out = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16), causal=True)
    ref = attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    assert float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref))) < 0.05


def test_flash_mixed_dtypes_rejected():
    import jax
    import jax.numpy as jnp
    import pytest

    from veles_tpu.ops.pallas.flash import flash_attention
    key = jax.random.key(1)
    q, k, v = (jax.random.normal(kk, (1, 1, 64, 32), jnp.float32)
               for kk in jax.random.split(key, 3))
    with pytest.raises(ValueError, match="matching q/k/v dtypes"):
        flash_attention(q, k.astype(jnp.bfloat16), v)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_rope_with_sequence_parallel_mha(impl, f32_precision):
    """RoPE rotates the GLOBAL q/k before the seq-parallel shard_map, so
    ring/Ulysses attention under rope must match the single-device path."""
    from veles_tpu.models.layers import make_layer
    from veles_tpu import prng

    # seq=4: ulysses also needs n_heads (4) divisible by the axis size
    mesh = make_mesh({"seq": 4}, jax.devices()[:4])
    r = np.random.RandomState(11)
    x = jnp.asarray(r.randn(2, 16, 32).astype(np.float32))

    def out_for(impl_name, with_mesh):
        prng.seed_all(13)
        layer = make_layer({"type": "multihead_attention", "n_heads": 4,
                            "causal": True, "rope": True,
                            "impl": impl_name})
        layer.setup((16, 32))
        if with_mesh:
            layer.mesh = mesh
        params = layer.init_params(prng.get("w"))
        return np.asarray(layer.apply(params, x))

    got = out_for(impl, True)
    want = out_for("blockwise", False)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("t,w", [(64, 16), (64, 3), (57, 16),
                                 # shrunken-grid edges: window spanning
                                 # several blocks, window > t (span
                                 # clamps to nk), window == block, and
                                 # a window that overshoots past the
                                 # last k block on tail q blocks
                                 (128, 40), (64, 100), (64, 32),
                                 (96, 33)])
def test_flash_sliding_window(t, w):
    """Sliding-window causal flash: forward AND fused backward must
    match the masked naive reference (incl. ragged padding)."""
    q, k, v = _qkv(t=t, d=16, seed=6)

    ref = att.attention(q, k, v, causal=True, window=w)
    out = att.flash_attention(q, k, v, causal=True, window=w,
                              block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    g_ref = jax.grad(loss(lambda q, k, v: att.attention(
        q, k, v, causal=True, window=w)), argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(loss(lambda q, k, v: att.flash_attention(
        q, k, v, causal=True, window=w, block_q=16, block_k=16)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_window_validation():
    q, k, v = _qkv(t=32, d=16)
    with pytest.raises(ValueError, match="causal"):
        att.flash_attention(q, k, v, window=8)
    with pytest.raises(ValueError, match=">= 1"):
        att.flash_attention(q, k, v, causal=True, window=0)


def test_flash_block_sizes_from_site_config():
    """Site config sets the kernel's default tile sizes — a flashtune
    winner bakes in with no code edit.  d <= 64 resolves the *_d64
    keys (that regime fits — and wants — bigger blocks, measured
    2026-08-01); d > 64 resolves block_q/block_k as before."""
    from veles_tpu.config import root

    from veles_tpu.ops.pallas import flash as flash_mod

    q, k, v = _qkv(t=64, d=16)
    ref = att.attention(q, k, v, causal=True)
    root.common.engine.flash.block_q_d64 = 32
    root.common.engine.flash.block_k_d64 = 16
    # the d>64 keys must NOT leak into the small-d resolution
    root.common.engine.flash.block_q = 64
    root.common.engine.flash.block_k = 64
    flash_mod._flash_fn.cache_clear()
    try:
        out = att.flash_attention(q, k, v, causal=True, interpret=True)
        # the kernel really resolved the CONFIG sizes (the lru_cache
        # key holds the resolved block_q/block_k), via the public
        # wrapper
        assert flash_mod._flash_fn.cache_info().currsize == 1
        out2 = flash_mod.flash_attention(q, k, v, causal=True,
                                         block_q=32, block_k=16,
                                         interpret=True)
        # same (causal, scale, 32, 16, ...) signature -> cache HIT
        assert flash_mod._flash_fn.cache_info().currsize == 1
        np.testing.assert_allclose(np.asarray(out2), np.asarray(out),
                                   rtol=0, atol=0)
    finally:
        del root.common.engine.flash.block_q_d64
        del root.common.engine.flash.block_k_d64
        del root.common.engine.flash.block_q
        del root.common.engine.flash.block_k
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_small_d_defaults_cap_at_padded_t():
    """Unset *_d64 keys: the small-d default caps at min(1024,
    padded T), so a T=64 call resolves 128-sized blocks — the lru
    cache key it lands on must be the same one an explicit (128, 128)
    call hits, and the output matches the reference."""
    from veles_tpu.ops.pallas import flash as flash_mod

    q, k, v = _qkv(t=64, d=16)
    ref = att.attention(q, k, v, causal=True)
    flash_mod._flash_fn.cache_clear()
    out = att.flash_attention(q, k, v, causal=True, interpret=True)
    assert flash_mod._flash_fn.cache_info().currsize == 1
    flash_mod.flash_attention(q, k, v, causal=True, block_q=128,
                              block_k=128, interpret=True)
    assert flash_mod._flash_fn.cache_info().currsize == 1  # cache HIT
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_blockwise_window_validation():
    """blockwise_attention is a public entry point (the ring carry API) —
    window without causal must raise, not silently run full attention."""
    q, k, v = _qkv(t=32, d=16)
    with pytest.raises(ValueError, match="causal"):
        att.blockwise_attention(q, k, v, causal=False, window=8)


def test_mha_window_validated_for_all_impls():
    """window misconfigs must raise identically on every impl path."""
    from veles_tpu import prng
    prng.seed_all(3)
    params = att.mha_init(prng.get("w"), 16, 2)
    x = jnp.zeros((1, 8, 16), jnp.float32)
    for impl in ("blockwise", "naive", "flash"):
        with pytest.raises(ValueError, match="causal"):
            att.mha_forward(params, x, 2, causal=False, impl=impl,
                            window=4)
        with pytest.raises(ValueError, match=">= 1"):
            att.mha_forward(params, x, 2, causal=True, impl=impl,
                            window=0)


# --------------------------------------------------------------------------
# Paged-KV decode kernel (ops/pallas/paged.py)
# --------------------------------------------------------------------------

def _paged_setup(b=3, hkv=2, g=4, bs=16, nbm=4, hd=64, pool_blocks=None,
                 dtype=jnp.float32, seed=0):
    """Random pool + per-row tables with DISTINCT blocks per row (the
    batcher's allocation invariant) and staggered per-row lengths."""
    if pool_blocks is None:
        pool_blocks = b * nbm + 1
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(b, hkv * g, hd), dtype)
    pk = jnp.asarray(r.randn(1 + pool_blocks, hkv, bs, hd), dtype)
    pv = jnp.asarray(r.randn(1 + pool_blocks, hkv, bs, hd), dtype)
    ids = r.permutation(pool_blocks)[:b * nbm].reshape(b, nbm) + 1
    table = np.zeros((b, nbm), np.int32)
    # rows own a live prefix of blocks; dead entries stay 0 (dummy)
    pos = np.asarray([0, (nbm // 2) * bs + 3, nbm * bs - 1], np.int32)[:b]
    for i in range(b):
        live = pos[i] // bs + 1
        table[i, :live] = ids[i, :live]
    return q, pk, pv, jnp.asarray(table), jnp.asarray(pos)


@pytest.mark.parametrize("g,dtype,tol", [
    (1, jnp.float32, 2e-6), (4, jnp.float32, 2e-6),
    (4, jnp.bfloat16, 2e-2)])
def test_paged_decode_matches_reference(g, dtype, tol):
    from veles_tpu.ops.pallas.paged import (paged_attention_decode,
                                            paged_attention_reference)
    q, pk, pv, table, pos = _paged_setup(g=g, dtype=dtype)
    ref = paged_attention_reference(q, pk, pv, table, pos)
    out = paged_attention_decode(q, pk, pv, table, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=tol, atol=tol)


def test_paged_decode_reference_matches_dense_softmax():
    """The reference formulation itself against a hand-built dense
    masked softmax — pins the exact semantics (live = pos inclusive)."""
    from veles_tpu.ops.pallas.paged import paged_attention_reference
    q, pk, pv, table, pos = _paged_setup(b=2, g=1, bs=4, nbm=3, hd=8,
                                         pool_blocks=7)  # noqa: kept explicit
    b, hq, hd = q.shape
    out = np.asarray(paged_attention_reference(q, pk, pv, table, pos))
    for i in range(b):
        n = int(pos[i]) + 1
        ks, vs = [], []
        for t in range(n):
            blk, off = int(table[i, t // 4]), t % 4
            ks.append(np.asarray(pk)[blk, :, off])
            vs.append(np.asarray(pv)[blk, :, off])
        k = np.stack(ks, 1)                       # [hkv, n, hd]
        v = np.stack(vs, 1)
        s = np.einsum("hd,htd->ht", np.asarray(q)[i], k) * hd ** -0.5
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want = np.einsum("ht,htd->hd", p, v)
        np.testing.assert_allclose(out[i], want, rtol=2e-5, atol=2e-5)


def test_paged_decode_dead_blocks_cannot_leak():
    """Garbage in the dummy block and in allocated-but-beyond-pos
    blocks must not change the output (masking, not data layout, is
    what keeps dead keys out)."""
    from veles_tpu.ops.pallas.paged import paged_attention_decode
    q, pk, pv, table, pos = _paged_setup()
    base = np.asarray(paged_attention_decode(q, pk, pv, table, pos,
                                             interpret=True), np.float32)
    poison = jnp.full(pk.shape[1:], 1e4, pk.dtype)
    pk2 = pk.at[0].set(poison)                    # dummy block
    pv2 = pv.at[0].set(poison)
    # also poison a block allocated to row 1 beyond its position
    live1 = int(pos[1]) // pk.shape[2] + 1
    table2 = table.at[1, live1].set(int(table[2, 0]))
    out = np.asarray(paged_attention_decode(q, pk2, pv2, table2, pos,
                                            interpret=True), np.float32)
    np.testing.assert_allclose(out, base, rtol=1e-6, atol=1e-6)
