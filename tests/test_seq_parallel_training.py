"""Sequence-parallel attention (ring / Ulysses) inside the REAL training
path: a transformer classifier whose core attention runs sharded over the
mesh's ``seq`` axis, trained end-to-end on the 8-device virtual mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from veles_tpu import prng  # noqa: E402
from veles_tpu.loader.fullbatch import FullBatchLoader  # noqa: E402
from veles_tpu.models.standard_workflow import StandardWorkflow  # noqa: E402
from veles_tpu.models.zoo import transformer_classifier  # noqa: E402
from veles_tpu.parallel import MeshConfig, make_mesh  # noqa: E402


def _train(impl, mesh_axes, n_heads=8, seq_len=16, epochs=2):
    prng.seed_all(33)
    n = 16
    x = np.random.RandomState(0).rand(2 * n, seq_len, 4).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 3, 2 * n).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=8,
                             class_lengths=[0, n, n])
    mc = (MeshConfig(make_mesh(mesh_axes)) if mesh_axes else None)
    wf = StandardWorkflow(
        layers=transformer_classifier(n_classes=3, d_model=16,
                                      n_heads=n_heads, n_layers=1,
                                      dropout=0.0, impl=impl, lr=0.01),
        loader=loader, decision_config={"max_epochs": epochs},
        mesh_config=mc, name="sp-%s" % impl)
    wf.initialize()
    wf.run()
    return wf


@pytest.mark.parametrize("impl,axes", [
    ("ring", {"data": 1, "seq": 8}),
    ("ulysses", {"data": 1, "seq": 8}),
])
def test_seq_parallel_transformer_trains(impl, axes):
    wf = _train(impl, axes)
    res = wf.gather_results()
    assert res["epochs"] == 2
    assert res["best_metric"] is not None


def test_ring_matches_blockwise_training():
    """Same seed/model: sequence-parallel attention must not change the
    math — losses after one epoch agree with the single-device impl."""
    ref = _train("blockwise", None, epochs=1)
    rng = _train("ring", {"data": 1, "seq": 8}, epochs=1)
    a = ref.gather_results()["epoch_metrics"]["validation"]["loss"]
    b = rng.gather_results()["epoch_metrics"]["validation"]["loss"]
    assert a == pytest.approx(b, rel=1e-3)


def test_seq_parallel_without_mesh_raises():
    with pytest.raises(ValueError, match="seq"):
        _train("ring", None)
