"""Solver semantics (models.optimizer): each update rule against a
hand-computed single-step oracle, plus the adam-vs-adamw decoupling."""

import numpy as np
import pytest

import jax.numpy as jnp

from veles_tpu.models import optimizer


def _one_step(solver, w0, g, wd=0.0, lr=0.1, leaf="weights", **extra):
    params = {"l": {leaf: jnp.asarray(w0)}}
    grads = {"l": {leaf: jnp.asarray(g)}}
    state = optimizer.init_state(params)
    hyper = optimizer.resolve_hyper(
        dict({"solver": solver, "learning_rate": lr, "weights_decay": wd},
             **extra))
    params, state = optimizer.update(params, grads, state, {"l": hyper})
    return np.asarray(params["l"][leaf]), state


def test_gd_momentum_first_step():
    w, _ = _one_step("gd", [1.0, -2.0], [0.5, 0.5], wd=0.0)
    np.testing.assert_allclose(w, [1.0 - 0.05, -2.0 - 0.05], rtol=1e-6)


def test_adam_first_step_is_lr_sized():
    # bias correction makes |step| ~= lr regardless of gradient scale
    w, _ = _one_step("adam", [1.0], [1e-3])
    np.testing.assert_allclose(w, [1.0 - 0.1], rtol=1e-3)
    w2, _ = _one_step("adam", [1.0], [100.0])
    np.testing.assert_allclose(w2, [1.0 - 0.1], rtol=1e-3)


def test_adamw_decouples_decay():
    # zero gradient: adamw still decays the weight by lr*wd; adam's
    # coupled decay passes through the adaptive rescale instead
    w_adamw, _ = _one_step("adamw", [2.0], [0.0], wd=0.01)
    np.testing.assert_allclose(w_adamw, [2.0 - 0.1 * 0.01 * 2.0],
                               rtol=1e-5)
    # with gradient: adamw step = adam step (wd=0) + decay term
    w_adam0, _ = _one_step("adam", [2.0], [0.5], wd=0.0)
    w_w, _ = _one_step("adamw", [2.0], [0.5], wd=0.01)
    np.testing.assert_allclose(w_w, w_adam0 - 0.1 * 0.01 * 2.0, rtol=1e-5)


def test_adamw_exempts_bias_from_decay_by_default():
    # zero gradient on a BIAS leaf: adamw must not decay it
    b, _ = _one_step("adamw", [2.0], [0.0], wd=0.01, leaf="bias")
    np.testing.assert_allclose(b, [2.0], rtol=1e-7)
    # explicit weights_decay_bias opts back in
    b2, _ = _one_step("adamw", [2.0], [0.0], wd=0.01, leaf="bias",
                      weights_decay_bias=0.01)
    np.testing.assert_allclose(b2, [2.0 - 0.1 * 0.01 * 2.0], rtol=1e-5)


def test_unknown_solver_rejected():
    with pytest.raises(ValueError, match="unknown solver"):
        optimizer.resolve_hyper({"solver": "adamW"})


def test_adagrad_shrinks_with_history():
    w, state = _one_step("adagrad", [1.0], [1.0])
    np.testing.assert_allclose(w, [1.0 - 0.1 * 1.0 / (1.0 + 1e-8)],
                               rtol=1e-5)


def test_rprop_sign_steps():
    w, _ = _one_step("rprop", [1.0, 1.0], [0.3, -0.7])
    np.testing.assert_allclose(w, [1.0 - 0.1, 1.0 + 0.1], rtol=1e-6)


@pytest.mark.slow
def test_adamw_trains_transformer():
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models import zoo
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(51)
    r = np.random.RandomState(2)
    toks = ((np.arange(16)[None, :] * 3 + r.randint(0, 5, 192)[:, None])
            % 17).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 48, 144])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=17, d_model=32, n_heads=4,
                                  n_layers=1, lr=5e-3, solver="adamw"),
        loader=loader, loss="lm",
        gd_defaults={"weights_decay": 0.01},
        decision_config={"max_epochs": 15}, name="adamw-lm")
    wf.initialize()
    wf.run()
    assert wf.decision.best_metric < 0.15, wf.decision.best_metric


def test_newton_schulz_orthogonalizes():
    """ns(G) drives ALL singular values into a narrow band around 1
    (the quintic coefficients trade exact orthogonality for speed —
    they converge sv's into ~[0.7, 1.25], which is what Muon needs),
    incl. tall inputs (transposed path) and conv-shaped leaves
    (flattened to 2-D)."""
    r = np.random.RandomState(0)
    for shape in ((32, 48), (48, 32), (3, 3, 8, 16)):
        g = jnp.asarray(r.randn(*shape).astype(np.float32))
        u = np.asarray(optimizer.newton_schulz(g, steps=5))
        sv_in = np.linalg.svd(np.asarray(g).reshape(-1, shape[-1]),
                              compute_uv=False)
        sv = np.linalg.svd(u.reshape(-1, shape[-1]), compute_uv=False)
        assert sv_in.max() / sv_in.min() > 2          # input NOT flat
        assert sv.min() > 0.5 and sv.max() < 1.3, (shape, sv)
        assert sv.max() / sv.min() < 2, (shape, sv)   # spread collapsed


def test_muon_falls_back_to_adamw_for_tables_and_biases():
    # 1-D bias: identical to adamw (no decay by default, no NS)
    b_m, _ = _one_step("muon", [2.0], [0.5], wd=0.01, leaf="bias")
    b_w, _ = _one_step("adamw", [2.0], [0.5], wd=0.01, leaf="bias")
    np.testing.assert_allclose(b_m, b_w, rtol=1e-6)
    # embedding table (2-D, key 'table'): adamw rule, not NS
    t = np.ones((4, 8), np.float32)
    g = np.full((4, 8), 0.5, np.float32)
    t_m, _ = _one_step("muon", t, g, leaf="table")
    t_w, _ = _one_step("adamw", t, g, leaf="table")
    np.testing.assert_allclose(t_m, t_w, rtol=1e-6)
    # a weight matrix: NS path — update magnitude is lr-sized per
    # element and NOT the adamw update
    w = np.ones((8, 8), np.float32)
    w_m, _ = _one_step("muon", w, g.reshape(8, 4).repeat(2, 1))
    assert not np.allclose(
        w_m, _one_step("adamw", w, g.reshape(8, 4).repeat(2, 1))[0])
    # LM/classifier head layers take the adamw rule even for 2-D
    # weights (Muon recipe: hidden matrices only)
    gw = g.reshape(8, 4).repeat(2, 1)
    params = {"l05_timestep_dense": {"weights": jnp.asarray(w)}}
    grads = {"l05_timestep_dense": {"weights": jnp.asarray(gw)}}
    hy = {"l05_timestep_dense": optimizer.resolve_hyper(
        {"solver": "muon", "learning_rate": 0.1})}
    p_head, _ = optimizer.update(params, grads,
                                 optimizer.init_state(params), hy)
    w_aw, _ = _one_step("adamw", w, gw)
    np.testing.assert_allclose(
        np.asarray(p_head["l05_timestep_dense"]["weights"]), w_aw,
        rtol=1e-6)


def test_per_layer_solver_knobs_reach_the_optimizer():
    """The Layer.gd key set derives from optimizer.DEFAULTS — a
    solver-specific knob set on a LAYER config must not be silently
    dropped (the stale-whitelist bug class)."""
    from veles_tpu.models.layers import make_layer
    layer = make_layer({"type": "all2all_tanh", "output_sample_shape": 4,
                        "solver": "muon", "muon_ns_steps": 3,
                        "muon_momentum": 0.9, "rprop_inc": 1.1})
    assert layer.gd["muon_ns_steps"] == 3
    assert layer.gd["muon_momentum"] == 0.9
    assert layer.gd["rprop_inc"] == 1.1
    h = optimizer.resolve_hyper(layer.gd)
    assert h["muon_ns_steps"] == 3 and h["muon_momentum"] == 0.9


@pytest.mark.slow
def test_muon_trains_transformer():
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models import zoo
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(51)
    r = np.random.RandomState(2)
    toks = ((np.arange(16)[None, :] * 3 + r.randint(0, 5, 192)[:, None])
            % 17).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 48, 144])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=17, d_model=32, n_heads=4,
                                  n_layers=1, lr=5e-3, solver="muon"),
        loader=loader, loss="lm",
        gd_defaults={"weights_decay": 0.01, "clip_norm": 1.0},
        decision_config={"max_epochs": 15}, name="muon-lm")
    wf.initialize()
    wf.run()
    assert wf.decision.best_metric < 0.15, wf.decision.best_metric


def test_clip_by_global_norm():
    g = {"a": jnp.asarray([3.0, 4.0]), "b": jnp.asarray([0.0])}  # norm 5
    clipped = optimizer.clip_by_global_norm(g, 1.0)
    np.testing.assert_allclose(np.asarray(clipped["a"]), [0.6, 0.8],
                               rtol=1e-6)
    # already inside the bound: untouched
    same = optimizer.clip_by_global_norm(g, 10.0)
    np.testing.assert_allclose(np.asarray(same["a"]), [3.0, 4.0],
                               rtol=1e-6)


@pytest.mark.slow
def test_clip_norm_applied_in_training():
    """clip_norm in gd_defaults reaches optimizer.update: a near-zero
    clip freezes the params; a generous clip leaves training
    untouched."""
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow

    def run(gd_defaults, lr, seed=61):
        prng.seed_all(seed)
        r = np.random.RandomState(1)
        x = r.rand(256, 16).astype(np.float32)
        y = r.randint(0, 4, 256).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y,
                                 minibatch_size=64,
                                 class_lengths=[0, 64, 192])
        wf = StandardWorkflow(
            layers=[{"type": "all2all_tanh", "output_sample_shape": 16,
                     "learning_rate": lr},
                    {"type": "softmax", "output_sample_shape": 4,
                     "learning_rate": lr}],
            loader=loader, gd_defaults=gd_defaults,
            decision_config={"max_epochs": 4}, name="clip-t")
        wf.initialize()
        w0 = np.array(wf.trainer.host_params()[
            wf.trainer.layers[0].name]["weights"])
        wf.run()
        w1 = np.array(wf.trainer.host_params()[
            wf.trainer.layers[0].name]["weights"])
        return (wf.decision.epoch_metrics[2]["loss"],
                float(np.abs(w1 - w0).max()))

    _, moved = run({}, lr=0.1)
    _, frozen = run({"clip_norm": 1e-8}, lr=0.1)
    assert moved > 1e-3, moved               # normal training moves
    assert frozen < 1e-6, frozen             # clipped-to-nothing doesn't
    # generous clip on a sane run: identical result (norm never reached)
    a, _ = run({}, lr=0.1)
    b, _ = run({"clip_norm": 1e6}, lr=0.1)
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_clip_norm_zero_means_disabled():
    g = {"l": {"weights": jnp.asarray([3.0, 4.0])}}
    p = {"l": {"weights": jnp.asarray([1.0, 1.0])}}
    hy = {"l": optimizer.resolve_hyper({"learning_rate": 0.1})}
    p0, _ = optimizer.update(p, g, optimizer.init_state(p), hy,
                             clip_norm=0)
    p1, _ = optimizer.update(p, g, optimizer.init_state(p), hy,
                             clip_norm=None)
    np.testing.assert_array_equal(np.asarray(p0["l"]["weights"]),
                                  np.asarray(p1["l"]["weights"]))
    with pytest.raises(ValueError, match="positive"):
        optimizer.update(p, g, optimizer.init_state(p), hy,
                         clip_norm=-1.0)


class TestGradAccumulation:
    """grad_accum=k: every call accumulates; each k-th applies ONE update
    with the microbatch-mean gradient — exactly one k=1 update on the
    mean (the per-element-mean loss makes k steps at batch B equal one
    step at batch k*B)."""

    def test_k_microsteps_equal_one_mean_update(self):
        w0 = np.array([[1.0, -2.0], [0.5, 3.0]], np.float32)
        g1 = np.array([[0.2, 0.4], [-0.6, 0.1]], np.float32)
        g2 = np.array([[-0.1, 0.3], [0.2, 0.5]], np.float32)
        hyper = {"l": optimizer.resolve_hyper(
            {"solver": "adamw", "learning_rate": 0.1})}

        params = {"l": {"weights": jnp.asarray(w0)}}
        state = optimizer.init_state(params, grad_accum=2)
        p1, s1 = optimizer.update(params, {"l": {"weights": jnp.asarray(g1)}},
                                  state, hyper, grad_accum=2)
        # first microstep: params untouched, gradient banked, no step
        np.testing.assert_array_equal(np.asarray(p1["l"]["weights"]), w0)
        assert int(s1["step"]) == 0 and int(s1["micro"]) == 1
        p2, s2 = optimizer.update(p1, {"l": {"weights": jnp.asarray(g2)}},
                                  s1, hyper, grad_accum=2)
        assert int(s2["step"]) == 1
        np.testing.assert_allclose(
            np.asarray(s2["gacc"]["l"]["weights"]), 0.0)

        # reference: ONE plain update on the mean gradient
        ref_p = {"l": {"weights": jnp.asarray(w0)}}
        ref_s = optimizer.init_state(ref_p)
        ref_p, ref_s = optimizer.update(
            ref_p, {"l": {"weights": jnp.asarray((g1 + g2) / 2)}},
            ref_s, hyper)
        np.testing.assert_allclose(np.asarray(p2["l"]["weights"]),
                                   np.asarray(ref_p["l"]["weights"]),
                                   rtol=1e-6)

    def test_training_matches_double_batch(self):
        """digits MLP: mb=750 + grad_accum=2 reproduces mb=1500 up to
        float summation order (same shuffle order, per-element-mean
        loss, no RNG layers; few updates keep associativity drift from
        compounding through adamw's normalizer)."""
        from sklearn.datasets import load_digits
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow

        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)

        def run(mb, accum):
            prng.seed_all(21)
            loader = FullBatchLoader(None, data=x, labels=y,
                                     minibatch_size=mb,
                                     class_lengths=[0, 297, 1500])
            wf = StandardWorkflow(
                layers=[{"type": "all2all_tanh",
                         "output_sample_shape": 24},
                        {"type": "softmax", "output_sample_shape": 10}],
                # momentum GD: the update is LINEAR in g, so float
                # summation-order noise stays O(1e-7) instead of being
                # amplified by adamw's sign-like first-step normalizer
                loader=loader, gd_defaults={
                    "solver": "gd", "learning_rate": 0.05,
                    "gradient_moment": 0.9,
                    "grad_accum_steps": accum},
                decision_config={"max_epochs": 2}, name="accum-%d" % accum)
            wf.initialize()
            wf.run()
            return wf.trainer.params

        # 1500 train samples: mb=750 -> 2 microbatches = 1 update/epoch
        pa = run(750, 2)
        pb = run(1500, 1)
        for lname in pa:
            for k in pa[lname]:
                # f32 batch-grouping summation noise through the tanh
                # stack caps near 2e-5; a broken accumulation scale
                # (missing /k, double update) shows at ~1e-1
                np.testing.assert_allclose(
                    np.asarray(pa[lname][k]), np.asarray(pb[lname][k]),
                    rtol=1e-4, atol=3e-5)

    def test_accumulation_composes_with_fused_sweep(self):
        """steps_per_dispatch carries the accumulator through the scan:
        fused and per-step dispatch produce BITWISE-identical params."""
        from sklearn.datasets import load_digits
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow

        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)

        def run(spd):
            prng.seed_all(21)
            loader = FullBatchLoader(None, data=x, labels=y,
                                     minibatch_size=100,
                                     class_lengths=[0, 297, 1500])
            wf = StandardWorkflow(
                layers=[{"type": "all2all_tanh",
                         "output_sample_shape": 24},
                        {"type": "softmax", "output_sample_shape": 10}],
                loader=loader,
                gd_defaults={"solver": "gd", "learning_rate": 0.05,
                             "gradient_moment": 0.9,
                             "grad_accum_steps": 3},
                steps_per_dispatch=spd,
                decision_config={"max_epochs": 2},
                name="accum-spd%d" % spd)
            wf.initialize()
            wf.run()
            return wf.trainer.params

        pa, pb = run(1), run(5)
        for ln in pa:
            for k in pa[ln]:
                np.testing.assert_allclose(
                    np.asarray(pa[ln][k]), np.asarray(pb[ln][k]),
                    rtol=1e-6)


class TestEMA:
    """Polyak/EMA weight averaging (gd_defaults["ema_decay"])."""

    def test_ema_tracks_hand_computed_average(self):
        w0 = np.array([1.0, -2.0], np.float32)
        g = np.array([0.5, 0.5], np.float32)
        d = 0.9
        params = {"l": {"weights": jnp.asarray(w0)}}
        state = optimizer.init_state(params, ema_decay=d)
        np.testing.assert_array_equal(
            np.asarray(state["ema"]["l"]["weights"]), w0)
        hyper = {"l": optimizer.resolve_hyper(
            {"solver": "gd", "learning_rate": 0.1})}
        ema = w0.copy()
        for _ in range(3):
            params, state = optimizer.update(
                params, {"l": {"weights": jnp.asarray(g)}}, state, hyper,
                ema_decay=d)
            ema = d * ema + (1 - d) * np.asarray(params["l"]["weights"])
        np.testing.assert_allclose(
            np.asarray(state["ema"]["l"]["weights"]), ema, rtol=1e-6)

    def test_training_exposes_ema_and_serves_it(self):
        from sklearn.datasets import load_digits
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow

        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)
        prng.seed_all(7)
        loader = FullBatchLoader(None, data=x, labels=y,
                                 minibatch_size=100,
                                 class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[{"type": "all2all_tanh", "output_sample_shape": 24},
                    {"type": "softmax", "output_sample_shape": 10}],
            loader=loader,
            gd_defaults={"solver": "adamw", "learning_rate": 0.01,
                         "ema_decay": 0.95},
            decision_config={"max_epochs": 4}, name="ema-digits")
        wf.initialize()
        wf.run()
        tr = wf.trainer
        ema = tr.ema_params
        assert ema is not None
        # the average lags the live weights but is close after training
        w_live = np.asarray(tr.params["l00_all2all_tanh"]["weights"])
        w_ema = np.asarray(ema["l00_all2all_tanh"]["weights"])
        assert not np.array_equal(w_live, w_ema)
        assert np.max(np.abs(w_live - w_ema)) < 0.5
        # serve path: EMA weights classify about as well as the live ones
        probs = np.asarray(wf.forward_fn()(tr.serve_params(use_ema=True),
                                           x[:297]))
        err_ema = np.mean(np.argmax(probs, 1) != y[:297])
        assert err_ema < 0.15, err_ema
        # --test path: evaluating the average works and restores the
        # live params afterwards
        live_before = tr.params
        stats = wf.evaluate(use_ema=True)
        assert tr.params is live_before
        assert stats["validation"]["count"] == 297
        # off -> loud error, not silent un-averaged serving
        wf2_trainer_has_no_ema = tr.velocity.pop("ema")
        with pytest.raises(ValueError, match="ema_decay"):
            tr.serve_params(use_ema=True)
        tr.velocity["ema"] = wf2_trainer_has_no_ema


class TestAdafactor:
    """Factored second moments (Shazeer & Stern): O(n+m) state for an
    [n, m] weight instead of O(n·m), RMS-clipped updates, dense-adam
    fallback for 1-D leaves."""

    def _setup(self, shape, solver="adafactor"):
        r = np.random.RandomState(3)
        params = {"l": {"weights": jnp.asarray(
            r.randn(*shape).astype(np.float32))}}
        hyper = {"l": optimizer.resolve_hyper(
            {"solver": solver, "learning_rate": 0.05})}
        state = optimizer.init_state(params, hypers=hyper)
        return params, hyper, state, r

    def test_state_is_factored(self):
        params, hyper, state, _ = self._setup((32, 48))
        assert state["slot1"]["l"]["weights"].shape == (0,)
        assert state["slot2"]["l"]["weights"].shape == (32 + 48,)
        # conv-shaped leaf flattens its leading dims into rows
        p2, h2, s2, _ = self._setup((3, 3, 8, 16))
        assert s2["slot2"]["l"]["weights"].shape == (3 * 3 * 8 + 16,)

    def test_update_tracks_full_second_moment_for_rank1_noise(self):
        """For gradients with near-rank-1 second-moment structure the
        factored estimate matches the dense one, so the adafactor step
        approximates adam-without-momentum; here: update is finite,
        RMS-bounded, and descends a quadratic."""
        params, hyper, state, r = self._setup((16, 24))
        w_prev = np.asarray(params["l"]["weights"])
        target = jnp.zeros((16, 24))
        for _ in range(60):
            g = {"l": {"weights": params["l"]["weights"] - target}}
            params, state = optimizer.update(params, g, state, hyper)
        w = np.asarray(params["l"]["weights"])
        assert np.all(np.isfinite(w))
        assert np.abs(w).mean() < np.abs(w_prev).mean() * 0.5
        # update clipping: no single step exceeded lr * clip * ~sqrt(nm)
        assert np.max(np.abs(w - w_prev)) < 60 * 0.05 * 2.0

    def test_bias_falls_back_to_dense_adam(self):
        b_af, state = _one_step("adafactor", [2.0, -1.0], [0.5, 0.5],
                                leaf="bias")
        b_ad, _ = _one_step("adam", [2.0, -1.0], [0.5, 0.5], leaf="bias")
        np.testing.assert_allclose(b_af, b_ad, rtol=1e-6)

    @pytest.mark.slow
    def test_trains_transformer(self):
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models import zoo
        from veles_tpu.models.standard_workflow import StandardWorkflow

        prng.seed_all(51)
        r = np.random.RandomState(2)
        toks = ((np.arange(16)[None, :] * 3
                 + r.randint(0, 5, 192)[:, None]) % 17).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=48,
                                 class_lengths=[0, 48, 144])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=17, d_model=32,
                                      n_heads=4, n_layers=1, lr=2e-2,
                                      solver="adafactor"),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 15}, name="adafactor-lm")
        wf.initialize()
        wf.run()
        assert wf.decision.best_metric < 0.2, wf.decision.best_metric
        # the big matrices really carry factored state
        tr = wf.trainer
        mha = tr.velocity["slot2"]["l02_transformer_block"]["mha"]["wq"]
        assert mha.ndim == 1 and mha.shape[0] == 32 + 32

    def test_resume_across_solver_change_reinitializes_moments(self):
        """A snapshot from an adamw run restores into an adafactor
        config (and the shapes are incompatible): the moments restart
        with a warning instead of crashing mid-trace."""
        from sklearn.datasets import load_digits
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow

        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)

        def build(solver, epochs):
            prng.seed_all(9)
            loader = FullBatchLoader(None, data=x, labels=y,
                                     minibatch_size=100,
                                     class_lengths=[0, 297, 1500])
            wf = StandardWorkflow(
                layers=[{"type": "all2all_tanh",
                         "output_sample_shape": 24},
                        {"type": "softmax", "output_sample_shape": 10}],
                loader=loader,
                gd_defaults={"solver": solver, "learning_rate": 0.01},
                snapshotter_config={"interval": 1000},
                decision_config={"max_epochs": epochs}, name="xsolver")
            wf.initialize()
            return wf

        wf1 = build("adamw", 1)
        wf1.run()
        snap = wf1.snapshotter.collect()
        wf2 = build("adafactor", 2)
        wf2.restore(snap)
        wf2.run()
        assert wf2.loader.epoch_number == 2
        assert wf2.trainer.velocity["slot2"][
            "l00_all2all_tanh"]["weights"].shape == (64 + 24,)
