"""Numerics, determinism & Pallas auditor suite (VN4xx/VR5xx/VP6xx,
docs/static_analysis.md): one seeded hazard per rule caught from a
PURELY ABSTRACT trace (no computation dispatched, no device array
created — asserted), guarded counterparts silent, MNIST- and
CIFAR-shaped sample workflows audit clean end to end, the prng
seed-collision satellite, and the CLI surfaces (``--numerics``,
``--vmem-kib``, unified ``--fail-on`` exit codes)."""

import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veles_tpu.analysis import lint_workflow, threshold_reached
from veles_tpu.analysis.findings import ERROR, INFO, WARNING, Finding
from veles_tpu.analysis.numerics_audit import (DEFAULT_VMEM_KIB,
                                               audit_kernel_launch,
                                               audit_numerics_step,
                                               audit_pallas_kernels,
                                               audit_prng_registry)


def rules(findings):
    return [f.rule for f in findings]


def by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


def S(*shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


def audit(fn, *args, **spec_extra):
    spec = dict({"fn": fn, "args": args, "name": "t"}, **spec_extra)
    return audit_numerics_step(spec)


# --------------------------------------------------------------------------
# VN4xx: seeded hazards fire, guarded counterparts stay silent
# --------------------------------------------------------------------------
class TestVN400:
    def test_unguarded_log_fires(self):
        assert rules(audit(lambda x: jnp.log(x).sum(), S(8))) == ["VN400"]

    def test_clamped_log_silent(self):
        fs = audit(lambda x: jnp.log(jnp.maximum(x, 1e-6)).sum(), S(8))
        assert fs == []

    def test_log_of_exp_silent(self):
        assert audit(lambda x: jnp.log(jnp.exp(x - x.max())).sum(),
                     S(8)) == []

    def test_log_of_eps_plus_erf_fires(self):
        """erf ranges over [-1, 1] — it is NOT nonnegative, so an eps
        does not make log(eps + erf(x)) safe (review finding)."""
        fs = audit(lambda x: jnp.log(1e-6 + jax.lax.erf(x)).sum(),
                   S(8))
        assert rules(fs) == ["VN400"]

    def test_unguarded_div_fires(self):
        assert rules(audit(lambda x, y: (x / y).sum(),
                           S(8), S(8))) == ["VN400"]

    def test_count_guarded_div_silent(self):
        fs = audit(lambda x, n: (x.sum() / jnp.maximum(n, 1.0)),
                   S(8), S())
        assert fs == []

    def test_eps_guarded_rsqrt_silent(self):
        fs = audit(lambda x: jax.lax.rsqrt(x * x + 1e-6).sum(), S(8))
        assert fs == []

    def test_unguarded_rsqrt_fires(self):
        assert rules(audit(lambda x: jax.lax.rsqrt(x).sum(),
                           S(8))) == ["VN400"]

    def test_layer_norm_grad_silent(self):
        """jnp.var's ddof arithmetic and the max-gradient tie count are
        literal-foldable — the classic LN backward must not fire."""
        from veles_tpu.ops import norm

        def step(x, g):
            return jax.grad(lambda x: norm.layer_norm(x, g).sum())(x)
        assert audit(step, S(8, 16, 32), S(32)) == []

    def test_online_softmax_scan_grad_silent(self):
        """The blockwise-attention backward divides by residuals that
        ride a scan — the ``maximum(l, eps)`` guard must survive the
        stacked-ys flag mapping."""
        from veles_tpu.ops import attention

        def step(q, k, v):
            return jax.grad(lambda q: attention.blockwise_attention(
                q, k, v, causal=True).sum())(q)
        assert audit(step, S(2, 2, 16, 8), S(2, 2, 16, 8),
                     S(2, 2, 16, 8)) == []

    def test_adam_bias_correction_needs_vouched_step(self):
        """``1 - beta**t`` is positive only because t >= 1 — which the
        auditor accepts exactly when the caller vouches for the step
        input (the trainer does; an unvouched step still fires)."""
        def adamish(m, step):
            t = step.astype(jnp.float32)
            return m / (1.0 - 0.9 ** t)

        args = (S(4), S(dtype=jnp.int32))
        assert rules(audit(adamish, *args)) == ["VN400"]
        assert audit(adamish, *args,
                     input_flags={1: ("pos", "nonneg")}) == []


class TestVN401:
    def test_unguarded_exp_fires(self):
        assert rules(audit(lambda x: jnp.exp(x).sum(), S(8))) == ["VN401"]

    def test_sub_max_guard_silent(self):
        assert audit(lambda x: jnp.exp(x - x.max()).sum(), S(8)) == []

    def test_clamp_guard_silent(self):
        assert audit(lambda x: jnp.exp(jnp.minimum(x, 30.0)).sum(),
                     S(8)) == []

    def test_literal_minus_unbounded_still_fires(self):
        """exp(c - x) overflows for very negative x — a bounded minuend
        alone must not launder the bound (review finding)."""
        fs = audit(lambda x: jnp.exp(5.0 - x).sum(), S(8))
        assert rules(fs) == ["VN401"]

    def test_literal_minus_nonneg_silent(self):
        assert audit(lambda x: jnp.exp(5.0 - jnp.abs(x)).sum(),
                     S(8)) == []

    def test_log_softmax_loss_silent(self):
        from veles_tpu.ops import losses

        def step(w, x, lbl, valid):
            def loss(w):
                ls, _e, nv = losses.masked_softmax_xent(
                    jnp.tanh(x @ w), lbl, valid)
                return ls / jnp.maximum(nv, 1.0)
            return jax.grad(loss)(w)
        assert audit(step, S(8, 10), S(64, 8),
                     S(64, dtype=jnp.int32), S(64)) == []


class TestVN402:
    def test_raw_softmax_then_log_fires(self):
        fs = audit(lambda x: jnp.log(jax.nn.softmax(x)).sum(), S(4, 8))
        assert rules(fs) == ["VN402"]
        assert "log_softmax" in fs[0].hint

    def test_log_softmax_silent(self):
        assert audit(lambda x: jax.nn.log_softmax(x).sum(), S(4, 8)) == []


class TestVN403:
    B16 = jax.ShapeDtypeStruct((64, 4096), jnp.bfloat16)
    W16 = jax.ShapeDtypeStruct((4096, 64), jnp.bfloat16)

    def test_bf16_dot_accumulation_fires(self):
        fs = audit(lambda x, y: x @ y, self.B16, self.W16)
        assert rules(fs) == ["VN403"]

    def test_f32_preferred_type_silent(self):
        def f(x, y):
            return jax.lax.dot_general(
                x, y, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        assert audit(f, self.B16, self.W16) == []

    def test_small_contraction_silent(self):
        small = jax.ShapeDtypeStruct((64, 64), jnp.bfloat16)
        assert audit(lambda x, y: x @ y, small, small) == []

    def test_jnp_sum_upcasts_silent(self):
        """jnp internally upcasts f16/bf16 sums to f32 — no finding."""
        x = jax.ShapeDtypeStruct((4096,), jnp.bfloat16)
        assert audit(lambda x: x.sum(), x) == []


class TestVN404:
    I32 = jax.ShapeDtypeStruct((8,), jnp.int32)

    def test_narrowing_cast_fires(self):
        fs = audit(lambda x: x.astype(jnp.int8).sum(), self.I32)
        assert rules(fs) == ["VN404"]

    def test_clip_guard_silent(self):
        assert audit(lambda x: jnp.clip(x, 0, 127).astype(jnp.int8)
                     .sum(), self.I32) == []

    def test_signed_clip_guard_silent(self):
        """The documented fix — clip to the SIGNED target range — must
        pass (review finding: the lattice has no bounded-below flag,
        so the clamp literals are checked against the dtype range)."""
        assert audit(lambda x: jnp.clip(x, -128, 127).astype(jnp.int8)
                     .sum(), self.I32) == []

    def test_too_wide_clip_still_fires(self):
        fs = audit(lambda x: jnp.clip(x, -1000, 1000).astype(jnp.int8)
                   .sum(), self.I32)
        assert rules(fs) == ["VN404"]

    def test_widening_cast_silent(self):
        i8 = jax.ShapeDtypeStruct((8,), jnp.int8)
        assert audit(lambda x: x.astype(jnp.int32).sum(), i8) == []


# --------------------------------------------------------------------------
# VR5xx: randomness & determinism
# --------------------------------------------------------------------------
KEY = None


def key_spec():
    global KEY
    if KEY is None:
        KEY = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    return KEY


class TestVR500:
    def test_key_reused_by_two_draws_fires(self):
        def f(k):
            return jax.random.normal(k, (4,)) \
                + jax.random.uniform(k, (4,))
        assert rules(audit(f, key_spec())) == ["VR500"]

    def test_split_keys_silent(self):
        def f(k):
            a, b = jax.random.split(k)
            return jax.random.normal(a, (4,)) \
                + jax.random.uniform(b, (4,))
        assert audit(f, key_spec()) == []

    def test_fold_in_same_counter_fires(self):
        def f(k):
            return (jax.random.normal(jax.random.fold_in(k, 7), (4,))
                    + jax.random.uniform(jax.random.fold_in(k, 7),
                                         (4,)))
        assert rules(audit(f, key_spec())) == ["VR500"]

    def test_fold_in_distinct_counters_silent(self):
        def f(k):
            return (jax.random.normal(jax.random.fold_in(k, 1), (4,))
                    + jax.random.uniform(jax.random.fold_in(k, 2),
                                         (4,)))
        assert audit(f, key_spec()) == []

    def test_trainer_per_layer_fold_pattern_silent(self):
        """The StagedTrainer folds the step then each layer index —
        all distinct streams."""
        def f(k, step):
            k = jax.random.fold_in(k, step)
            return sum(jax.random.normal(jax.random.fold_in(k, i),
                                         (4,)).sum()
                       for i in range(3))
        assert audit(f, key_spec(), S(dtype=jnp.int32)) == []


class TestVR501:
    def test_explicit_seed_collision_reported(self):
        from veles_tpu import prng
        prng._streams.clear()
        prng.get("a").seed(123)
        prng.get("b").seed(123)
        try:
            fs = audit_prng_registry()
            assert rules(fs) == ["VR501"]
            assert "a" in fs[0].message and "b" in fs[0].message
        finally:
            prng._streams.clear()

    def test_derived_seeds_never_collide(self):
        from veles_tpu import prng
        prng._streams.clear()
        prng.seed_all(7)
        for i in range(64):
            prng.get("stream-%d" % i)
        try:
            assert prng.seed_collisions() == []
            assert audit_prng_registry() == []
        finally:
            prng._streams.clear()


class TestVR502:
    def test_host_numpy_random_fires(self, tmp_path):
        mod = tmp_path / "staged_host_rand.py"
        mod.write_text(
            "import numpy as np\n"
            "def step(x):\n"
            "    return x * np.random.rand()\n")
        import importlib.util
        spec = importlib.util.spec_from_file_location("staged_host_rand",
                                                      mod)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        fs = audit(m.step, S(4))
        assert rules(fs) == ["VR502"]
        assert fs[0].severity == ERROR

    def test_jax_random_silent(self):
        def f(k):
            return jax.random.normal(k, (4,))
        assert audit(f, key_spec()) == []

    def test_host_scan_covers_loss_callees(self, tmp_path):
        """The trainer's step fn is framework code — a user loss with
        host randomness is caught via the spec's host_scan list (the
        trainer passes its loss evaluator and non-veles_tpu layers)."""
        mod = tmp_path / "user_loss.py"
        mod.write_text(
            "import numpy as np\n"
            "def noisy_loss(out):\n"
            "    return out.sum() * np.random.rand()\n")
        import importlib.util
        spec = importlib.util.spec_from_file_location("user_loss", mod)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)

        def clean_step(x):           # framework-style wrapper
            return m.noisy_loss(x)

        assert audit(clean_step, S(4)) == []       # wrapper scan misses
        fs = audit(clean_step, S(4), host_scan=(m.noisy_loss,))
        assert rules(fs) == ["VR502"]
        assert "noisy_loss" in fs[0].message


class TestVR503:
    I32 = jax.ShapeDtypeStruct((4,), jnp.int32)

    def test_float_scatter_add_fires(self):
        fs = audit(lambda x, i, u: x.at[i].add(u), S(8), self.I32, S(4))
        assert rules(fs) == ["VR503"]

    def test_unique_indices_silent(self):
        assert audit(lambda x, i, u: x.at[i].add(u, unique_indices=True),
                     S(8), self.I32, S(4)) == []

    def test_int_scatter_silent(self):
        i8 = jax.ShapeDtypeStruct((8,), jnp.int32)
        u = jax.ShapeDtypeStruct((4,), jnp.int32)
        assert audit(lambda x, i, u: x.at[i].add(u), i8, self.I32,
                     u) == []

    def test_take_along_backward_silent(self):
        """The loss's take_along_axis backward scatters one index per
        batch row (operand batching dims) — exempt."""
        def f(x, lbl):
            return jnp.take_along_axis(x, lbl, axis=1).sum()
        assert audit(lambda x, lbl: jax.grad(f)(x, lbl),
                     S(4, 10), jax.ShapeDtypeStruct((4, 1),
                                                    jnp.int32)) == []

    def test_embedding_backward_silent(self):
        """jnp.take's transpose (the embedding-table gradient) is
        XLA-generated and TPU-deterministic — exempt."""
        def f(table, ids):
            return jnp.take(table, ids, axis=0).sum()
        assert audit(lambda t, i: jax.grad(f)(t, i),
                     S(16, 8), jax.ShapeDtypeStruct((4,),
                                                    jnp.int32)) == []


# --------------------------------------------------------------------------
# VP6xx: Pallas launch geometry
# --------------------------------------------------------------------------
class TestVP600:
    def test_unaligned_sublane_fires(self):
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": True, "scratch": [],
             "blocks": [("q", (1, 100, 256), jnp.bfloat16)],
             "grid_axes": []})
        assert rules(fs) == ["VP600"]
        assert "(16, 128)" in fs[0].message    # bf16 tile

    def test_aligned_silent(self):
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": True, "scratch": [],
             "blocks": [("q", (1, 128, 256), jnp.bfloat16)],
             "grid_axes": []})
        assert fs == []

    def test_full_lane_head_dim_exempt(self):
        """d=64 models exist: a lane dim that IS the head dim is the
        model's geometry, not a tunable tile choice."""
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": True, "scratch": [],
             "blocks": [("q", (1, 128, 64), jnp.bfloat16,
                         {"full_lane": True})],
             "grid_axes": []})
        assert fs == []

    def test_f32_sublane_tile_is_8(self):
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": True, "scratch": [],
             "blocks": [("q", (1, 24, 128), jnp.float32)],
             "grid_axes": []})
        assert fs == []    # 24 % 8 == 0


class TestVP601:
    def test_ragged_unmasked_grid_fires(self):
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": False, "scratch": [],
             "blocks": [], "grid_axes": [("q", 1000, 128)]})
        assert rules(fs) == ["VP601"]

    def test_masked_kernel_exempt(self):
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": True, "scratch": [],
             "blocks": [], "grid_axes": [("q", 1000, 128)]})
        assert fs == []


class TestVP602:
    def test_over_budget_fires_error(self):
        fs = audit_kernel_launch(
            {"kernel": "t", "masked": True,
             "blocks": [("q", (1, 4096, 2048), jnp.float32)],
             "scratch": [], "grid_axes": []})
        assert rules(fs) == ["VP602"]
        assert fs[0].severity == ERROR

    def test_budget_knob(self):
        launch = {"kernel": "t", "masked": True,
                  "blocks": [("q", (1, 128, 128), jnp.float32)],
                  "scratch": [], "grid_axes": []}
        assert audit_kernel_launch(launch) == []
        assert rules(audit_kernel_launch(launch, vmem_kib=64)) \
            == ["VP602"]

    def test_checked_escape_hatch(self):
        launch = {"kernel": "t", "masked": True, "checked": ("VP602",),
                  "blocks": [("q", (1, 4096, 2048), jnp.float32)],
                  "scratch": [], "grid_axes": []}
        assert audit_kernel_launch(launch) == []


class TestConfiguredKernels:
    def test_registered_launches_audit_clean(self):
        """The shipped flash/paged kernels at their site-config block
        sizes pass their own auditor (the analyzer gates the repo that
        ships it)."""
        assert audit_pallas_kernels() == []

    def test_flash_audit_launch_matches_kernel_geometry(self):
        from veles_tpu.ops.pallas import flash
        fwd, dq, dkv = flash.audit_launch(1024, 1024, 128, causal=True,
                                          block_q=512, block_k=512)
        names = [b[0] for b in fwd["blocks"]]
        assert names == ["q", "k", "v", "o", "lse"]
        assert fwd["blocks"][0][1] == (1, 512, 128)
        assert dq["scratch"][0][1] == (512, 128)
        assert {b[0] for b in dkv["blocks"]} >= {"dk", "dv", "delta"}

    def test_flash_oversized_blocks_over_budget(self):
        from veles_tpu.ops.pallas import flash
        launches = flash.audit_launch(8192, 8192, 128, causal=True,
                                      block_q=4096, block_k=4096)
        fs = audit_pallas_kernels(launches=launches,
                                  vmem_kib=DEFAULT_VMEM_KIB)
        assert "VP602" in rules(fs)

    def test_unmasked_description_fires_vp601(self):
        from veles_tpu.ops.pallas import flash
        launches = flash.audit_launch(1000, 1000, 128, block_q=128,
                                      block_k=128, masked=False)
        assert "VP601" in rules(audit_pallas_kernels(launches=launches))


# --------------------------------------------------------------------------
# the combined hazard workflow: every rule exactly once through
# lint_workflow (the acceptance fixture)
# --------------------------------------------------------------------------
ALL_RULES = ("VN400", "VN401", "VN402", "VN403", "VN404",
             "VR500", "VR501", "VR502", "VR503",
             "VP600", "VP601", "VP602")


def _hazard_step_module(tmp_path):
    mod = tmp_path / "hazard_step.py"
    mod.write_text(
        "import jax, jax.numpy as jnp, numpy as np\n"
        "def step(x, b16, i32, key, idx, upd):\n"
        "    np.random.rand()                       # VR502\n"
        "    a = jnp.log(x)                         # VN400\n"
        "    b = jnp.exp(x)                         # VN401\n"
        "    c = jnp.log(jax.nn.softmax(x))         # VN402\n"
        "    d = (b16 @ b16.T)                      # VN403\n"
        "    e = i32.astype(jnp.int8)               # VN404\n"
        "    f = jax.random.normal(key, (4,))       # VR500 (reuse)\n"
        "    g = jax.random.uniform(key, (4,))\n"
        "    h = x.at[idx].add(upd)                 # VR503\n"
        "    return (a.sum() + b.sum() + c.sum()\n"
        "            + d.astype(jnp.float32).sum()\n"
        "            + e.sum() + f.sum() + g.sum() + h.sum())\n")
    import importlib.util
    spec = importlib.util.spec_from_file_location("hazard_step", mod)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


class TestHazardWorkflow:
    def test_every_rule_exactly_once(self, tmp_path, monkeypatch):
        from veles_tpu import prng
        from veles_tpu.ops import pallas
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow

        m = _hazard_step_module(tmp_path)
        args = (S(8), jax.ShapeDtypeStruct((64, 4096), jnp.bfloat16),
                jax.ShapeDtypeStruct((8,), jnp.int32), key_spec(),
                jax.ShapeDtypeStruct((4,), jnp.int32), S(4))

        class Hazard(TrivialUnit):
            def lint_numerics_spec(self):
                return {"fn": m.step, "args": args,
                        "name": "hazard.step"}

        # VP6xx: one bad launch per rule via the kernel-audit registry
        monkeypatch.setattr(pallas, "KERNEL_AUDITS", {"bad": lambda: [
            {"kernel": "bad.tile", "masked": True, "scratch": [],
             "blocks": [("q", (1, 100, 256), jnp.bfloat16)],
             "grid_axes": []},
            {"kernel": "bad.grid", "masked": False, "scratch": [],
             "blocks": [], "grid_axes": [("q", 1000, 128)]},
            {"kernel": "bad.vmem", "masked": True, "scratch": [],
             "blocks": [("q", (1, 4096, 2048), jnp.float32)],
             "grid_axes": []},
        ]})
        # VR501: two explicitly same-seeded streams
        prng._streams.clear()
        prng.get("h1").seed(99)
        prng.get("h2").seed(99)

        wf = Workflow(name="hazards")
        u = Hazard(wf, name="hazard")
        u.link_from(wf.start_point)
        wf.end_point.link_from(u)
        try:
            fs = [f for f in lint_workflow(wf)
                  if f.rule.startswith(("VN", "VR", "VP"))]
        finally:
            prng._streams.clear()
        counts = {}
        for f in fs:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        assert counts == {r: 1 for r in ALL_RULES}

    def test_audit_is_purely_abstract_no_device_arrays(self, tmp_path):
        """The acceptance gate: the VN/VR audit runs off
        ShapeDtypeStructs — no computation dispatched, no device array
        allocated (the VP rules are plain arithmetic)."""
        m = _hazard_step_module(tmp_path)
        args = (S(8), jax.ShapeDtypeStruct((64, 4096), jnp.bfloat16),
                jax.ShapeDtypeStruct((8,), jnp.int32), key_spec(),
                jax.ShapeDtypeStruct((4,), jnp.int32), S(4))
        for leaf in args:
            assert isinstance(leaf, jax.ShapeDtypeStruct)
        gc.collect()
        before = len(jax.live_arrays())
        fs = audit_numerics_step({"fn": m.step, "args": args})
        assert fs    # it did find the seeded hazards
        gc.collect()
        assert len(jax.live_arrays()) <= before


# --------------------------------------------------------------------------
# sample-shaped workflows audit clean (the other half of acceptance)
# --------------------------------------------------------------------------
def build_wf(name, layers, data, labels, loss="softmax", mb=32,
             gd=None):
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    prng.seed_all(7)
    loader = FullBatchLoader(
        None, data=data, labels=labels, minibatch_size=mb,
        class_lengths=[0, len(data) // 4, len(data) - len(data) // 4])
    kwargs = {"gd_defaults": gd} if gd else {}
    wf = StandardWorkflow(layers=layers, loader=loader, loss=loss,
                          decision_config={"max_epochs": 1}, name=name,
                          **kwargs)
    wf.initialize()
    return wf


def numerics_findings(wf):
    return [f for f in lint_workflow(wf)
            if f.rule.startswith(("VN", "VR", "VP"))]


class TestSamplesClean:
    def test_mnist_shaped_mlp_clean(self):
        from veles_tpu.models import zoo
        rng = np.random.default_rng(0)
        wf = build_wf("mnist-numerics", zoo.mnist_mlp(),
                      rng.normal(size=(512, 28, 28)).astype(np.float32),
                      rng.integers(0, 10, 512).astype(np.int32))
        assert numerics_findings(wf) == []

    def test_cifar_shaped_conv_clean(self):
        from veles_tpu.models import zoo
        rng = np.random.default_rng(0)
        wf = build_wf("cifar-numerics", zoo.cifar_conv(),
                      rng.normal(size=(128, 32, 32, 3)).astype(
                          np.float32),
                      rng.integers(0, 10, 128).astype(np.int32), mb=16)
        assert numerics_findings(wf) == []

    @pytest.mark.slow
    def test_transformer_lm_clean(self):
        from veles_tpu.models import zoo
        rng = np.random.default_rng(0)
        tok = rng.integers(0, 64, size=(128, 16)).astype(np.int32)
        wf = build_wf("lm-numerics",
                      zoo.transformer_lm(vocab_size=64, d_model=32,
                                         n_heads=2, n_layers=2,
                                         dropout=0.1),
                      tok, tok, loss="lm", mb=8)
        assert numerics_findings(wf) == []

    def test_grad_accum_adam_clean(self):
        """The cond-wrapped accumulating update keeps its vouched step
        counter through the branch mapping."""
        rng = np.random.default_rng(0)
        wf = build_wf(
            "gacc-numerics",
            [{"type": "all2all_tanh", "output_sample_shape": 16,
              "solver": "adam"},
             {"type": "softmax", "output_sample_shape": 10}],
            rng.normal(size=(128, 24)).astype(np.float32),
            rng.integers(0, 10, 128).astype(np.int32), mb=16,
            gd={"grad_accum_steps": 2, "clip_norm": 1.0})
        assert numerics_findings(wf) == []


# --------------------------------------------------------------------------
# hooks & escape hatches
# --------------------------------------------------------------------------
class TestTrainerHook:
    def test_spec_shape_and_abstract_args(self):
        rng = np.random.default_rng(0)
        wf = build_wf("hook-numerics",
                      [{"type": "all2all_tanh",
                        "output_sample_shape": 16},
                       {"type": "softmax", "output_sample_shape": 10}],
                      rng.normal(size=(128, 24)).astype(np.float32),
                      rng.integers(0, 10, 128).astype(np.int32), mb=16)
        spec = wf.trainer.lint_numerics_spec()
        assert spec is not None
        assert spec["name"].endswith("train_step")
        for leaf in jax.tree_util.tree_leaves(spec["args"]):
            assert isinstance(leaf, jax.ShapeDtypeStruct)
        # the step counter is vouched positive
        assert ("pos", "nonneg") in spec["input_flags"].values()

    def test_none_before_initialize(self):
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        rng = np.random.default_rng(0)
        loader = FullBatchLoader(
            None, data=rng.normal(size=(64, 8)).astype(np.float32),
            labels=rng.integers(0, 4, 64).astype(np.int32),
            minibatch_size=16, class_lengths=[0, 16, 48])
        wf = StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 4}],
            loader=loader, decision_config={"max_epochs": 1},
            name="uninit-numerics")
        assert wf.trainer.lint_numerics_spec() is None

    def test_loss_suppress_escape_hatch(self):
        from veles_tpu.ops.losses import _LOSSES, register_loss

        @register_loss("test_suppressed", kind="class",
                       numerics_suppress=("VN404",))
        def suppressed(out, labels, targets, valid):
            narrowed = labels.astype(jnp.int8).astype(jnp.float32)
            return (narrowed.sum(), jnp.asarray(0.0),
                    jnp.maximum(valid.sum(), 1.0), 1)
        try:
            rng = np.random.default_rng(0)
            wf = build_wf("suppress-numerics",
                          [{"type": "all2all_tanh",
                            "output_sample_shape": 8}],
                          rng.normal(size=(128, 8)).astype(np.float32),
                          rng.integers(0, 4, 128).astype(np.int32),
                          loss="test_suppressed", mb=16)
            spec = wf.trainer.lint_numerics_spec()
            assert "VN404" in spec["suppress"]
            assert by_rule(audit_numerics_step(spec), "VN404") == []
        finally:
            _LOSSES.pop("test_suppressed", None)


# --------------------------------------------------------------------------
# prng satellite: derived-seed collision detection + deterministic rehash
# --------------------------------------------------------------------------
class TestPrngSeedDerivation:
    def test_collision_rehashes_deterministically(self, caplog):
        from veles_tpu import prng
        saved = dict(prng._derived_seeds)
        prng._derived_seeds.clear()
        try:
            s_a = prng._derive_seed("alpha", 1234)
            # force a collision: pretend another stream owns alpha's slot
            prng._derived_seeds.clear()
            prng._derived_seeds[s_a] = "other"
            import logging
            with caplog.at_level(logging.WARNING, logger="prng"):
                s_a2 = prng._derive_seed("alpha", 1234)
            assert s_a2 != s_a
            assert any("collides" in r.message for r in caplog.records)
            # deterministic: same preconditions, same rehash result
            prng._derived_seeds.clear()
            prng._derived_seeds[s_a] = "other"
            assert prng._derive_seed("alpha", 1234) == s_a2
        finally:
            prng._derived_seeds.clear()
            prng._derived_seeds.update(saved)

    def test_same_name_rederives_same_seed(self):
        from veles_tpu import prng
        saved = dict(prng._derived_seeds)
        prng._derived_seeds.clear()
        try:
            assert prng._derive_seed("x", 42) == \
                prng._derive_seed("x", 42)
        finally:
            prng._derived_seeds.clear()
            prng._derived_seeds.update(saved)

    def test_seed_all_replays_fresh_process_derivation(self):
        from veles_tpu import prng
        prng._streams.clear()
        try:
            prng.seed_all(11)
            g1 = prng.get("s1")
            g2 = prng.get("s2")
            seeds_fresh = (g1._seed, g2._seed)
            prng.seed_all(11)     # re-seed in place
            assert (g1._seed, g2._seed) == seeds_fresh
        finally:
            prng._streams.clear()


# --------------------------------------------------------------------------
# exit-code unification satellite + CLI surfaces
# --------------------------------------------------------------------------
class TestThresholdReached:
    FS = [Finding("VN400", WARNING, "u", "m"),
          Finding("VM300", INFO, "u", "m")]

    def test_error_threshold(self):
        assert not threshold_reached(self.FS, "error")
        assert threshold_reached(
            self.FS + [Finding("VR502", ERROR, "u", "m")], "error")

    def test_warning_threshold(self):
        assert threshold_reached(self.FS, "warning")
        assert not threshold_reached([self.FS[1]], "warning")

    def test_bad_threshold_raises(self):
        with pytest.raises(ValueError):
            threshold_reached(self.FS, "nope")


WF_TEMPLATE = """\
import numpy as np
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow

def run(load, main):
    rng = np.random.default_rng(0)
    loader = FullBatchLoader(
        None, data=rng.normal(size=(128, 16)).astype(np.float32),
        labels=rng.integers(0, 4, 128).astype(np.int32),
        minibatch_size=16, class_lengths=[0, 32, 96])
    load(StandardWorkflow,
         layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                 {"type": "softmax", "output_sample_shape": 4}],
         loader=loader, decision_config={"max_epochs": 1},
         name="cli-numerics")
    main()
"""


class TestCLI:
    def test_numerics_flag_initializes_and_audits(self, tmp_path,
                                                  capsys):
        from veles_tpu.analysis.cli import main
        wf = tmp_path / "wf.py"
        wf.write_text(WF_TEMPLATE)
        rc = main([str(wf), "--numerics"])
        out = capsys.readouterr().out
        assert rc == 0
        # clean step: only the passive-Forward VG002 infos remain
        assert "VN4" not in out and "VR5" not in out

    def test_vmem_kib_knob_reaches_vp602(self, tmp_path, capsys):
        """A starvation budget turns the shipped flash launches into
        VP602 errors, and --fail-on error exits 1 — the unified gate."""
        from veles_tpu.analysis.cli import main
        wf = tmp_path / "wf.py"
        wf.write_text(WF_TEMPLATE)
        rc = main([str(wf), "--vmem-kib", "16"])
        out = capsys.readouterr().out
        assert "VP602" in out
        assert rc == 1

    def test_fail_on_warning_applies_to_numerics(self, tmp_path,
                                                 capsys, monkeypatch):
        from veles_tpu import prng
        from veles_tpu.analysis.cli import main
        wf = tmp_path / "wf.py"
        wf.write_text(WF_TEMPLATE)
        prng._streams.clear()
        prng.get("c1").seed(5)
        prng.get("c2").seed(5)       # VR501 warning
        try:
            assert main([str(wf)]) == 0
            capsys.readouterr()
            rc = main([str(wf), "--fail-on", "warning"])
            out = capsys.readouterr().out
            assert "VR501" in out
            assert rc == 1
        finally:
            prng._streams.clear()

    def test_help_documents_exit_codes(self, capsys):
        from veles_tpu.analysis.cli import main
        with pytest.raises(SystemExit):
            main(["--help"])
        out = capsys.readouterr().out
        assert "exit codes" in out
        assert "threshold" in out
