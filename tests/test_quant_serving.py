"""Quantized decode depth: w4a8 serving weights, the fused paged
decode kernel's quantized-pool (int8 QuantCache) variant, per-row
speculative routing, and the stray-dequant jaxpr audit that pins the
whole story — no QuantWeight may dequantize outside a dot on the
decode hot path (ISSUE 14)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.generate import (ContinuousBatcher, LMGenerator,
                                       PagedContinuousBatcher)
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.ops import quant


def _lm_workflow(max_epochs=0, vocab=13, t=16, seed=31, **zoo_kwargs):
    prng.seed_all(seed)
    r = np.random.RandomState(5)
    toks = ((np.arange(t)[None, :] * 2 + r.randint(0, 4, 192)[:, None])
            % vocab).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 48, 144])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32,
                                  n_heads=4, n_layers=2, lr=5e-3,
                                  dropout=0.0, **zoo_kwargs),
        loader=loader, loss="lm",
        decision_config={"max_epochs": max(max_epochs, 1)},
        name="quant-lm")
    wf.initialize()
    if max_epochs > 0:
        wf.run()
    return wf, toks


# --------------------------------------------------------------------------
# w4a8 scheme unit level
# --------------------------------------------------------------------------

class TestW4A8Scheme:
    @pytest.mark.parametrize("n_in", [16, 17])   # even + odd (pad path)
    def test_pack_unpack_roundtrip(self, n_in):
        r = np.random.RandomState(0)
        q = r.randint(-7, 8, (n_in, 12)).astype(np.int8)
        packed = quant._pack_nibbles(jnp.asarray(q), 0)
        assert packed.shape == ((n_in + 1) // 2, 12)
        assert packed.dtype == jnp.int8
        unp = np.asarray(quant._unpack_nibbles(packed, n_in, 0))
        np.testing.assert_array_equal(unp, q)

    def test_quantize_weight4_layout_and_error_bound(self):
        r = np.random.RandomState(1)
        w = r.randn(24, 10).astype(np.float32)
        qw = quant.quantize_weight4(w)
        assert isinstance(qw, quant.QuantWeight4)
        assert qw.q.shape == (12, 10) and qw.scale.shape == (10,)
        assert (qw.n, qw.axis) == (24, 0)
        deq = (np.asarray(quant._unpack_nibbles(qw.q, 24, 0),
                          np.float32) * np.asarray(qw.scale))
        # round-to-nearest symmetric int4: error <= scale/2 per entry
        assert np.all(np.abs(deq - w)
                      <= np.asarray(qw.scale) * 0.5 + 1e-6)

    def test_w4a8_matmul_matches_dequantized_reference(self):
        """The fused w4a8 dot must equal the explicit two-step
        (quantize acts, dequantize weight, float matmul) bit for bit —
        the integer-valued f32 dot is exact, so 'fp accumulation'
        changes nothing but the wire format."""
        r = np.random.RandomState(2)
        w = r.randn(16, 12).astype(np.float32)
        x = r.randn(5, 16).astype(np.float32)
        qw = quant.quantize_weight4(w)
        got = np.asarray(quant.w4a8_matmul(jnp.asarray(x), qw))
        xq, xs = quant.symmetric_int8(jnp.asarray(x))
        deq = (np.asarray(quant._unpack_nibbles(qw.q, 16, 0),
                          np.float32))
        want = ((np.asarray(xq, np.float32) @ deq)
                * np.asarray(xs) * np.asarray(qw.scale))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_table_direction_and_take_rows(self):
        r = np.random.RandomState(3)
        t = r.randn(11, 16).astype(np.float32)     # odd vocab is fine
        qt = quant.quantize_weight4(t, axis=1)
        assert qt.q.shape == (11, 8) and qt.scale.shape == (11,)
        x = r.randn(3, 16).astype(np.float32)
        got = np.asarray(quant.w4a8_matmul_t(jnp.asarray(x), qt))
        deq = (np.asarray(quant._unpack_nibbles(qt.q, 16, 1),
                          np.float32) * np.asarray(qt.scale)[:, None])
        xq, xs = quant.symmetric_int8(jnp.asarray(x))
        want = (np.asarray(xq, np.float32) @ deq.T) * np.asarray(xs)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        rows = np.asarray(quant.take_rows(qt, jnp.asarray([0, 4, 10])))
        np.testing.assert_allclose(rows, deq[[0, 4, 10]], rtol=1e-6)

    def test_quantize_lm_params_scheme_dispatch(self):
        wf, _ = _lm_workflow()
        p8 = quant.quantize_lm_params(wf.trainer.params,
                                      embed_name="l00_embedding")
        p4 = quant.quantize_lm_params(wf.trainer.params,
                                      embed_name="l00_embedding",
                                      scheme="w4a8")
        w8 = p8["l02_transformer_block"]["mha"]["wq"]
        w4 = p4["l02_transformer_block"]["mha"]["wq"]
        assert isinstance(w8, quant.QuantWeight)
        assert isinstance(w4, quant.QuantWeight4)
        # half the payload bytes again
        assert w4.q.size * 2 == w8.q.size
        assert isinstance(p4["l00_embedding"]["table"],
                          quant.QuantWeight4)
        with pytest.raises(ValueError, match="scheme"):
            quant.quantize_lm_params(wf.trainer.params, scheme="int2")

    def test_min_payload_elems_counts_logical_int4(self):
        """Odd packed axis: the threshold must be the LOGICAL n*m
        element count (what a dense dequant converts), never the
        padded-nibble count above it — or the audit's own threshold
        would hide the exact convert it exists to catch."""
        w = np.random.RandomState(0).randn(17, 8).astype(np.float32)
        tree = {"w": quant.quantize_weight4(w)}
        assert quant.min_payload_elems(tree) == 17 * 8
        assert quant.min_payload_elems(
            {"w": quant.quantize_weight(w)}) == 17 * 8
        with pytest.raises(ValueError, match="no quantized"):
            quant.min_payload_elems({"w": w})

    def test_pytree_roundtrip(self):
        qw = quant.quantize_weight4(np.eye(8, dtype=np.float32))
        leaves, treedef = jax.tree_util.tree_flatten(qw)
        assert len(leaves) == 2
        back = jax.tree_util.tree_unflatten(treedef, leaves)
        assert (back.n, back.axis) == (qw.n, qw.axis)
        np.testing.assert_array_equal(np.asarray(back.q),
                                      np.asarray(qw.q))


# --------------------------------------------------------------------------
# w4a8 end-to-end decode: argmax agreement on decided samples
# --------------------------------------------------------------------------

class TestW4A8Decode:
    def test_argmax_agreement_on_decided_samples(self, f32_precision):
        """The PR 10 export-native methodology: int4 quantization
        legitimately flips near-ties, so gate argmax agreement on the
        positions whose FLOAT top-2 margin clears the measured
        quantization error — those must agree exactly."""
        wf, toks = _lm_workflow(max_epochs=10)
        gen_f = LMGenerator(wf.trainer, max_len=16)
        gen_4 = LMGenerator(wf.trainer, max_len=16, weights="w4a8")
        sf = gen_f.score(toks[:8]).reshape(-1, 13)
        s4 = gen_4.score(toks[:8]).reshape(-1, 13)
        err = np.abs(s4 - sf).max(axis=1)
        top2 = np.sort(sf, axis=1)
        margin = top2[:, -1] - top2[:, -2]
        decided = margin > 4 * err
        assert decided.sum() >= 20, (margin.max(), err.max())
        np.testing.assert_array_equal(s4.argmax(1)[decided],
                                      sf.argmax(1)[decided])

    def test_w4a8_through_the_serving_batcher(self, f32_precision):
        """w4a8 weights ride the continuous batcher (the REST engine's
        decode path) — streams must equal the solo w4a8 decode."""
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16, weights="w4a8")
        cb = ContinuousBatcher(gen, slots=2)
        rid = cb.submit(toks[0, :4].tolist(), 8)
        cb.run_all()
        assert cb.pop_result(rid) == \
            gen.generate(toks[:1, :4], 8)[0].tolist()


# --------------------------------------------------------------------------
# Quantized-pool fused paged decode kernel
# --------------------------------------------------------------------------

def _quant_paged_setup(b=3, hkv=2, g=4, bs=16, nbm=4, hd=64, seed=0):
    from veles_tpu.ops.attention import QuantCache, quantize_kv
    r = np.random.RandomState(seed)
    pool_blocks = b * nbm + 1
    q = jnp.asarray(r.randn(b, hkv * g, hd), jnp.float32)
    kd = jnp.asarray(r.randn(1 + pool_blocks, hkv, bs, hd), jnp.float32)
    vd = jnp.asarray(r.randn(1 + pool_blocks, hkv, bs, hd), jnp.float32)
    pk = QuantCache(*quantize_kv(kd))
    pv = QuantCache(*quantize_kv(vd))
    ids = r.permutation(pool_blocks)[:b * nbm].reshape(b, nbm) + 1
    table = np.zeros((b, nbm), np.int32)
    pos = np.asarray([0, (nbm // 2) * bs + 3, nbm * bs - 1],
                     np.int32)[:b]
    for i in range(b):
        live = pos[i] // bs + 1
        table[i, :live] = ids[i, :live]
    return q, pk, pv, jnp.asarray(table), jnp.asarray(pos)


class TestQuantPagedKernel:
    @pytest.mark.parametrize("g,qdtype,tol", [
        (1, jnp.float32, 2e-5), (4, jnp.float32, 2e-5),
        (4, jnp.bfloat16, 2e-2)])
    def test_interpret_parity_vs_reference(self, g, qdtype, tol):
        """The acceptance pin: the quantized-pool kernel variant ==
        paged_attention_reference over the same QuantCache pools, in
        interpret mode, at staggered per-row lengths."""
        from veles_tpu.ops.pallas.paged import (paged_attention_decode,
                                                paged_attention_reference)
        q, pk, pv, table, pos = _quant_paged_setup(g=g)
        q = q.astype(qdtype)
        ref = paged_attention_reference(q, pk, pv, table, pos)
        out = paged_attention_decode(q, pk, pv, table, pos,
                                     interpret=True)
        assert out.dtype == q.dtype
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=tol, atol=tol)

    def test_dead_blocks_cannot_leak(self):
        """Poison in the dummy block / beyond-pos blocks (data AND
        scales) must not change the quantized kernel's output."""
        from veles_tpu.ops.attention import QuantCache
        from veles_tpu.ops.pallas.paged import paged_attention_decode
        q, pk, pv, table, pos = _quant_paged_setup()
        base = np.asarray(paged_attention_decode(
            q, pk, pv, table, pos, interpret=True), np.float32)
        poison_d = jnp.full(pk.data.shape[1:], 127, jnp.int8)
        poison_s = jnp.full(pk.scale.shape[1:], 1e4, jnp.float32)
        pk2 = QuantCache(pk.data.at[0].set(poison_d),
                         pk.scale.at[0].set(poison_s))
        pv2 = QuantCache(pv.data.at[0].set(poison_d),
                         pv.scale.at[0].set(poison_s))
        live1 = int(pos[1]) // pk.data.shape[2] + 1
        table2 = table.at[1, live1].set(int(table[2, 0]))
        out = np.asarray(paged_attention_decode(
            q, pk2, pv2, table2, pos, interpret=True), np.float32)
        np.testing.assert_allclose(out, base, rtol=1e-6, atol=1e-6)

    def test_vp6xx_registered_and_tuner_resolvable(self, tmp_path,
                                                   monkeypatch):
        """Acceptance: the quantized variant is part of the registered
        VP6xx audit hook (both pool flavors audited) and resolves its
        pool block through tuner.lookup at the int8 dtype key, exactly
        like the bf16 path."""
        from veles_tpu.analysis.numerics_audit import (
            ERROR, audit_pallas_kernels)
        from veles_tpu.ops.pallas import kernel_audit_launches, paged
        launches = [l for l in kernel_audit_launches()
                    if l["kernel"].startswith("paged.decode")]
        kinds = {l["kernel"] for l in launches}
        assert kinds == {"paged.decode", "paged.decode.q8"}, kinds
        q8 = next(l for l in launches
                  if l["kernel"] == "paged.decode.q8")
        block_dtypes = {name: jnp.dtype(dt)
                        for name, _s, dt, *_ in q8["blocks"]}
        assert block_dtypes["k"] == jnp.int8
        assert block_dtypes["k_scale"] == jnp.float32
        # the configured launches audit clean (no ERROR findings)
        findings = audit_pallas_kernels(launches)
        assert not [f for f in findings if f.severity == ERROR], \
            findings

        # tuner resolution at the int8 key
        import veles_tpu.tuner as tuner
        monkeypatch.setenv("VELES_TUNE_CACHE",
                           str(tmp_path / "winners.json"))
        tuner.reset()
        try:
            t = tuner.get_tuner()
            t.record("paged.decode", tuner.paged_shape_key(64, 1),
                     "int8", {"block": 64, "block_g": 32}, 1.0,
                     launches=paged.audit_launch(
                         64, 64, g=32, dtype="int8"))
            assert paged.preferred_pool_block(64, 1, jnp.int8) == 64
            assert paged._resolve_block_g(1, 64, jnp.int8) == 32
            # the bf16 key is untouched -> falls to defaults
            assert paged.preferred_pool_block(
                64, 1, jnp.bfloat16) == 16
        finally:
            tuner.reset()

    def test_quant_sweep_populates_cache(self, tmp_path, monkeypatch):
        """The tune-smoke shape: sweep_paged(dtype='int8') in
        interpret mode must produce a winner at the int8 key with
        zero audit-rejected candidates."""
        import veles_tpu.tuner as tuner
        from veles_tpu.tuner import sweeps
        monkeypatch.setenv("VELES_TUNE_CACHE",
                           str(tmp_path / "winners.json"))
        tuner.reset()
        try:
            res = sweeps.sweep_paged(tuner.get_tuner(), hd=32, g=1,
                                     dtype="int8", iters=1, repeats=1,
                                     warmup=1, interpret=True)
            (_, dtype, _hd), sr = next(iter(res.items()))
            assert dtype == "int8"
            assert sr.winner, sr.candidates
            assert not sr.audit_rejected
            assert "|int8|" in sr.key
            win = tuner.lookup("paged.decode",
                               tuner.paged_shape_key(32, 1), "int8")
            assert win and win["block"] == sr.winner["config"]["block"]
        finally:
            tuner.reset()

    def test_engine_serves_quant_paged_fused(self, f32_precision):
        """End to end: ContinuousEngine + cache_dtype=int8 +
        paged_block runs the fused quantized kernel and serves the
        dense int8 batcher's exact streams."""
        from veles_tpu.services.restful import ContinuousEngine
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16, cache_dtype="int8")
        eng = ContinuousEngine(gen, slots=2, paged_block=4,
                               pool_tokens=48)
        try:
            assert eng.cb.fused
            p = toks[0, :4].tolist()
            got = list(map(int, eng.submit(p, 7)))
            assert got == gen.generate(toks[:1, :4], 7)[0].tolist()
        finally:
            eng.stop()


# --------------------------------------------------------------------------
# Per-row speculative routing: the cliff is gone
# --------------------------------------------------------------------------

class TestPerRowSpecRouting:
    def test_mixed_pool_greedy_rows_byte_identical(self,
                                                   f32_precision):
        """THE acceptance pin: greedy rows in a pool that also holds
        one sampled request produce byte-identical streams to the
        all-greedy pool — one sampled request can no longer perturb
        (or de-speculate) its greedy neighbors."""
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)

        def greedy_streams(with_sampled):
            cb = ContinuousBatcher(gen, slots=3, speculative_k=4)
            g1 = cb.submit(toks[0, :4].tolist(), 8)
            rids = [g1]
            if with_sampled:
                cb.submit(toks[1, :6].tolist(), 4, temperature=0.7,
                          seed=11)
            g2 = cb.submit(toks[2, :3].tolist(), 7)
            rids.append(g2)
            cb.run_all()
            return [cb.pop_result(r) for r in rids]

        assert greedy_streams(True) == greedy_streams(False)

    def test_sampled_row_still_matches_one_token_pool(self,
                                                      f32_precision):
        """The sampled row itself keeps the 1-token pool's bit-exact
        stream (same (seed, position) keys) through the per-row
        routed core."""
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)

        def run(cb):
            rid = cb.submit(toks[1, :6].tolist(), 5, temperature=0.7,
                            seed=11)
            cb.submit(toks[0, :4].tolist(), 8)
            cb.run_all()
            return cb.pop_result(rid)

        assert run(ContinuousBatcher(gen, slots=2, speculative_k=4)) \
            == run(ContinuousBatcher(gen, slots=2))

    def test_no_pool_wide_cond_around_verify(self, f32_precision):
        """Structural pin: the speculative core's jaxpr carries at
        most ONE cond (the draw-cost guard), and the K-wide verify
        (the transformer stack) sits OUTSIDE it — so the verify can
        never be switched pool-wide by one row's temperature."""
        wf, toks = _lm_workflow(max_epochs=0)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2, speculative_k=4)
        core = cb._make_core_spec(4)
        st = cb._state()
        abstract = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (gen.params, st, cb._aids))
        jaxpr = jax.make_jaxpr(core)(*abstract)

        conds = [e for e in jaxpr.jaxpr.eqns
                 if e.primitive.name == "cond"]
        assert len(conds) <= 1, "pool-wide branching is back"
        if conds:
            # the guarded branches must be draw-sized, not
            # transformer-sized: no dot_general inside them (the
            # verify's matmuls all live outside the cond)
            def dots(jx):
                n = sum(1 for e in jx.eqns
                        if e.primitive.name == "dot_general")
                for e in jx.eqns:
                    for key in ("jaxpr", "call_jaxpr"):
                        sub = e.params.get(key)
                        if sub is not None:
                            n += dots(getattr(sub, "jaxpr", sub))
                return n
            for br in conds[0].params["branches"]:
                assert dots(br.jaxpr) == 0, \
                    "model compute inside the sampling cond"


# --------------------------------------------------------------------------
# The stray-dequant audit (acceptance: asserted by a jaxpr scan)
# --------------------------------------------------------------------------

class TestStrayDequantAudit:
    """PR 14's one-off decode-jaxpr assertions, retired into the VD700
    rule (ISSUE 16): the decode path is now audited through
    ``analysis.decode_audit``, which traces the SAME tick body serving
    jits (``ContinuousBatcher._tick_body``) — the rule and this test
    can't drift apart.  The detector-mechanics test below keeps
    pinning ``quant.stray_dequant_sites`` itself, which VD700 wraps."""

    @pytest.mark.parametrize("scheme", ["int8", "w4a8"])
    def test_decode_tick_clean_via_vd700(self, scheme):
        """Acceptance: no QuantWeight dequantizes outside a dot
        anywhere in the decode tick serving would dispatch — and the
        rest of the VD7xx family stays silent on it too."""
        from veles_tpu.analysis import decode_audit
        wf, _ = _lm_workflow()
        gen = LMGenerator(wf.trainer, max_len=16, weights=scheme)
        cb = ContinuousBatcher(gen, slots=2)
        findings = decode_audit.audit_decode_tick(cb)
        assert not [f for f in findings if f.rule == "VD700"], findings
        assert not findings, findings

    def test_prefill_pass_clean_via_vd700(self):
        """The segmented-prefill chunk pass (the other jaxpr serving
        dispatches per admission) is dequant-clean as well."""
        from veles_tpu.analysis import decode_audit
        wf, _ = _lm_workflow()
        gen = LMGenerator(wf.trainer, max_len=16, weights="int8")
        findings = decode_audit.audit_prefill_pass(gen, segment=8)
        assert not findings, findings

    def test_detector_fires_on_naive_dequant(self):
        """The audit must actually detect the bug class it pins: a
        dense dequantize-then-matmul materializes a payload-sized
        float weight outside the dot and must be flagged."""
        r = np.random.RandomState(0)
        qw = quant.quantize_weight(r.randn(32, 16).astype(np.float32))

        def naive(x, q, s):
            w = q.astype(jnp.float32) * s        # dense dequant: BAD
            return x @ w

        jaxpr = jax.make_jaxpr(naive)(
            jax.ShapeDtypeStruct((4, 32), jnp.float32),
            jax.ShapeDtypeStruct(qw.q.shape, jnp.int8),
            jax.ShapeDtypeStruct(qw.scale.shape, jnp.float32))
        sites = quant.stray_dequant_sites(jaxpr, 32 * 16)
        assert sites, "naive dense dequant not detected"
        # while the real funnels pass at the same threshold
        good = jax.make_jaxpr(quant.int8_matmul)(
            jax.ShapeDtypeStruct((4, 32), jnp.float32),
            quant.QuantWeight(
                jax.ShapeDtypeStruct((32, 16), jnp.int8),
                jax.ShapeDtypeStruct((16,), jnp.float32)))
        assert not quant.stray_dequant_sites(good, 32 * 16)


# --------------------------------------------------------------------------
# VN4xx numerics audit over the quantized decode step
# --------------------------------------------------------------------------

class TestQuantStepNumericsAudit:
    @pytest.mark.parametrize("scheme,cache", [("int8", "int8"),
                                              ("w4a8", None)])
    def test_quantized_decode_step_audits_clean(self, scheme, cache):
        """Acceptance: the VN4xx value-range audit over the quantized
        decode step (quantized weights, int8 KV cache for the int8
        leg) reports NOTHING — the quantizers' eps guards and f32
        accumulation keep every log/div/exp provably safe."""
        from veles_tpu.analysis.numerics_audit import audit_numerics_step
        wf, _ = _lm_workflow()
        gen = LMGenerator(wf.trainer, max_len=16, weights=scheme,
                          cache_dtype=cache)
        abstract = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            gen.params, is_leaf=lambda x: hasattr(x, "shape"))
        caches = jax.eval_shape(
            lambda: gen._init_caches(2, gen._model_dtype()))
        findings = audit_numerics_step({
            "fn": gen._step,
            "args": (abstract, caches,
                     jax.ShapeDtypeStruct((2,), jnp.int32), 3),
            "name": "%s-decode" % scheme})
        assert not findings, [str(f) for f in findings]
