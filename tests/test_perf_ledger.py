"""Performance ledger + regression sentinel (telemetry.ledger,
telemetry.perfcli, analysis.perf_lint — docs/perf.md "Performance
ledger & regression sentinel").

Pins the PR's acceptance behaviour: a planted 20%-worse run trips the
sentinel (``perf.regression`` flight event, ``veles-tpu-perf gate``
exit 1 naming the drifted component) while the same run inside the MAD
noise band stays quiet (exit 0); appends are atomic under concurrent
writers and fail-soft on an unwritable path; v0 blob rows migrate;
every bench row lands with its pre-registered target attached; the
VL12xx target-contract lint fires exactly once per orphan."""

import json
import os
import threading

import pytest

from veles_tpu.analysis.findings import ERROR, WARNING
from veles_tpu.analysis.perf_lint import lint_perf
from veles_tpu.telemetry import flight
from veles_tpu.telemetry import ledger as led
from veles_tpu.telemetry import perfcli


def _book(tmp_path, name="led.jsonl"):
    return led.PerfLedger(str(tmp_path / name))


def _seed(book, metric="step_ms", values=(100.0, 100.5, 99.5, 100.2),
          components=True, **kw):
    for v in values:
        comps = None
        if components:
            comps = {"compute_ms": v * 0.6, "host_ms": v * 0.1,
                     "dispatch_ms": v * 0.2, "collective_ms": 0.0,
                     "compile_ms": 0.0}
        book.append(metric, v, workload="train", unit="ms",
                    source="test", components=comps, **kw)


# ====================================================== schema / migration
class TestSchema:
    def test_v0_blob_row_migrates(self, tmp_path):
        path = tmp_path / "led.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"metric": "lm_mfu", "value": 0.3,
                                "when": 123.0}) + "\n")
        recs = led.PerfLedger(str(path)).records()
        assert len(recs) == 1
        rec = recs[0]
        assert rec["schema"] == led.SCHEMA
        assert rec["ts"] == 123.0 and "when" not in rec
        # unkeyed axes default so v0 history groups with v1 appends
        for axis in ("workload", "backend", "mesh", "dtype"):
            assert rec[axis] == "-"

    def test_v0_groups_with_fresh_append_on_same_key(self, tmp_path):
        path = tmp_path / "led.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"metric": "m", "value": 1.0,
                                "when": 1.0}) + "\n")
        book = led.PerfLedger(str(path))
        rec = book.append("m", 2.0, workload="-", backend="-",
                          mesh="-", dtype="-")
        assert rec is not None
        key = led.key_of(rec)
        assert [r["value"] for r in book.records(key=key)] == [1.0, 2.0]

    def test_round_trip_preserves_current_schema(self, tmp_path):
        book = _book(tmp_path)
        rec = book.append("m", 3.0, workload="w", unit="ms",
                          dtype="bf16", source="t", extra_field=7)
        got = book.records(metric="m")[0]
        assert got["schema"] == led.SCHEMA
        assert got["value"] == 3.0 and got["extra_field"] == 7
        assert led.key_of(got) == led.key_of(rec)

    def test_future_schema_and_garbage_lines_survive(self, tmp_path):
        path = tmp_path / "led.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps({"schema": led.SCHEMA + 1,
                                "metric": "m", "value": 9.0}) + "\n")
            f.write("{torn half-line\n")
            f.write("\n")
        recs = led.PerfLedger(str(path)).records()
        assert [r["value"] for r in recs] == [9.0]


# ========================================================= atomic appends
class TestAppend:
    def test_concurrent_writers_interleave_whole_lines(self, tmp_path):
        path = str(tmp_path / "led.jsonl")
        n_per = 100

        def writer(tag):
            book = led.PerfLedger(path)   # one fd-open per append
            for i in range(n_per):
                assert book.append("m", float(i), workload=tag,
                                   assess=False) is not None

        threads = [threading.Thread(target=writer, args=("w%d" % t,))
                   for t in range(2)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        with open(path) as f:
            lines = [ln for ln in f if ln.strip()]
        assert len(lines) == 2 * n_per
        for ln in lines:          # every line parses: no torn writes
            assert isinstance(json.loads(ln), dict)
        recs = led.PerfLedger(path).records()
        assert len(recs) == 2 * n_per

    def test_fail_soft_on_unwritable_path(self, tmp_path):
        blocker = tmp_path / "not_a_dir"
        blocker.write_text("plain file")
        book = led.PerfLedger(str(blocker / "led.jsonl"))
        _seed(book, values=(1.0, 1.0, 1.0, 1.0))
        rec = book.append("step_ms", 2.0, workload="train", unit="ms")
        assert rec is not None          # the run is never failed
        assert book._disk_dead
        # history degraded to in-memory, still assessable
        assert len(book.records(metric="step_ms")) == 5
        assert rec["verdict"]["status"] in ("regression", "ok",
                                            "improved")

    def test_record_value_respects_enabled_knob(self, tmp_path,
                                                monkeypatch):
        from veles_tpu.config import root
        monkeypatch.setenv("VELES_TPU_PERF_LEDGER",
                           str(tmp_path / "led.jsonl"))
        old = root.common.perf.enabled
        try:
            root.common.perf.enabled = False
            assert led.record_value("m", 1.0) is None
            root.common.perf.enabled = True
            rec = led.record_value("m", 1.0)
            assert rec is not None and rec["value"] == 1.0
        finally:
            root.common.perf.enabled = old


# ============================================================== sentinel
class TestSentinel:
    def test_planted_regression_trips_and_names_component(
            self, tmp_path):
        book = _book(tmp_path)
        _seed(book)
        before = len([e for e in flight.recorder.snapshot()
                      if e.get("kind") == "perf.regression"])
        # 20% worse than the ~100 ms history, compute share inflated
        rec = book.append(
            "step_ms", 120.0, workload="train", unit="ms",
            source="test",
            components={"compute_ms": 80.0, "host_ms": 10.0,
                        "dispatch_ms": 20.0, "collective_ms": 0.0,
                        "compile_ms": 0.0})
        v = rec["verdict"]
        assert v["status"] == "regression"
        assert v["component"] == "compute_ms"
        assert v["drift"] == pytest.approx(0.2, rel=0.05)
        events = [e for e in flight.recorder.snapshot()
                  if e.get("kind") == "perf.regression"]
        assert len(events) == before + 1
        assert events[-1]["component"] == "compute_ms"

    def test_in_band_noise_stays_quiet(self, tmp_path):
        book = _book(tmp_path)
        _seed(book)
        # within the 5% min_rel_band floor of the ~100 ms median
        rec = book.append("step_ms", 102.0, workload="train",
                          unit="ms", source="test")
        assert rec["verdict"]["status"] == "ok"

    def test_improvement_is_not_a_regression(self, tmp_path):
        book = _book(tmp_path)
        _seed(book)
        rec = book.append("step_ms", 80.0, workload="train", unit="ms")
        assert rec["verdict"]["status"] == "improved"

    def test_higher_is_better_polarity(self, tmp_path):
        book = _book(tmp_path)
        for v in (100.0, 101.0, 99.0, 100.0):
            book.append("tok_per_s", v, workload="lm", unit="tok/s",
                        better="higher")
        worse = book.append("tok_per_s", 80.0, workload="lm",
                            unit="tok/s", better="higher")
        assert worse["verdict"]["status"] == "regression"

    def test_no_history_below_min_history(self, tmp_path):
        book = _book(tmp_path)
        book.append("m", 1.0, workload="w", unit="ms")
        rec = book.append("m", 99.0, workload="w", unit="ms")
        assert rec["verdict"]["status"] == "no_history"

    def test_drift_gauge_and_regression_counter(self, tmp_path):
        from veles_tpu.telemetry.registry import MetricsRegistry
        reg = MetricsRegistry()
        book = led.PerfLedger(str(tmp_path / "led.jsonl"),
                              registry=reg)
        _seed(book, components=False)
        book.append("step_ms", 150.0, workload="train", unit="ms")
        names = {s["name"]: s for s in reg.snapshot()}
        assert "veles_perf_drift" in names
        assert names["veles_perf_drift"]["labels"] == {
            "metric": "step_ms"}
        assert names["veles_perf_regressions_total"]["value"] == 1

    def test_target_met_event_and_verdict(self, tmp_path):
        book = _book(tmp_path)
        rec = book.append("lm_large_mfu", 0.47, workload="lm_large",
                          unit="MFU", better="higher")
        # declared target (0.44, higher) auto-attached from TARGETS
        assert rec["target"]["id"] == "lm_large_mfu"
        assert rec["verdict"]["target_met"] is True
        miss = book.append("lm_large_mfu", 0.30, workload="lm_large",
                           unit="MFU", better="higher")
        assert miss["verdict"]["target_met"] is False
        met = [e for e in flight.recorder.snapshot()
               if e.get("kind") == "perf.target_met"]
        assert met and met[-1]["met"] is False


# ============================================================ bench rows
class TestBenchIntegration:
    LINE = {"value": 10611.7, "gemm_bf16_mfu": 0.438,
            "lm_large_mfu": 0.369, "serve_int8_vs_bf16_x": 1.133,
            "flash_bwd_vs_xla_x": 1.743, "serve_seg_stall_x": 2.1,
            "serve_cost_vs_rr_x": 1.05, "mlp_step_ms": 4.463,
            "flash_ok": True, "ring_ok": True, "flash_platform": "cpu",
            "beam_ms_per_pos_t4096": 0.0}    # zero = did not run

    def test_every_row_lands_with_its_registered_target(self,
                                                        tmp_path):
        book = _book(tmp_path)
        n = book.append_bench_line(self.LINE)
        recs = book.records()
        assert n == len(recs) == 8       # bools/zeros/strings stay out
        by_metric = {r["metric"]: r for r in recs}
        assert "beam_ms_per_pos_t4096" not in by_metric
        assert "flash_ok" not in by_metric
        for t in led.TARGETS:
            if t.metric in by_metric:
                tgt = by_metric[t.metric]["target"]
                assert tgt == {"id": t.metric, "goal": t.goal,
                               "better": t.better}
        # untargeted rows carry no target
        assert by_metric["mlp_step_ms"]["target"] is None
        # workload axis is the measuring phase
        assert by_metric["lm_large_mfu"]["workload"] == "lm_large"
        assert by_metric["lm_large_mfu"]["source"] == "bench.lm_large"

    def test_migrate_bench_blob_seeds_history(self, tmp_path):
        blob = {"value": 10611.7, "lm_large_mfu": 0.369,
                "flash_bwd_vs_xla_x": 1.743,
                "measured_at": "2026-08-01 10:30:54"}
        recs = led.migrate_bench_blob(blob)
        assert {r["metric"] for r in recs} == {
            "value", "lm_large_mfu", "flash_bwd_vs_xla_x"}
        for r in recs:
            assert r["schema"] == led.SCHEMA
            assert r["ts"] > 0          # parsed measured_at
            assert r["backend"] == "tpu:1"
        tgt = {r["metric"]: r["target"] for r in recs}
        assert tgt["lm_large_mfu"]["goal"] == 0.44
        assert tgt["value"] is None

    def test_last_known_good_reads_back_from_ledger(self, tmp_path):
        book = _book(tmp_path)
        for r in led.migrate_bench_blob(
                {"value": 100.0, "lm_mfu": 0.2,
                 "measured_at": "2026-08-01 10:30:54"}):
            book._write(r)
        book.append_bench_line({"value": 200.0})   # fresh run, now
        lkg = book.last_known_good_line()
        assert lkg["value"] == 200.0        # freshest wins
        assert lkg["lm_mfu"] == 0.2         # older key carried
        assert "lm_mfu" in lkg["carried_from"]   # honestly dated
        assert "value" not in lkg["carried_from"]

    def test_repo_seed_ledger_is_valid(self):
        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        book = led.PerfLedger(os.path.join(repo, "PERF_LEDGER.jsonl"))
        recs = book.records()
        assert recs, "checked-in seed ledger must parse"
        assert all(r["schema"] == led.SCHEMA for r in recs)
        assert {r["metric"] for r in recs} >= {
            "value", "lm_large_mfu", "serve_ms_per_tok_int8"}
        # the seed carries measured history for targeted ratios
        assert book.records(metric="serve_int8_vs_bf16_x")
        assert book.records(metric="flash_bwd_vs_xla_x")

    def test_bench_target_keys_read_from_registry(self):
        import bench
        assert bench._target("serve_int8_vs_bf16_x", 0.0) == 1.5
        assert bench._target("serve_seg_stall_x", 0.0) == 4.0
        assert bench._target("serve_cost_vs_rr_x", 0.0) == 1.0
        assert bench._target("no_such_target", 7.0) == 7.0


# ============================================================ VL12xx lint
class TestPerfLint:
    def test_orphan_target_fires_exactly_once(self, tmp_path):
        recs = [{"schema": 1, "metric": "m", "value": 1.0,
                 "target": {"id": "ghost", "goal": 1.0}},
                {"schema": 1, "metric": "m", "value": 2.0,
                 "target": {"id": "ghost", "goal": 1.0}}]
        findings = lint_perf(targets=(), records=recs)
        orphans = [f for f in findings if f.rule == "VL1201"]
        assert len(orphans) == 1
        assert orphans[0].severity == ERROR
        assert "ghost" in orphans[0].message

    def test_target_never_measured_warns(self):
        findings = lint_perf(records=[])
        never = {f.unit for f in findings if f.rule == "VL1200"}
        assert never == {t.metric for t in led.TARGETS}
        assert all(f.severity == WARNING for f in findings
                   if f.rule == "VL1200")

    def test_measured_target_clears_vl1200(self, tmp_path):
        book = _book(tmp_path)
        book.append("lm_large_mfu", 0.4, workload="lm_large",
                    unit="MFU", better="higher")
        findings = lint_perf(records=book.records())
        assert "lm_large_mfu" not in {
            f.unit for f in findings if f.rule == "VL1200"}

    def test_polarity_conflict_warns_once(self):
        recs = [{"schema": 1, "metric": "lm_large_mfu", "value": 0.4,
                 "better": "lower",
                 "target": {"id": "lm_large_mfu", "goal": 0.44}}] * 3
        findings = lint_perf(records=recs)
        pol = [f for f in findings if f.rule == "VL1203"]
        assert len(pol) == 1

    def test_duplicate_conflicting_declaration(self):
        dup = (led.Target("m", 1.0, "lower", "ms", "a"),
               led.Target("m", 2.0, "lower", "ms", "b"))
        findings = lint_perf(targets=dup, records=[])
        assert any(f.rule == "VL1202" and f.severity == ERROR
                   for f in findings)


# ================================================================= CLI
class TestPerfCli:
    def _regressed_ledger(self, tmp_path):
        book = _book(tmp_path)
        _seed(book)
        book.append("step_ms", 120.0, workload="train", unit="ms",
                    components={"compute_ms": 80.0, "host_ms": 10.0,
                                "dispatch_ms": 20.0,
                                "collective_ms": 0.0,
                                "compile_ms": 0.0})
        return book

    def test_gate_exit_1_names_drifted_component(self, tmp_path,
                                                 capsys):
        book = self._regressed_ledger(tmp_path)
        rc = perfcli.main(["gate", "--ledger", book.path])
        out = capsys.readouterr().out
        assert rc == 1
        assert "VL1210" in out and "compute_ms" in out

    def test_gate_exit_0_inside_noise_band(self, tmp_path, capsys):
        book = _book(tmp_path)
        _seed(book)
        book.append("step_ms", 102.0, workload="train", unit="ms")
        rc = perfcli.main(["gate", "--ledger", book.path])
        assert rc == 0
        # VL1200 never-measured warnings ride along but stay below
        # the default --fail-on error threshold
        assert "VL1200" in capsys.readouterr().out

    def test_gate_fail_on_warning_trips_on_missed_target(
            self, tmp_path, capsys):
        book = _book(tmp_path)
        book.append("lm_large_mfu", 0.30, workload="lm_large",
                    unit="MFU", better="higher")
        assert perfcli.main(["gate", "--ledger", book.path]) == 0
        rc = perfcli.main(["gate", "--ledger", book.path,
                           "--fail-on", "warning"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "VL1211" in out

    def test_report_and_targets_exit_0(self, tmp_path, capsys):
        book = self._regressed_ledger(tmp_path)
        assert perfcli.main(["report", "--ledger", book.path]) == 0
        out = capsys.readouterr().out
        assert "regression" in out
        assert perfcli.main(["targets", "--ledger", book.path]) == 0
        out = capsys.readouterr().out
        assert "lm_large_mfu" in out and "NEVER MEASURED" in out

    def test_report_json_is_parseable(self, tmp_path, capsys):
        book = self._regressed_ledger(tmp_path)
        assert perfcli.main(["report", "--ledger", book.path,
                             "--format", "json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload[0]["verdict"]["status"]

    def test_diff_against_baseline_ledger(self, tmp_path, capsys):
        base = _book(tmp_path, "base.jsonl")
        base.append("m", 100.0, workload="w", unit="ms")
        cur = _book(tmp_path, "cur.jsonl")
        cur.append("m", 110.0, workload="w", unit="ms")
        assert perfcli.main(["diff", "--ledger", cur.path,
                             "--baseline", base.path]) == 0
        assert "+10.0%" in capsys.readouterr().out

    def test_usage_error_exits_2(self):
        with pytest.raises(SystemExit) as exc:
            perfcli.main(["no-such-subcommand"])
        assert exc.value.code == 2
        with pytest.raises(SystemExit) as exc:
            perfcli.main([])
        assert exc.value.code == 2

    def test_lint_cli_perf_flag(self, tmp_path, capsys):
        from veles_tpu.analysis import cli as lint_cli
        path = str(tmp_path / "led.jsonl")
        with open(path, "w") as f:
            f.write(json.dumps(
                {"schema": 1, "metric": "m", "value": 1.0,
                 "target": {"id": "ghost", "goal": 1.0}}) + "\n")
        rc = lint_cli.main(["--perf", "--ledger", path])
        out = capsys.readouterr().out
        assert rc == 1                   # VL1201 orphan is an error
        assert "VL1201" in out


# ==================================================== runtime bank hooks
class TestRuntimeHooks:
    def test_web_status_perf_report(self, tmp_path, monkeypatch):
        monkeypatch.setenv("VELES_TPU_PERF_LEDGER",
                           str(tmp_path / "led.jsonl"))
        _seed(led.PerfLedger(str(tmp_path / "led.jsonl")))
        from veles_tpu.services.web_status import WebStatusServer
        report = WebStatusServer(port=0).perf_report()
        assert report["keys"], report.get("error")
        row = report["keys"][0]
        assert row["metric"] == "step_ms"
        assert len(row["trend"]) == 4
        assert row["verdict"]["status"] in ("ok", "no_history",
                                            "improved", "regression")

    def test_anatomy_components_partition_the_step(self):
        from veles_tpu.telemetry import anatomy
        from veles_tpu.telemetry.registry import MetricsRegistry
        reg = MetricsRegistry()
        comps = anatomy.step_components(object(), steps=10,
                                        wall_s=0.5, registry=reg)
        assert comps is not None
        assert set(comps) == set(anatomy.COMPONENTS)
        step_ms = 0.5 / 10 * 1e3
        assert sum(comps.values()) == pytest.approx(step_ms, abs=0.01)
        assert all(v >= 0.0 for v in comps.values())

    def test_anatomy_floors_priced_by_cost_model(self):
        from veles_tpu.telemetry import anatomy
        floors = anatomy.predicted_floors(steps_per_dispatch=100)
        assert floors["host_ms"] > 0.0
        assert floors["dispatch_ms"] < anatomy.predicted_floors(
            steps_per_dispatch=1)["dispatch_ms"]
