"""Test configuration: force an 8-device virtual CPU platform so sharding
tests exercise real multi-device semantics without TPU hardware (the driver
separately dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

Must run before jax is imported anywhere."""

import os
import sys

# force-override: the session env pins JAX_PLATFORMS to the TPU plugin,
# but the unit-test suite must run on the virtual 8-device CPU platform
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pytest plugins (jaxtyping) import jax before this conftest, freezing the
# env snapshot — override through the live config as well (safe while
# backends are uninitialized)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
