"""Test configuration: force an 8-device virtual CPU platform so sharding
tests exercise real multi-device semantics without TPU hardware (the driver
separately dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

Must run before jax is imported anywhere."""

import os
import sys

# force-override: the session env pins JAX_PLATFORMS to the TPU plugin,
# but the unit-test suite must run on the virtual 8-device CPU platform
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pytest plugins (jaxtyping) import jax before this conftest, freezing the
# env snapshot — override through the live config as well (safe while
# backends are uninitialized)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)


import pytest  # noqa: E402


@pytest.fixture
def f32_precision():
    """Force f32 compute (precision_level 1) for tests that compare two
    computation paths tightly — under the default bf16 policy, different
    matmul groupings alone produce ~1e-2 disagreement."""
    from veles_tpu.config import root
    prev = root.common.engine.get("precision_level", 0)
    root.common.engine.precision_level = 1
    try:
        yield
    finally:
        root.common.engine.precision_level = prev
