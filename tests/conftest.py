"""Test configuration: force an 8-device virtual CPU platform so sharding
tests exercise real multi-device semantics without TPU hardware (the driver
separately dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).

Must run before jax is imported anywhere."""

import os
import sys

# force-override: the session env pins JAX_PLATFORMS to the TPU plugin,
# but the unit-test suite must run on the virtual 8-device CPU platform
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# pytest plugins (jaxtyping) import jax before this conftest, freezing the
# env snapshot — override through the live config as well (safe while
# backends are uninitialized)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax: only the XLA_FLAGS fallback above exists; it is applied
    # as long as no backend was initialized before this conftest ran
    pass


import pytest  # noqa: E402

#: Modules whose every test joins the slow tier (measured on the 1-core
#: CI box, see README "Test tiers": these are the multi-process,
#: compile-heavy, and subprocess-CLI suites).  Individual tests elsewhere
#: opt in with @pytest.mark.slow.  Smoke tier = `pytest -m "not slow"`.
#: BUDGET RULE (README "Test tiers"): the full suite must stay <= 45
#: minutes on the 1-core CI box.  Every NEW slow module must either
#: replace an existing one or document its wall-clock cost here, and
#: each round's session log records a ``--durations=20`` report so
#: creep is visible before it compounds.
SLOW_MODULES = {
    # real multi-process SPMD (jax.distributed over localhost)
    "test_multihost.py",
    # 8-virtual-device shard_map / pjit compile-heavy suites
    "test_parallel.py", "test_pipeline.py",
    "test_seq_parallel_training.py", "test_moe.py",
    # decode/generation: many distinct jit signatures to compile
    "test_generate.py",
    # transformer e2e trainings: 15-54s each on the 1-core CI box
    "test_transformer.py",
    # end-to-end subprocess trainings (fresh jax init per test)
    "test_cli.py", "test_genetics_ensemble.py", "test_elasticity.py",
    # long sweeps / CD-k training loops
    "test_fused_sweep.py", "test_rbm_recurrent.py",
    # r5: two small LM trainings + REST round-trips, ~85 s total
    "test_lora_serving.py",
}


#: Kept in the smoke tier despite living in a slow module — each is the
#: cheapest end-to-end sentinel for a subsystem smoke would otherwise
#: not touch at all.
SMOKE_SENTINELS = {
    "test_transformer_classifier_trains",   # transformer stack e2e
    "test_greedy_generation_continues_pattern",  # KV-cache decode
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.fspath.basename in SLOW_MODULES \
                and item.originalname not in SMOKE_SENTINELS \
                and item.name not in SMOKE_SENTINELS:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def f32_precision():
    """Force f32 compute (precision_level 1) for tests that compare two
    computation paths tightly — under the default bf16 policy, different
    matmul groupings alone produce ~1e-2 disagreement."""
    from veles_tpu.config import root
    prev = root.common.engine.get("precision_level", 0)
    root.common.engine.precision_level = 1
    try:
        yield
    finally:
        root.common.engine.precision_level = prev
