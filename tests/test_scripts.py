"""Tests for the utility scripts: compare_snapshots, generate_frontend,
bboxer."""

import gzip
import io
import json
import pickle

import numpy as np

from veles_tpu.scripts import bboxer, compare_snapshots, generate_frontend


def _write_snapshot(path, state):
    with gzip.open(path, "wb") as f:
        pickle.dump(state, f, protocol=4)


class TestCompareSnapshots:
    def test_identical_and_differing(self, tmp_path):
        a = {"params": {"l0": {"weights": np.ones((2, 2)),
                               "bias": np.zeros(2)}},
             "epoch": 3}
        pa = str(tmp_path / "a.pickle.gz")
        pb = str(tmp_path / "b.pickle.gz")
        _write_snapshot(pa, a)
        _write_snapshot(pb, a)
        out = io.StringIO()
        assert compare_snapshots.compare(pa, pb, out=out) == 0
        assert "match" in out.getvalue()
        b = {"params": {"l0": {"weights": np.ones((2, 2)) * 1.5,
                               "bias": np.zeros(2)}},
             "epoch": 4}
        _write_snapshot(pb, b)
        out = io.StringIO()
        assert compare_snapshots.compare(pa, pb, out=out) == 1
        text = out.getvalue()
        assert "weights" in text and "epoch" in text
        assert "bias" not in text

    def test_structure_mismatch_reported(self, tmp_path):
        pa = str(tmp_path / "a.pickle.gz")
        pb = str(tmp_path / "b.pickle.gz")
        _write_snapshot(pa, {"x": 1})
        _write_snapshot(pb, {"y": 1})
        out = io.StringIO()
        assert compare_snapshots.compare(pa, pb, out=out) == 1
        assert "ONLY IN" in out.getvalue()


class TestGenerateFrontend:
    def test_writes_composer_html(self, tmp_path, capsys):
        out = str(tmp_path / "frontend.html")
        assert generate_frontend.main(["-o", out]) == 0
        html = open(out).read()
        for needle in ("random_seed", "snapshot", "config_list",
                       "command composer", "SPEC ="):
            assert needle in html

    def test_spec_covers_cli_options(self):
        spec = generate_frontend.describe_parser(
            generate_frontend._main_parser())
        dests = {s["dest"] for s in spec}
        assert {"workflow", "config", "random_seed", "test",
                "result_file"} <= dests
        flags = {s["dest"] for s in spec if s["kind"] == "flag"}
        assert "test" in flags and "verbose" in flags


class TestBboxer:
    def test_add_list_export_remove(self, tmp_path, capsys):
        store = str(tmp_path / "ann.json")
        assert bboxer.add(store, "img1.png", "cat", 1, 2, 30, 40) == 1
        assert bboxer.add(store, "img1.png", "dog", 5, 5, 10, 10) == 2
        assert bboxer.add(store, "img2.png", "cat", 0, 0, 3, 3) == 1
        out = io.StringIO()
        assert bboxer.list_boxes(store, out=out) == 3
        assert "img1.png[1]: dog" in out.getvalue()
        exported = str(tmp_path / "out.json")
        assert bboxer.export(store, exported) == 3
        data = json.load(open(exported))
        assert data["img1.png"][0]["label"] == "cat"
        bboxer.remove(store, "img1.png", 0)
        out = io.StringIO()
        assert bboxer.list_boxes(store, "img1.png", out=out) == 1
        import pytest
        with pytest.raises(ValueError):
            bboxer.add(store, "img1.png", "bad", 0, 0, 0, 0)

    def test_cli_main(self, tmp_path, capsys):
        store = str(tmp_path / "ann.json")
        assert bboxer.main(["add", store, "i.png", "cat",
                            "1", "2", "3", "4"]) == 0
        assert bboxer.main(["list", store]) == 0
        assert "cat" in capsys.readouterr().out

    def test_serve_gui_roundtrip(self, tmp_path):
        """The browser annotator (`serve`) drives the SAME store
        functions over HTTP: page loads, images list, add/remove
        round-trip, traversal blocked (ref veles/scripts/bboxer.py —
        the GUI counterpart with the CLI's artifact)."""
        import threading
        import urllib.request

        store = str(tmp_path / "ann.json")
        imgs = tmp_path / "imgs"
        imgs.mkdir()
        (imgs / "a.png").write_bytes(b"\x89PNG fake")
        (imgs / "not_an_image.txt").write_text("no")
        srv = bboxer.serve(store, str(imgs), port=0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        base = "http://127.0.0.1:%d" % srv.server_address[1]

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status, r.read()

        def post(path, obj):
            req = urllib.request.Request(
                base + path, data=json.dumps(obj).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read())

        try:
            status, page = get("/")
            assert status == 200 and b"bboxer" in page
            assert json.loads(get("/api/images")[1]) == ["a.png"]
            assert get("/img/a.png")[1] == b"\x89PNG fake"
            assert post("/api/add", {"image": "a.png", "label": "cat",
                                     "x": 1, "y": 2, "w": 30,
                                     "h": 40}) == {"ok": True,
                                                   "boxes": 1}
            boxes = json.loads(
                get("/api/annotations?image=a.png")[1])
            assert boxes[0]["label"] == "cat" and boxes[0]["w"] == 30
            # the GUI writes the CLI's exact artifact
            out = io.StringIO()
            assert bboxer.list_boxes(store, out=out) == 1
            assert post("/api/remove",
                        {"image": "a.png", "index": 0}) == {"ok": True}
            assert json.loads(
                get("/api/annotations?image=a.png")[1]) == []
            # path traversal is refused
            import urllib.error
            import pytest
            with pytest.raises(urllib.error.HTTPError) as ei:
                get("/img/..%2F..%2Fann.json")
            assert ei.value.code == 404
            # bad add surfaces as 400, not a server crash
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("/api/add", {"image": "a.png", "label": "x",
                                  "x": 0, "y": 0, "w": 0, "h": 0})
            assert ei.value.code == 400
        finally:
            srv.shutdown()
