"""Preemption elasticity (VERDICT r1 #5): SIGKILL a training run
mid-epoch, restart the same command line, and the resumed run's final
metrics must match an uninterrupted run bit-for-bit — the TPU-era
equivalent of the reference's slave respawn + failed-minibatch requeue
(veles/server.py:637-655, loader/base.py:679-687) mapped onto
checkpoint-restart."""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cmd(snap_dir, result, max_epochs=20):
    return [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
            "samples/digits_config.py", "--backend", "cpu",
            "--random-seed", "11",
            "--snapshot", "auto", "--snapshot-every", "1",
            "--config-list", "root.digits.max_epochs=%d" % max_epochs,
            "root.common.dirs.snapshots=%r" % str(snap_dir),
            "--result-file", result]


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONUNBUFFERED="1")

    # reference: one uninterrupted run
    res_a = str(tmp_path / "a.json")
    r = subprocess.run(_cmd(tmp_path / "snap_a", res_a), env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    a = json.load(open(res_a))
    assert a["epochs"] == 20

    # leg 1: same command, SIGKILLed mid-epoch after the 2nd snapshot
    res_b = str(tmp_path / "b.json")
    p = subprocess.Popen(_cmd(tmp_path / "snap_b", res_b), env=env,
                         cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    snapshots_seen = 0
    for line in p.stdout:
        if "snapshot ->" in line:
            snapshots_seen += 1
            if snapshots_seen == 2:
                break
    time.sleep(0.05)           # land inside the next epoch
    p.kill()                   # SIGKILL — no cleanup, no final snapshot
    p.wait()
    assert p.returncode != 0
    assert not os.path.exists(res_b)   # it really died before finishing

    # leg 2: identical command line resumes from <prefix>_current
    r2 = subprocess.run(_cmd(tmp_path / "snap_b", res_b), env=env,
                        cwd=REPO, capture_output=True, text=True,
                        timeout=420)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "[auto-resume]" in r2.stderr and "fresh start" not in r2.stderr
    b = json.load(open(res_b))

    # bit-for-bit: the resumed run converges to the identical result
    assert b["epochs"] == a["epochs"]
    assert b["best_metric"] == a["best_metric"]
    assert b["best_epoch"] == a["best_epoch"]
    assert b["epoch_metrics"] == a["epoch_metrics"]


def test_auto_snapshot_fresh_start(tmp_path):
    """--snapshot auto with no prior snapshot is a clean fresh start."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = str(tmp_path / "r.json")
    r = subprocess.run(_cmd(tmp_path / "snap", res, max_epochs=1), env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "fresh start" in r.stderr
    assert json.load(open(res))["epochs"] == 1
    # and it left a resumable _current behind
    assert os.path.exists(str(tmp_path / "snap" / "digits-mlp_current"))
