"""Preemption elasticity (VERDICT r1 #5): SIGKILL a training run
mid-epoch, restart the same command line, and the resumed run's final
metrics must match an uninterrupted run bit-for-bit — the TPU-era
equivalent of the reference's slave respawn + failed-minibatch requeue
(veles/server.py:637-655, loader/base.py:679-687) mapped onto
checkpoint-restart."""

import json
import os
import subprocess
import sys
import time

from veles_tpu.services.supervisor import run_with_startup_retry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cmd(snap_dir, result, max_epochs=20, snapshot_every=1):
    return [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
            "samples/digits_config.py", "--backend", "cpu",
            "--random-seed", "11",
            "--snapshot", "auto", "--snapshot-every", str(snapshot_every),
            "--config-list", "root.digits.max_epochs=%d" % max_epochs,
            "root.common.dirs.snapshots=%r" % str(snap_dir),
            "--result-file", result]


def test_kill_and_resume_matches_uninterrupted(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONUNBUFFERED="1")

    # reference: one uninterrupted run
    res_a = str(tmp_path / "a.json")
    r = run_with_startup_retry(_cmd(tmp_path / "snap_a", res_a), env=env, cwd=REPO,
                       timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    a = json.load(open(res_a))
    assert a["epochs"] == 20

    # leg 1: same command, SIGKILLed mid-epoch after the 2nd snapshot
    res_b = str(tmp_path / "b.json")
    p = subprocess.Popen(_cmd(tmp_path / "snap_b", res_b), env=env,
                         cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    snapshots_seen = 0
    for line in p.stdout:
        if "snapshot ->" in line:
            snapshots_seen += 1
            if snapshots_seen == 2:
                break
    time.sleep(0.05)           # land inside the next epoch
    p.kill()                   # SIGKILL — no cleanup, no final snapshot
    p.wait()
    assert p.returncode != 0
    assert not os.path.exists(res_b)   # it really died before finishing

    # leg 2: identical command line resumes from <prefix>_current
    r2 = run_with_startup_retry(_cmd(tmp_path / "snap_b", res_b), env=env,
                        cwd=REPO, timeout=420)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "[auto-resume]" in r2.stderr and "fresh start" not in r2.stderr
    b = json.load(open(res_b))

    # bit-for-bit: the resumed run converges to the identical result
    assert b["epochs"] == a["epochs"]
    assert b["best_metric"] == a["best_metric"]
    assert b["best_epoch"] == a["best_epoch"]
    assert b["epoch_metrics"] == a["epoch_metrics"]


def test_auto_snapshot_fresh_start(tmp_path):
    """--snapshot auto with no prior snapshot is a clean fresh start."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = str(tmp_path / "r.json")
    r = run_with_startup_retry(_cmd(tmp_path / "snap", res, max_epochs=1), env=env,
                       cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "fresh start" in r.stderr
    assert json.load(open(res))["epochs"] == 1
    # and it left a resumable _current behind
    assert os.path.exists(str(tmp_path / "snap" / "digits-mlp_current"))


def _read_until(stream, needle, limit=400):
    lines = []
    for line in stream:
        lines.append(line)
        if needle in line:
            return lines
        if len(lines) > limit:
            break
    raise AssertionError("%r not seen in:\n%s" % (needle, "".join(lines)))


def test_sigterm_checkpoints_and_resumes(tmp_path):
    """Graceful preemption: SIGTERM mid-run → the snapshotter fires OFF
    its interval (interval=1000 here, so only the preemption path can
    possibly write) at the next CYCLE — mid-epoch — the process exits
    75, and the identical command resumes from the preemption
    checkpoint to metrics equal to an uninterrupted run: the
    TPU-scheduler maintenance-event story end to end."""
    import signal

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")

    # reference: one uninterrupted run
    res_a = str(tmp_path / "a.json")
    r = run_with_startup_retry(_cmd(tmp_path / "snap_a", res_a, max_epochs=25,
                            snapshot_every=1000), env=env, cwd=REPO,
                       timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    a = json.load(open(res_a))

    snap, res = tmp_path / "snap", str(tmp_path / "r.json")
    cmd = _cmd(snap, res, max_epochs=25, snapshot_every=1000)
    p = subprocess.Popen(cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    _read_until(p.stdout, "epoch 2:")     # past warmup, mid-training
    p.send_signal(signal.SIGTERM)
    out_tail, err_tail = p.communicate(timeout=120)
    assert p.returncode == 75, err_tail + out_tail
    assert "graceful preemption" in err_tail, err_tail
    assert "preemption checkpoint complete" in out_tail, out_tail
    assert os.path.exists(str(snap / "digits-mlp_current"))
    assert not os.path.exists(res) or json.load(open(res)).get(
        "epochs", 0) < 25

    # supervisor-style restart of the identical command line; the
    # mid-epoch checkpoint (loader offset/order, step counter, PRNG)
    # makes the resumed run bit-identical to the uninterrupted one
    r = run_with_startup_retry(cmd, env=env, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "[auto-resume]" in r.stderr and "fresh start" not in r.stderr
    b = json.load(open(res))
    assert b["epochs"] == a["epochs"] == 25
    assert b["best_metric"] == a["best_metric"]
    assert b["epoch_metrics"] == a["epoch_metrics"]


def test_sigterm_without_snapshotter_still_exits_75(tmp_path):
    """No snapshotter unit in the graph: SIGTERM still stops at a unit
    boundary and exits 75 (nothing to checkpoint, supervisor restart
    falls back to the last interval snapshot or a fresh start)."""
    import signal

    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")
    res = str(tmp_path / "r.json")
    cmd = [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
           "samples/digits_config.py", "--backend", "cpu",
           "--random-seed", "11",
           "--config-list", "root.digits.max_epochs=50",
           "--result-file", res]
    p = subprocess.Popen(cmd, env=env, cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True)
    _read_until(p.stdout, "epoch 1:")
    p.send_signal(signal.SIGTERM)
    out_tail, err_tail = p.communicate(timeout=120)
    assert p.returncode == 75, err_tail + out_tail
    assert "no snapshotter" in out_tail, out_tail


def test_death_probability_fault_injection(tmp_path):
    """--death-probability (ref --slave-death-probability,
    client.py:303-307): randomly crash the process mid-run, restart the
    identical command under a supervisor loop, and still converge to
    the uninterrupted run's exact metrics — the full recovery drill,
    with the crashes injected by the framework itself instead of an
    external kill."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")

    res_a = str(tmp_path / "a.json")
    r = run_with_startup_retry(_cmd(tmp_path / "snap_a", res_a, max_epochs=8),
                       env=env, cwd=REPO, timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    a = json.load(open(res_a))

    # supervisor loop: restart-on-failure until clean exit.  The crash
    # is probabilistic, so a drill where injection never fired proves
    # nothing — repeat with doubled p (fresh snapshot dir) until at
    # least one crash was observed; 3 doublings make a zero-crash
    # outcome vanishingly unlikely while each epoch still progresses.
    p, b = 0.004, None
    for round_ in range(3):
        snap = tmp_path / ("snap_b%d" % round_)
        res_b = str(tmp_path / ("b%d.json" % round_))
        crashes = 0
        for attempt in range(60):
            r = run_with_startup_retry(
                _cmd(snap, res_b, max_epochs=8)
                + ["--death-probability", "%g" % p],
                env=env, cwd=REPO, timeout=420)
            if r.returncode == 0:
                break
            assert r.returncode == 1, r.stderr[-1500:]
            assert "fault injection: simulated crash" in r.stdout
            crashes += 1
        else:
            raise AssertionError("never finished under injection")
        if crashes >= 1:
            b = json.load(open(res_b))
            break
        p *= 2
    assert b is not None, "injection never fired across 3 drills " \
        "(p up to %g) — suspiciously quiet" % p
    assert b["epochs"] == a["epochs"]
    assert b["best_metric"] == a["best_metric"]


def test_kill_and_resume_with_orbax_backend(tmp_path):
    """The elasticity story on the orbax sharded backend: periodic
    .orbax directory checkpoints, SIGKILL mid-run, identical command
    resumes from the orbax `_current` to the exact uninterrupted
    metrics — proving --snapshot auto is backend-agnostic."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONUNBUFFERED="1")

    def cmd(snap_dir, result, max_epochs=20):
        # extend _cmd's EXISTING --config-list (a second flag instance
        # would replace the first under argparse nargs="*")
        c = _cmd(snap_dir, result, max_epochs=max_epochs)
        i = c.index("--result-file")
        return c[:i] + ["root.common.snapshot.backend='orbax'"] + c[i:]

    res_a = str(tmp_path / "a.json")
    r = run_with_startup_retry(cmd(tmp_path / "snap_a", res_a),
                       env=env, cwd=REPO,
                       timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    a = json.load(open(res_a))

    res_b = str(tmp_path / "b.json")
    p = subprocess.Popen(cmd(tmp_path / "snap_b", res_b),
                         env=env, cwd=REPO,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True)
    seen = 0
    for line in p.stdout:
        if "snapshot ->" in line:
            assert ".orbax" in line, line
            seen += 1
            if seen == 2:
                break
    p.kill()                 # SIGKILL mid-run, well before epoch 20
    p.wait()
    assert p.returncode != 0
    assert not os.path.exists(res_b)   # really died before finishing

    r2 = run_with_startup_retry(cmd(tmp_path / "snap_b", res_b),
                        env=env, cwd=REPO,
                        timeout=420)
    assert r2.returncode == 0, r2.stderr[-2000:]
    # it must really resume from an .orbax checkpoint — the fresh-start
    # message also contains "[auto-resume]" and a fixed-seed from-
    # scratch run would reproduce the same metrics
    assert "fresh start" not in r2.stderr, r2.stderr[-800:]
    assert ".orbax" in r2.stderr, r2.stderr[-800:]
    b = json.load(open(res_b))
    assert b["epochs"] == a["epochs"]
    assert b["best_metric"] == a["best_metric"]
