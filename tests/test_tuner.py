"""Kernel autotuner (veles_tpu/tuner): winner cache robustness, the
VP6xx audit gate, shape-bucket/mesh keying, launch-path resolution, and
the split dq/dkv backward geometries the tuner exists to search.

The acceptance-pinned contracts:

* an over-VMEM candidate can NEVER win, even with the best measured
  time (the audit gate runs before timing can matter);
* winners persist across processes keyed by (kernel, shape-bucket,
  dtype, mesh);
* ``mesh.refit`` invalidates mesh-keyed winners so degraded pods
  re-tune instead of inheriting full-size configs;
* flash fwd / the split dq/dkv backward kernels / fused paged decode
  all resolve blocks through ``tuner.lookup`` at launch, with config
  overrides still winning.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veles_tpu import tuner as tn
from veles_tpu.tuner import sweeps
from veles_tpu.tuner.cache import WinnerCache, validate_entry


def _mk(tmp_path, **kwargs):
    return tn.KernelTuner(path=str(tmp_path / "winners.json"), **kwargs)


@pytest.fixture
def global_tuner(tmp_path, monkeypatch):
    """Point the process-global tuner (the launch paths' lookup) at a
    fresh tmp cache; restore the pristine global afterwards."""
    monkeypatch.setenv("VELES_TUNE_CACHE",
                       str(tmp_path / "global" / "winners.json"))
    tn.reset()
    tn.set_ambient_mesh(None)
    yield tn.get_tuner()
    tn.reset()
    tn.set_ambient_mesh(None)


# --------------------------------------------------------------------------
# winner cache
# --------------------------------------------------------------------------

class TestWinnerCache:
    def test_roundtrip_across_instances(self, tmp_path):
        """Winners persist across processes: a second tuner on the same
        path (a fresh process, as far as the cache can tell) serves the
        first one's winner."""
        t1 = _mk(tmp_path)
        t1.record("flash.fwd", "t1024_d128", "bfloat16",
                  {"block_q": 256, "block_k": 128}, 1.5, mesh="tpu:4")
        t2 = _mk(tmp_path)
        got = t2.lookup("flash.fwd", "t1024_d128", "bfloat16",
                        mesh="tpu:4")
        assert got == {"block_q": 256, "block_k": 128}
        # a different mesh key is a different winner slot
        assert t2.lookup("flash.fwd", "t1024_d128", "bfloat16",
                         mesh="tpu:8") is None

    def test_corrupt_entry_quarantined_never_served(self, tmp_path):
        path = tmp_path / "winners.json"
        good = {"config": {"block_q": 128}, "ms": 1.0,
                "kernel": "flash.fwd"}
        path.write_text(json.dumps({"version": 1, "winners": {
            "flash.fwd|t128_d64|bfloat16|cpu:1": good,
            "flash.fwd|t256_d64|bfloat16|cpu:1":
                {"config": {"block_q": "not-an-int"}, "ms": 1.0},
            "flash.fwd|t512_d64|bfloat16|cpu:1":
                {"config": {}, "ms": float("nan")},
            "flash.fwd|t999_d64|bfloat16|cpu:1": "just a string",
        }}))
        cache = WinnerCache(str(path))
        assert cache.get("flash.fwd|t128_d64|bfloat16|cpu:1") == good
        for bad in ("t256", "t512", "t999"):
            key = [k for k in cache.quarantined() if bad in k]
            assert key, "corrupt %s entry not quarantined" % bad
            assert cache.get(key[0]) is None
        # quarantine survives a save (forensics, still never served)
        cache.put("new|k|bf16|cpu", {"config": {"b": 1}, "ms": 2.0})
        reloaded = WinnerCache(str(path))
        assert len(reloaded.quarantined()) == 3
        assert reloaded.get("flash.fwd|t256_d64|bfloat16|cpu:1") is None

    def test_corrupt_file_moved_aside(self, tmp_path):
        path = tmp_path / "winners.json"
        path.write_text("{ this is not json")
        cache = WinnerCache(str(path))
        assert len(cache) == 0
        assert os.path.exists(str(path) + ".corrupt")
        # and the cache is usable again
        cache.put("k|s|d|m", {"config": {"b": 8}, "ms": 3.0})
        assert WinnerCache(str(path)).get("k|s|d|m")["ms"] == 3.0

    def test_validate_entry(self):
        assert validate_entry({"config": {"block_q": 128}, "ms": 1.0})
        assert validate_entry({"config": {"block_q": "128"}, "ms": 1})
        assert not validate_entry({"config": {}, "ms": 1.0})
        assert not validate_entry({"ms": 1.0})
        assert not validate_entry({"config": {"b": 1}, "ms": "fast"})
        assert not validate_entry({"config": {"b": 1},
                                   "ms": float("inf")})
        assert not validate_entry([1, 2, 3])

    def test_memory_only_mode(self):
        cache = WinnerCache(None)
        cache.put("k|s|d|m", {"config": {"b": 8}, "ms": 3.0})
        assert cache.get("k|s|d|m")["config"] == {"b": 8}

    def test_concurrent_writers_merge_not_clobber(self, tmp_path):
        """Two tuner processes sharing the cache (e.g. a flash sweep
        and a paged sweep on the same TPU window): each loads once,
        then whole-file saves must MERGE the other's recordings, not
        clobber them — and a deliberate removal must stay removed."""
        path = str(tmp_path / "winners.json")
        a, b = WinnerCache(path), WinnerCache(path)
        a.put("flash|t1|bf16|m", {"config": {"block_q": 1}, "ms": 1.0})
        b.put("paged|h1|bf16|m", {"config": {"block": 8}, "ms": 2.0})
        a.put("flash|t2|bf16|m", {"config": {"block_q": 2}, "ms": 3.0})
        fresh = WinnerCache(path)
        assert set(fresh.items()) == {"flash|t1|bf16|m",
                                      "paged|h1|bf16|m",
                                      "flash|t2|bf16|m"}
        # a removal in one instance survives its later saves even
        # though the other instance's file still holds the key
        a.remove(lambda k, e: k.startswith("paged|"))
        a.put("flash|t3|bf16|m", {"config": {"block_q": 4}, "ms": 4.0})
        assert "paged|h1|bf16|m" not in WinnerCache(path).items()


# --------------------------------------------------------------------------
# the audit gate
# --------------------------------------------------------------------------

def _flash_launches(block_q, block_k, t=8192):
    from veles_tpu.ops.pallas import flash
    return flash.audit_launch(t, t, 128, causal=True, block_q=block_q,
                              block_k=block_k, kernels=("forward",))


class TestAuditGate:
    def test_overvmem_candidate_with_best_time_never_wins(self,
                                                          tmp_path):
        """THE acceptance pin: a candidate whose launch blows the VMEM
        budget is rejected by the VP6xx audit before measurement can
        crown it — plant it with a measured time 100x better than the
        legal candidate and it still loses."""
        tuner = _mk(tmp_path)
        times = {4096: 0.001, 128: 0.1}   # over-VMEM "measures" 100x faster

        cands = [
            {"config": {"block_q": 4096, "block_k": 4096},
             "launches": _flash_launches(4096, 4096)},
            {"config": {"block_q": 128, "block_k": 128},
             "launches": _flash_launches(128, 128)},
        ]
        res = tuner.sweep("flash.fwd", "t8192_d128", "bfloat16", cands,
                          lambda cfg: times[cfg["block_q"]],
                          repeats=2, warmup=1)
        assert res.winner["config"] == {"block_q": 128, "block_k": 128}
        verdicts = {c["config"]["block_q"]: c["verdict"]
                    for c in res.candidates}
        assert verdicts[4096] == "audit_rejected"
        assert any("VP602" in f for c in res.audit_rejected
                   for f in c["findings"])
        # and the persisted winner is the audited one
        assert _mk(tmp_path).lookup(
            "flash.fwd", "t8192_d128", "bfloat16")["block_q"] == 128

    def test_record_refuses_unaudited_config(self, tmp_path):
        tuner = _mk(tmp_path)
        with pytest.raises(ValueError, match="VP6xx"):
            tuner.record("flash.fwd", "t8192_d128", "bfloat16",
                         {"block_q": 4096, "block_k": 4096}, 0.001,
                         launches=_flash_launches(4096, 4096))
        assert tuner.lookup("flash.fwd", "t8192_d128",
                            "bfloat16") is None

    def test_all_rejected_means_no_winner(self, tmp_path):
        tuner = _mk(tmp_path, vmem_kib=1)   # nothing fits 1 KiB
        res = tuner.sweep(
            "flash.fwd", "t128_d64", "bfloat16",
            [{"config": {"block_q": 128, "block_k": 128},
              "launches": _flash_launches(128, 128, t=128)}],
            lambda cfg: 0.001, repeats=1, warmup=0)
        assert res.winner is None
        assert len(res.audit_rejected) == 1

    def test_repeats_clamped_to_one(self, tmp_path):
        """--repeats 0 must not crash median([]) after the warm-ups
        already ran — it clamps to one sample."""
        tuner = _mk(tmp_path)
        res = tuner.sweep(
            "flash.fwd", "t128_d64", "bfloat16",
            [{"config": {"block_q": 128, "block_k": 128},
              "launches": _flash_launches(128, 128, t=128)}],
            lambda cfg: 0.002, repeats=0, warmup=0)
        assert res.winner is not None

    def test_failed_measurement_is_not_a_winner(self, tmp_path):
        tuner = _mk(tmp_path)

        def measure(cfg):
            if cfg["block_q"] == 256:
                raise RuntimeError("VMEM overflow on chip")
            return 0.01
        res = tuner.sweep(
            "flash.fwd", "t128_d64", "bfloat16",
            [{"config": {"block_q": 256, "block_k": 128},
              "launches": _flash_launches(256, 128, t=256)},
             {"config": {"block_q": 128, "block_k": 128},
              "launches": _flash_launches(128, 128, t=256)}],
            measure, repeats=1, warmup=0)
        assert res.winner["config"]["block_q"] == 128
        verdicts = {c["config"]["block_q"]: c["verdict"]
                    for c in res.candidates}
        assert verdicts[256] == "failed"


# --------------------------------------------------------------------------
# keying: shape buckets + mesh
# --------------------------------------------------------------------------

class TestKeying:
    def test_shape_bucket_pow2(self):
        assert tn.flash_shape_key(1000, 128) == "t1024_d128"
        assert tn.flash_shape_key(1024, 128) == "t1024_d128"
        assert tn.flash_shape_key(1025, 64) == "t2048_d64"
        assert tn.flash_shape_key(7, 64) == "t128_d64"   # floor

    def test_bucketed_lookup_shares_winner(self, tmp_path):
        tuner = _mk(tmp_path)
        tuner.record("flash.fwd", tn.flash_shape_key(1024, 128),
                     "bfloat16", {"block_q": 256, "block_k": 256}, 2.0)
        # a ragged T in the same bucket hits ...
        assert tuner.lookup("flash.fwd", tn.flash_shape_key(1000, 128),
                            "bfloat16")["block_q"] == 256
        # ... the next bucket (and another dtype) miss
        assert tuner.lookup("flash.fwd", tn.flash_shape_key(2048, 128),
                            "bfloat16") is None
        assert tuner.lookup("flash.fwd", tn.flash_shape_key(1024, 128),
                            "float32") is None

    def test_mesh_descriptor_axes(self):
        assert tn.mesh_descriptor("tpu:v4:8") == "tpu:v4:8"
        # explicit axes key by the TOPOLOGY's device total + axes
        d = tn.mesh_descriptor({"data": 4, "model": 2})
        assert d.endswith(":8/data4xmodel2")
        # the default (launch-time AND sweep-time) key carries no axes
        # even while the launcher has an ambient mesh registered — a
        # CLI-swept winner must be reachable from a launcher run
        tn.set_ambient_mesh({"data": 4})
        try:
            assert "/" not in tn.mesh_descriptor()
        finally:
            tn.set_ambient_mesh(None)

    def test_mesh_refit_invalidates_configured_entries(self, tmp_path):
        """PR 10's elastic resize: winners tuned at the configured
        (full) topology are dropped on refit, and subsequent ambient
        lookups key to the fitted topology — a degraded pod re-tunes
        instead of inheriting full-size configs."""
        tuner = _mk(tmp_path)
        full, degraded = {"data": 4}, {"data": 3}
        tuner.record("flash.fwd", "t1024_d128", "bfloat16",
                     {"block_q": 512, "block_k": 512}, 1.0, mesh=full)
        tuner.record("flash.fwd", "t1024_d128", "bfloat16",
                     {"block_q": 128, "block_k": 128}, 9.9,
                     mesh="other:topology")
        assert tuner.lookup("flash.fwd", "t1024_d128", "bfloat16",
                            mesh=full) is not None

        gone = tuner.invalidate_mesh(full)
        assert len(gone) == 1
        assert tuner.lookup("flash.fwd", "t1024_d128", "bfloat16",
                            mesh=full) is None
        assert tuner.lookup("flash.fwd", "t1024_d128", "bfloat16",
                            mesh=degraded) is None
        # the unrelated topology's winner survives
        assert tuner.lookup("flash.fwd", "t1024_d128", "bfloat16",
                            mesh="other:topology") is not None

    def test_on_mesh_refit_invalidates_both_key_forms(self,
                                                      global_tuner):
        full, degraded = {"data": 4}, {"data": 3}
        # explicit (axes-form) recording, e.g. a pod tool's
        global_tuner.record("flash.fwd", "t1024_d128", "bfloat16",
                            {"block_q": 512, "block_k": 512}, 1.0,
                            mesh=full)
        # launch-time recordings carry the bare backend:count form at
        # the CONFIGURED (full) device total — simulate one
        bare_full = tn.mesh_descriptor(full).split("/", 1)[0]
        global_tuner.record("flash.fwd", "t1024_d128", "bfloat16",
                            {"block_q": 256, "block_k": 256}, 1.0,
                            mesh=bare_full)
        tn.on_mesh_refit(full, degraded)
        # BOTH full-size entries are gone (the live device count has
        # already shrunk when the hook fires, so the invalidation must
        # key off the configured topology, not the live backend)
        assert global_tuner.lookup("flash.fwd", "t1024_d128",
                                   "bfloat16", mesh=full) is None
        assert global_tuner.lookup("flash.fwd", "t1024_d128",
                                   "bfloat16", mesh=bare_full) is None
        assert tn.ambient_axes() == degraded
        # a wildcard configured topology has no knowable pre-refit
        # device total: nothing is invalidated (the launcher never
        # refits one — fitted == configured there), ambient re-keys
        global_tuner.record("flash.fwd", "t1024_d128", "bfloat16",
                            {"block_q": 128, "block_k": 128}, 1.0,
                            mesh="cpu:8")
        assert tn.on_mesh_refit({"data": -1}, {"data": 2}) == []
        assert global_tuner.lookup("flash.fwd", "t1024_d128",
                                   "bfloat16", mesh="cpu:8") is not None


# --------------------------------------------------------------------------
# launch-path resolution (flash + paged)
# --------------------------------------------------------------------------

class TestLaunchResolution:
    def test_flash_bwd_blocks_resolve_tuner_winner(self, global_tuner):
        from veles_tpu.ops.pallas import flash
        key = tn.flash_shape_key(256, 128)
        global_tuner.record("flash.bwd_dq", key, "bfloat16",
                            {"block_q": 64, "block_k": 128}, 1.0)
        global_tuner.record("flash.bwd_dkv", key, "bfloat16",
                            {"block_q": 128, "block_k": 64}, 1.0)
        blocks = flash._resolve_blocks(256, 256, 128, jnp.bfloat16)
        assert blocks[2:] == (64, 128, 128, 64)
        # deterministic under interpret mode: same key, same answer
        assert flash._resolve_blocks(256, 256, 128,
                                     jnp.bfloat16) == blocks

    def test_cross_attention_dkv_keys_by_tk(self, global_tuner):
        """In cross-attention (tq != tk) the dkv grid walks the KEY
        axis, so its winner comes from the tk bucket while fwd/dq key
        by tq."""
        from veles_tpu.ops.pallas import flash
        global_tuner.record("flash.bwd_dq",
                            tn.flash_shape_key(128, 128), "bfloat16",
                            {"block_q": 32, "block_k": 64}, 1.0)
        global_tuner.record("flash.bwd_dkv",
                            tn.flash_shape_key(8192, 128), "bfloat16",
                            {"block_q": 64, "block_k": 512}, 1.0)
        blocks = flash._resolve_blocks(128, 8192, 128, jnp.bfloat16)
        assert blocks[2:4] == (32, 64)       # dq: tq bucket
        assert blocks[4:6] == (64, 512)      # dkv: tk bucket

    def test_block_g_config_grammar_never_raises(self, global_tuner):
        """serve.paged_block_g with a non-int value (natural by
        analogy with paged_block="auto") falls through to the tuner —
        the audit hook and every decode trace reach this."""
        from veles_tpu.config import root
        from veles_tpu.ops.pallas import paged
        global_tuner.record("paged.decode", tn.paged_shape_key(64, 1),
                            "float32", {"block": 16, "block_g": 32},
                            1.0)
        for val in ("auto", "", "off"):
            root.common.serve.paged_block_g = val
            try:
                assert paged._resolve_block_g(
                    1, 64, jnp.float32) == 32, val
            finally:
                del root.common.serve.paged_block_g

    def test_explicit_and_config_beat_tuner(self, global_tuner):
        from veles_tpu.config import root
        from veles_tpu.ops.pallas import flash
        key = tn.flash_shape_key(256, 128)
        global_tuner.record("flash.bwd_dq", key, "bfloat16",
                            {"block_q": 64, "block_k": 64}, 1.0)
        # explicit argument
        blocks = flash._resolve_blocks(256, 256, 128, jnp.bfloat16,
                                       block_q_dq=32)
        assert blocks[2] == 32
        # site config
        root.common.engine.flash.block_q_dq = 16
        try:
            blocks = flash._resolve_blocks(256, 256, 128, jnp.bfloat16)
            assert blocks[2] == 16
        finally:
            del root.common.engine.flash.block_q_dq

    def test_paged_pool_block_and_group_resolve(self, global_tuner):
        from veles_tpu.config import root
        from veles_tpu.ops.pallas import paged
        global_tuner.record("paged.decode", tn.paged_shape_key(64, 1),
                            "float32", {"block": 32, "block_g": 32},
                            1.0)
        assert paged.preferred_pool_block(64, 1, jnp.float32) == 32
        # the serve grammar's non-pinning values ("auto"/-1, dense
        # markers, garbage) must fall through to the tuner, never pin
        # (or crash the audit hook) — ONE grammar with the engine
        for val in ("auto", -1, "", "off", -2, "fast"):
            root.common.serve.paged_block = val
            try:
                assert paged.preferred_pool_block(
                    64, 1, jnp.float32) == 32, val
            finally:
                del root.common.serve.paged_block
        root.common.serve.paged_block = 8
        try:
            assert paged.preferred_pool_block(64, 1, jnp.float32) == 8
        finally:
            del root.common.serve.paged_block
        assert paged._resolve_block_g(1, 64, jnp.float32) == 32
        # untuned shapes fall back to the current defaults
        assert paged.preferred_pool_block(96, 1, jnp.float32) == 16
        assert paged._resolve_block_g(1, 96, jnp.float32) == \
            paged._MIN_G
        # a tuned pad can never shrink below the real group / sublane
        global_tuner.record("paged.decode", tn.paged_shape_key(64, 24),
                            "float32", {"block": 16, "block_g": 8},
                            1.0)
        assert paged._resolve_block_g(24, 64, jnp.float32) == 24

    def test_parse_paged_block_grammar(self):
        """serve.paged_block: off / explicit block / "auto" (paged,
        block through config > tuner > default) — the grammar that
        makes a tuned pool block reachable from `--serve`."""
        from veles_tpu.models.generate import parse_paged_block
        assert parse_paged_block(0) == (False, None)
        assert parse_paged_block("") == (False, None)
        assert parse_paged_block(None) == (False, None)
        assert parse_paged_block("off") == (False, None)
        assert parse_paged_block(16) == (True, 16)
        assert parse_paged_block("8") == (True, 8)
        assert parse_paged_block("auto") == (True, None)
        assert parse_paged_block(-1) == (True, None)

    def test_flash_runs_with_tuned_bwd_winner(self, global_tuner):
        """End to end under interpret mode: plant asymmetric dq/dkv
        winners, run the fused backward through the normal launch
        path, pin the gradients to the recompute oracle."""
        from veles_tpu.ops.pallas import flash
        key = tn.flash_shape_key(96, 32)
        global_tuner.record("flash.bwd_dq", key, "float32",
                            {"block_q": 64, "block_k": 16}, 1.0)
        global_tuner.record("flash.bwd_dkv", key, "float32",
                            {"block_q": 16, "block_k": 64}, 1.0)
        k0 = jax.random.key(0)
        q, k, v = (jax.random.normal(kk, (1, 2, 96, 32)) * 0.5
                   for kk in jax.random.split(k0, 3))

        def loss(fn):
            return lambda *a: jnp.sum(fn(*a) ** 2)
        g_tuned = jax.grad(loss(lambda q, k, v: flash.flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32,
            interpret=True)), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss(lambda q, k, v: flash.flash_attention(
            q, k, v, causal=True, backward="recompute",
            interpret=True)), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_tuned, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)


# --------------------------------------------------------------------------
# split dq/dkv geometry regression (odd T, blocks straddling the tail)
# --------------------------------------------------------------------------

@pytest.mark.parametrize("t", [67, 129, 193])
@pytest.mark.parametrize("bwd_blocks", [
    (32, 64, 64, 32),      # dq wide-k, dkv wide-q
    (64, 16, 16, 64),      # extreme asymmetry
])
def test_split_bwd_geometry_odd_t(t, bwd_blocks):
    """The new independent dq/dkv grids over ragged T: every (block_q,
    block_k) pairing must mask its tail exactly — fused gradients match
    the recompute oracle bit-for-tolerance, the same `_block_live`
    contract the forward liveness suite pins, now per backward grid."""
    from veles_tpu.ops import attention as att
    bq_dq, bk_dq, bq_dkv, bk_dkv = bwd_blocks
    k0 = jax.random.key(t)
    q, k, v = (jax.random.normal(kk, (1, 2, t, 16)) * 0.5
               for kk in jax.random.split(k0, 3))

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a) ** 2)
    g_split = jax.grad(loss(lambda q, k, v: att.flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32,
        block_q_dq=bq_dq, block_k_dq=bk_dq, block_q_dkv=bq_dkv,
        block_k_dkv=bk_dkv, interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss(lambda q, k, v: att.flash_attention(
        q, k, v, causal=True, backward="recompute", interpret=True)),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_split, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4)


# --------------------------------------------------------------------------
# sweeps + telemetry + CLI (in-process)
# --------------------------------------------------------------------------

class TestSweepsAndCli:
    def test_interpret_sweep_populates_cache_deterministically(
            self, tmp_path):
        """The CI tune-smoke contract in miniature: an interpret-mode
        sweep on tiny shapes produces an audited winner, persists it,
        and lookups serve it deterministically."""
        tuner = _mk(tmp_path)
        res = sweeps.sweep_flash(tuner, ts=(128,), d=64,
                                 kinds=("fwd",), iters=1, repeats=2,
                                 warmup=1, interpret=True)
        r = res[("fwd", 128)]
        assert r.winner is not None
        assert not r.audit_rejected
        got = [_mk(tmp_path).lookup("flash.fwd",
                                    tn.flash_shape_key(128, 64),
                                    "bfloat16") for _ in range(2)]
        assert got[0] == got[1] == r.winner["config"]

    def test_candidate_grids(self):
        # d=128: the flashtune grid; d<=64 widens to 1024 blocks
        c128 = sweeps.flash_candidates("fwd", 8192, 128)
        assert {tuple(sorted(c["config"].values())) for c in c128} == {
            (128, 128), (128, 256), (128, 512), (256, 256),
            (256, 512), (512, 512)}
        c64 = sweeps.flash_candidates("bwd_dq", 8192, 64)
        assert any(c["config"]["block_q"] == 1024 for c in c64)
        # every candidate audits the kernel it tunes, nothing else
        assert all(len(c["launches"]) == 1
                   and c["launches"][0]["kernel"] == "flash.bwd_dq"
                   for c in c64)
        # blocks never exceed the padded sequence length
        tiny = sweeps.flash_candidates("fwd", 128, 64)
        assert all(max(c["config"].values()) <= 128 for c in tiny)

    def test_lookup_flight_events_and_gauge(self, tmp_path):
        from veles_tpu import telemetry
        tuner = _mk(tmp_path)
        # unique shape keys: the bounded flight ring may be full of
        # other suites' events, so match OURS by key, not by position
        shape = "t128_d64_tunertest%d" % os.getpid()
        tuner.record("flash.fwd", shape, "bfloat16",
                     {"block_q": 128, "block_k": 128}, 1.0)
        tuner.lookup("flash.fwd", shape, "bfloat16")
        tuner.lookup("flash.fwd", shape + "_absent", "bfloat16")
        kinds = {e["kind"] for e in telemetry.flight.recorder.snapshot()
                 if shape in str(e.get("key", ""))}
        assert "tune.hit" in kinds and "tune.miss" in kinds
        gauges = {m.name: m for m in telemetry.registry.metrics()}
        assert "veles_tune_winners" in gauges
        assert "veles_tune_lookups_total" in gauges

    def test_cli_sweep_list_clear(self, tmp_path, capsys):
        from veles_tpu.tuner import cli
        cache = str(tmp_path / "winners.json")
        report = str(tmp_path / "report.json")
        rc = cli.main(["--cache", cache, "sweep", "--tiny",
                       "--kernels", "flash.fwd", "--json", report])
        assert rc == 0
        rep = json.load(open(report))
        assert rep["sweeps"] and all(
            s["winner"] and s["audit_rejected"] == 0
            for s in rep["sweeps"])
        rc = cli.main(["--cache", cache, "list", "--require-winners"])
        assert rc == 0
        assert "flash.fwd" in capsys.readouterr().out
        assert cli.main(["--cache", cache, "clear"]) == 0
        assert cli.main(["--cache", cache, "list",
                         "--require-winners"]) == 1

    def test_cli_dry_run_prints_verdicts(self, tmp_path, capsys):
        from veles_tpu.tuner import cli
        cache = str(tmp_path / "winners.json")
        rc = cli.main(["--cache", cache, "sweep", "--dry-run",
                       "--kernels", "flash.fwd", "--t", "256"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "ok" in out
        # nothing persisted
        assert not os.path.exists(cache)

    def test_bake_tool_imports_into_cache(self, tmp_path, monkeypatch,
                                          capsys):
        """tools/bake_flashtune.py re-pointed at the tuner cache: a
        legacy grid imports per-kernel winners; an over-VMEM winner in
        the log is REFUSED by the audit gate."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "bake_flashtune", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "tools", "bake_flashtune.py"))
        bake = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bake)

        grid = {"t1024_q128_k128": {"ms": 2.0, "ms_dq": 3.0,
                                    "ms_dkv": 4.5},
                "t1024_q256_k128": {"ms": 1.8, "ms_dq": 3.5,
                                    "ms_dkv": 4.0}}
        sweep_file = tmp_path / "sweep.json"
        sweep_file.write_text(json.dumps(grid))
        cache = str(tmp_path / "winners.json")
        monkeypatch.setattr(
            "sys.argv", ["bake_flashtune.py", str(sweep_file),
                         "--cache", cache, "--mesh", "tpu:test"])
        bake.main()
        tuner = tn.KernelTuner(path=cache)
        assert tuner.lookup("flash.fwd", "t1024_d128", "bfloat16",
                            mesh="tpu:test") == {"block_q": 256,
                                                 "block_k": 128}
        assert tuner.lookup("flash.bwd_dq", "t1024_d128", "bfloat16",
                            mesh="tpu:test") == {"block_q": 128,
                                                 "block_k": 128}
        # over-VMEM "winner" (fastest in the grid) is refused
        bad = {"t8192_q4096_k4096": {"ms": 0.1, "ms_dq": 0.1,
                                     "ms_dkv": 0.1}}
        bad_file = tmp_path / "bad.json"
        bad_file.write_text(json.dumps(bad))
        monkeypatch.setattr(
            "sys.argv", ["bake_flashtune.py", str(bad_file),
                         "--cache", cache, "--mesh", "tpu:test"])
        with pytest.raises(SystemExit):
            bake.main()
        assert "REFUSED" in capsys.readouterr().out
        assert tuner.lookup("flash.fwd", "t8192_d128", "bfloat16",
                            mesh="tpu:test") is None
