"""Golden numeric tests for the ops library — the TPU equivalent of the
reference's kernel-vs-numpy golden tests (AcceleratedTest pattern,
SURVEY.md §4): every op is checked against a straightforward numpy
re-implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.ops import (activations, conv, dropout, linear, losses, lrn,
                           misc, pooling)
from veles_tpu.ops.policy import Policy

F32 = Policy(compute=jnp.float32)  # exact-compare policy for golden tests
RNG = np.random.default_rng(42)


class TestLinear:
    def test_forward_matches_numpy(self):
        x = RNG.normal(size=(8, 20)).astype(np.float32)
        w = RNG.normal(size=(20, 10)).astype(np.float32)
        b = RNG.normal(size=(10,)).astype(np.float32)
        got = linear.forward({"weights": jnp.array(w), "bias": jnp.array(b)},
                             jnp.array(x), F32)
        np.testing.assert_allclose(np.asarray(got), x @ w + b, rtol=1e-5)

    def test_flattens_nd_input(self):
        x = RNG.normal(size=(4, 5, 5, 2)).astype(np.float32)
        w = RNG.normal(size=(50, 3)).astype(np.float32)
        got = linear.forward({"weights": jnp.array(w)}, jnp.array(x), F32)
        np.testing.assert_allclose(
            np.asarray(got), x.reshape(4, -1) @ w, rtol=1e-5)

    def test_bf16_policy_accumulates_f32(self):
        x = jnp.ones((4, 256))
        w = jnp.ones((256, 8)) * 0.01
        got = linear.forward({"weights": w}, x, Policy())
        assert got.dtype == jnp.float32
        # 256 * 0.01 = 2.56; pure-bf16 accumulation would lose ~1% here
        np.testing.assert_allclose(np.asarray(got), 2.56, rtol=2e-2)

    def test_init_params(self):
        p = linear.init_params(prng.RandomGenerator("t", 0), 100, 10)
        assert p["weights"].shape == (100, 10)
        assert np.abs(p["weights"]).max() <= 0.1 + 1e-6  # 1/sqrt(100)
        assert p["bias"].shape == (10,)


class TestActivations:
    def test_scaled_tanh(self):
        x = np.linspace(-3, 3, 7).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(activations.tanh(jnp.array(x))),
            1.7159 * np.tanh(0.6666 * x), rtol=1e-6)

    def test_veles_relu_is_softplus(self):
        x = np.array([-5.0, 0.0, 5.0], np.float32)
        np.testing.assert_allclose(
            np.asarray(activations.relu(jnp.array(x))),
            np.log1p(np.exp(x)), rtol=1e-5)

    def test_strict_relu(self):
        x = np.array([-1.0, 2.0], np.float32)
        np.testing.assert_array_equal(
            np.asarray(activations.strict_relu(jnp.array(x))), [0.0, 2.0])

    def test_log_is_asinh(self):
        x = np.array([-2.0, 0.5], np.float32)
        np.testing.assert_allclose(
            np.asarray(activations.log(jnp.array(x))), np.arcsinh(x),
            rtol=1e-6)

    def test_sincos_alternates(self):
        x = RNG.normal(size=(2, 6)).astype(np.float32)
        got = np.asarray(activations.sincos(jnp.array(x)))
        np.testing.assert_allclose(got[:, 0::2], np.sin(x[:, 0::2]), rtol=1e-6)
        np.testing.assert_allclose(got[:, 1::2], np.cos(x[:, 1::2]), rtol=1e-6)

    def test_registry_complete(self):
        for name in ("linear", "tanh", "sigmoid", "relu", "strict_relu",
                     "log", "tanhlog", "sincos"):
            assert name in activations.ACTIVATIONS


class TestConv:
    def test_valid_conv_matches_manual(self):
        x = RNG.normal(size=(2, 5, 5, 3)).astype(np.float32)
        k = RNG.normal(size=(3, 3, 3, 4)).astype(np.float32)
        got = np.asarray(conv.forward({"weights": jnp.array(k)},
                                      jnp.array(x), policy=F32))
        assert got.shape == (2, 3, 3, 4)
        # manual correlation at output (0,0)
        want00 = np.einsum("hwc,hwck->k", x[0, :3, :3, :], k)
        np.testing.assert_allclose(got[0, 0, 0], want00, rtol=1e-4)

    def test_explicit_padding_tuple(self):
        x = jnp.ones((1, 4, 4, 1))
        k = jnp.ones((3, 3, 1, 1))
        y = conv.forward({"weights": k}, x, padding=(1, 1, 1, 1), policy=F32)
        assert y.shape == (1, 4, 4, 1)

    def test_deconv_inverts_shape(self):
        x = jnp.ones((1, 4, 4, 2))
        k = jnp.ones((2, 2, 2, 3))
        y = conv.forward({"weights": k}, x, stride=(2, 2), policy=F32)
        assert y.shape == (1, 2, 2, 3)
        back = conv.deconv_forward(
            {"weights": jnp.ones((2, 2, 3, 2))}, y, stride=(2, 2),
            policy=F32)
        assert back.shape == (1, 4, 4, 2)


class TestPooling:
    x = RNG.normal(size=(2, 4, 4, 3)).astype(np.float32)

    def test_max_pool(self):
        got = np.asarray(pooling.max_pool(jnp.array(self.x), 2, 2))
        want = self.x.reshape(2, 2, 2, 2, 2, 3).max(axis=(2, 4))
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_avg_pool(self):
        got = np.asarray(pooling.avg_pool(jnp.array(self.x), 2, 2))
        want = self.x.reshape(2, 2, 2, 2, 2, 3).mean(axis=(2, 4))
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_max_abs_keeps_sign(self):
        x = np.array([[[[-5.0], [1.0]], [[2.0], [3.0]]]], np.float32)
        got = np.asarray(pooling.max_abs_pool(jnp.array(x), 2, 2))
        assert got[0, 0, 0, 0] == -5.0

    def test_stochastic_pool_picks_window_elements(self):
        key = jax.random.key(0)
        xs = jnp.array(np.abs(self.x))
        got = np.asarray(pooling.stochastic_pool(xs, 2, 2, key))
        # every output must be an element of its window
        patches = np.abs(self.x).reshape(2, 2, 2, 2, 2, 3)
        for n in range(2):
            for i in range(2):
                for j in range(2):
                    for c in range(3):
                        window = patches[n, i, :, j, :, c].ravel()
                        assert got[n, i, j, c] in window

    def test_stochastic_pool_reproducible(self):
        key = jax.random.key(7)
        a = pooling.stochastic_pool(jnp.array(self.x), 2, 2, key,
                                    absolute=True)
        b = pooling.stochastic_pool(jnp.array(self.x), 2, 2, key,
                                    absolute=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_stochastic_infer_is_weighted_mean(self):
        x = np.array([[[[1.0], [3.0]], [[0.0], [0.0]]]], np.float32)
        got = np.asarray(pooling.stochastic_pool_infer(jnp.array(x), 2, 2))
        np.testing.assert_allclose(got[0, 0, 0, 0], (1 + 9) / 4.0)

    def test_depool_upsamples(self):
        y = np.asarray(pooling.depool(jnp.array(self.x), 2, 2))
        assert y.shape == (2, 8, 8, 3)
        assert (y[:, ::2, ::2] == self.x).all()


class TestLRN:
    def test_identity_when_alpha_zero(self):
        x = jnp.array(RNG.normal(size=(1, 2, 2, 8)).astype(np.float32))
        got = lrn.forward(x, alpha=0.0, beta=0.75, n=3, k=1.0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(x), rtol=1e-6)

    def test_matches_manual_window_sum(self):
        x = RNG.normal(size=(1, 1, 1, 6)).astype(np.float32)
        got = np.asarray(lrn.forward(jnp.array(x), alpha=0.1, beta=0.5,
                                     n=3, k=2.0))
        sq = x[0, 0, 0] ** 2
        padded = np.concatenate([[0.0], sq, [0.0]])
        ssum = np.array([padded[i:i + 3].sum() for i in range(6)])
        want = x[0, 0, 0] * (2.0 + 0.1 * ssum) ** -0.5
        np.testing.assert_allclose(got[0, 0, 0], want, rtol=1e-5)


class TestGroupNorm:
    def test_per_sample_group_statistics(self):
        """Each (sample, group) slab normalizes to mean 0 / var 1 over
        its spatial+intra-group elements — and samples are independent
        (batch-size invariance, GN's defining property vs batch norm)."""
        from veles_tpu.ops import norm
        x = RNG.normal(size=(3, 4, 4, 8)).astype(np.float32) * 5 + 2
        y = np.asarray(norm.group_norm(jnp.array(x), groups=2))
        g = y.reshape(3, 4, 4, 2, 4)
        m = g.mean(axis=(1, 2, 4))
        v = g.var(axis=(1, 2, 4))
        np.testing.assert_allclose(m, np.zeros((3, 2)), atol=1e-5)
        np.testing.assert_allclose(v, np.ones((3, 2)), atol=1e-3)
        # batch independence: sample 0 normalized alone is identical
        y0 = np.asarray(norm.group_norm(jnp.array(x[:1]), groups=2))
        np.testing.assert_allclose(y0[0], y[0], rtol=1e-5)

    def test_groups_degrade_to_divisor_and_affine_applies(self):
        from veles_tpu.ops import norm
        x = RNG.normal(size=(2, 6)).astype(np.float32)   # C=6, 32→6
        gamma = np.full(6, 2.0, np.float32)
        beta = np.full(6, 1.0, np.float32)
        y = np.asarray(norm.group_norm(jnp.array(x), jnp.array(gamma),
                                       jnp.array(beta), groups=32))
        base = np.asarray(norm.group_norm(jnp.array(x), groups=6))
        np.testing.assert_allclose(y, base * 2.0 + 1.0, rtol=1e-5)

    def test_group1_equals_layer_norm_over_sample(self):
        from veles_tpu.ops import norm
        x = RNG.normal(size=(2, 3, 3, 4)).astype(np.float32)
        y = np.asarray(norm.group_norm(jnp.array(x), groups=1))
        flat = x.reshape(2, -1)
        want = ((flat - flat.mean(1, keepdims=True))
                / np.sqrt(flat.var(1, keepdims=True) + 1e-5)).reshape(
                    x.shape)
        np.testing.assert_allclose(y, want, rtol=1e-4, atol=1e-5)


class TestDropout:
    def test_train_scales_and_zeroes(self):
        x = jnp.ones((1000,))
        y = np.asarray(dropout.forward(x, jax.random.key(0), 0.5))
        kept = y != 0
        assert 0.4 < kept.mean() < 0.6
        np.testing.assert_allclose(y[kept], 2.0)

    def test_reproducible(self):
        x = jnp.ones((100,))
        a = dropout.forward(x, jax.random.key(3), 0.3)
        b = dropout.forward(x, jax.random.key(3), 0.3)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestLosses:
    def test_softmax_xent_metrics(self):
        logits = jnp.array([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
        labels = jnp.array([0, 0])
        out = losses.softmax_cross_entropy(logits, labels)
        assert int(out["n_errors"]) == 1
        assert np.asarray(out["confusion"])[0, 1] == 1
        assert out["loss"] > 0

    def test_softmax_xent_gradient_flows(self):
        def loss_fn(w):
            logits = jnp.array([[1.0, 2.0]]) * w
            return losses.softmax_cross_entropy(logits,
                                                jnp.array([0]))["loss"]
        g = jax.grad(loss_fn)(1.0)
        assert np.isfinite(float(g)) and float(g) != 0

    def test_mse(self):
        out = losses.mse(jnp.array([[1.0, 2.0]]), jnp.array([[0.0, 0.0]]))
        np.testing.assert_allclose(float(out["loss"]), 5.0)
        np.testing.assert_allclose(float(out["max_err"]), 2.0)


class TestMisc:
    def test_cut(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        y = misc.cut(x, 1, 1, 2, 2)
        assert y.shape == (1, 2, 2, 1)
        assert float(y[0, 0, 0, 0]) == 5.0

    def test_channel_split_merge_roundtrip(self):
        x = jnp.array(RNG.normal(size=(2, 3, 3, 4)).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(misc.channel_merge(misc.channel_split(x))),
            np.asarray(x))

    def test_zero_fill(self):
        w = jnp.ones((3, 3))
        mask = jnp.eye(3)
        np.testing.assert_array_equal(np.asarray(misc.zero_fill(w, mask)),
                                      np.eye(3))

    def test_input_join(self):
        a = jnp.ones((2, 3))
        b = jnp.zeros((2, 2, 2))
        assert misc.input_join(a, b).shape == (2, 7)
