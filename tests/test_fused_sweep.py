"""steps_per_dispatch: the fused k-step sweep must be numerically
equivalent to the per-step dispatch path (same ops in the same order — the
only change is how many minibatches ride one host→device round trip)."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.parallel import MeshConfig, make_mesh


def _run(steps_per_dispatch, mesh_config=None, max_epochs=3, seed=42,
         minibatch_size=64):
    prng.seed_all(seed)
    rs = np.random.RandomState(0)
    x = rs.rand(640, 36).astype(np.float32)
    y = rs.randint(0, 5, 640).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y,
                             minibatch_size=minibatch_size,
                             class_lengths=[0, 128, 512])
    wf = StandardWorkflow(
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 16,
             "learning_rate": 0.1, "gradient_moment": 0.9},
            {"type": "dropout", "dropout_ratio": 0.3},
            {"type": "softmax", "output_sample_shape": 5,
             "learning_rate": 0.1, "gradient_moment": 0.9},
        ],
        loader=loader,
        decision_config={"max_epochs": max_epochs},
        mesh_config=mesh_config,
        steps_per_dispatch=steps_per_dispatch,
        name="sweep-%d" % steps_per_dispatch)
    wf.initialize()
    wf.run()
    params = wf.trainer.host_params()
    # Decision records each epoch's accumulated stats BEFORE resetting
    # them — the meaningful place to catch dropped/double-counted steps
    stats = wf.decision.epoch_metrics[2]
    return wf.decision.best_metric, params, stats


class TestFusedSweep:
    def test_matches_per_step_path(self):
        m1, p1, s1 = _run(1)
        m4, p4, s4 = _run(4)
        assert s1["count"] == s4["count"] > 0
        assert m1 == pytest.approx(m4, abs=1e-6)
        for name in p1:
            for k in p1[name]:
                np.testing.assert_allclose(
                    p1[name][k], p4[name][k], rtol=2e-5, atol=2e-6,
                    err_msg="%s/%s diverged" % (name, k))

    def test_ragged_tail_uses_per_step_fallback(self):
        # 512 train / 64 = 8 steps per epoch; k=3 leaves a tail of 2
        m1, p1, _ = _run(1)
        m3, p3, _ = _run(3)
        assert m1 == pytest.approx(m3, abs=1e-6)
        for name in p1:
            for k in p1[name]:
                np.testing.assert_allclose(p1[name][k], p3[name][k],
                                           rtol=2e-5, atol=2e-6)

    def test_under_data_parallel_mesh(self):
        import jax
        mc = MeshConfig(make_mesh({"data": 4}, jax.devices()[:4]))
        m1, p1, s1 = _run(1, mesh_config=mc)
        mk, pk, sk = _run(4, mesh_config=mc)
        assert s1["count"] == sk["count"] > 0
        assert m1 == pytest.approx(mk, abs=1e-6)
        for name in p1:
            for k in p1[name]:
                np.testing.assert_allclose(p1[name][k], pk[name][k],
                                           rtol=2e-5, atol=2e-6)

    def test_snapshot_resume_flushes_pending(self, tmp_path):
        prng.seed_all(7)
        rs = np.random.RandomState(1)
        x = rs.rand(320, 16).astype(np.float32)
        y = rs.randint(0, 4, 320).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=32,
                                 class_lengths=[0, 64, 256])
        wf = StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 4,
                     "learning_rate": 0.1, "gradient_moment": 0.9}],
            loader=loader, decision_config={"max_epochs": 2},
            snapshotter_config={"directory": str(tmp_path), "interval": 1,
                                "prefix": "sw"},
            steps_per_dispatch=5, name="sweep-snap")
        wf.initialize()
        wf.run()
        from veles_tpu.services.snapshotter import SnapshotterBase
        snap = SnapshotterBase.import_(wf.snapshotter.destination)
        assert snap["epoch"] == 2
        # no steps may linger unapplied after the run completed
        assert not wf.trainer._pending
