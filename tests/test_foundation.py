"""Foundation-layer tests (ref test strategy: veles/tests/test_config.py,
test_mutable.py, prng tests — SURVEY.md §4)."""

import pickle

import numpy
import pytest

from veles_tpu import prng
from veles_tpu.config import Config, root
from veles_tpu.mutable import Bool, link
from veles_tpu.registry import MappedRegistry, UnitRegistry


class TestConfig:
    def test_autovivify(self):
        cfg = Config("test")
        cfg.a.b.c = 42
        assert cfg.a.b.c == 42
        assert cfg.as_dict() == {"a": {"b": {"c": 42}}}

    def test_update_deep_merge(self):
        cfg = Config("test")
        cfg.x.y = 1
        cfg.x.z = 2
        cfg.update({"x": {"z": 3, "w": 4}, "v": 5})
        assert cfg.x.y == 1 and cfg.x.z == 3 and cfg.x.w == 4 and cfg.v == 5

    def test_get_does_not_vivify(self):
        cfg = Config("test")
        assert cfg.get("nope", 7) == 7
        assert "nope" not in cfg

    def test_root_defaults(self):
        assert root.common.engine.precision.accum == "float32"
        assert isinstance(root.common.dirs.cache, str)


class TestBool:
    def test_assign_and_truth(self):
        b = Bool()
        assert not b
        b <<= True
        assert b

    def test_lazy_expression_tracks_sources(self):
        a, b = Bool(True), Bool(False)
        gate = a & ~b
        assert gate
        b <<= True            # flips the derived gate without rebuilding it
        assert not gate
        a <<= False
        assert not (a | b) == False  # noqa: E712 — (a|b) is True since b True

    def test_derived_not_assignable(self):
        a = Bool(True)
        gate = ~a
        with pytest.raises(ValueError):
            gate <<= True

    def test_xor(self):
        a, b = Bool(True), Bool(True)
        assert not (a ^ b)
        b <<= False
        assert a ^ b


class TestLink:
    def test_linkable_attribute_forwarding(self):
        class Src:
            val = 10

        class Dst:
            pass

        s, d = Src(), Dst()
        link(d, "val", s)
        assert d.val == 10
        d.val = 20
        assert s.val == 20


class TestRegistry:
    def test_unit_registry_records_subclasses(self):
        class Probe(metaclass=UnitRegistry):
            pass

        assert Probe in UnitRegistry.units
        assert UnitRegistry.find("Probe") is Probe

    def test_mapped_registry(self):
        class Family(metaclass=MappedRegistry):
            mapping = {}

        class Impl(Family):
            MAPPING = "impl"

        assert Family["impl"] is Impl
        assert "impl" in Family


class TestPrng:
    def test_streams_reproducible(self):
        g1 = prng.RandomGenerator("t", seed=7)
        g2 = prng.RandomGenerator("t", seed=7)
        assert numpy.array_equal(g1.permutation(100), g2.permutation(100))
        k1, k2 = g1.key(), g2.key()
        import jax
        assert jax.random.key_data(k1).tolist() == \
            jax.random.key_data(k2).tolist()

    def test_streams_differ_by_name(self):
        a = prng.RandomGenerator("a", seed=None)
        b = prng.RandomGenerator("b", seed=None)
        assert not numpy.array_equal(a.permutation(100), b.permutation(100))

    def test_state_resume_mid_stream(self):
        g = prng.RandomGenerator("t", seed=3)
        g.permutation(10)
        saved = pickle.dumps(g)
        expect = g.permutation(10)
        g2 = pickle.loads(saved)
        assert numpy.array_equal(g2.permutation(10), expect)

    def test_global_registry(self):
        assert prng.get("loader") is prng.get("loader")
