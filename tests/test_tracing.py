"""Request tracing tier (telemetry.tracing + the serving plane's span
points): the span store is a bounded ring whose overflow drops oldest
with a counted gauge, span adds stay within the flight recorder's
~2 µs/event budget, forged X-Veles-Trace headers are stripped at the
router edge (it always mints), and ONE trace id yields a gapless
single-terminal timeline across a mid-stream failover and across a
two-phase prefill handoff — byte-identical result included (the trace
must never perturb the splice).  Mirrors test_router.py's fleet
idioms; one tiny untrained transformer is shared module-wide."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.services.router import FleetRouter
from veles_tpu.telemetry import tracing
from veles_tpu.telemetry.tracing import SpanStore

T, VOCAB = 16, 11
PROMPT = [1, 2, 3, 4, 5]


@pytest.fixture(scope="module")
def gen():
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models import zoo
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(31)
    toks = np.random.RandomState(5).randint(
        0, VOCAB, (8, T)).astype(np.int32)
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                  n_heads=2, n_layers=1, dropout=0.0),
        loader=FullBatchLoader(None, data=toks, labels=toks,
                               minibatch_size=4,
                               class_lengths=[0, 4, 4]),
        loss="lm", decision_config={"max_epochs": 1},
        name="tracing-serve")
    wf.initialize()
    return LMGenerator(wf.trainer, max_len=T)


def _post(router, body, headers=None, timeout=120):
    conn = http.client.HTTPConnection(router.host, router.port,
                                      timeout=timeout)
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    conn.request("POST", router.path, json.dumps(body), h)
    return conn.getresponse(), conn


def _settled_timeline(router, tid, timeout=5.0):
    """The trace's timeline once its terminal span landed — the edge
    records it in the handler's ``finally`` AFTER the done line is
    written, so a client acting on ``done`` can be a beat early."""
    deadline = time.monotonic() + timeout
    timeline = None
    while time.monotonic() < deadline:
        timeline = router.trace_timeline(tid)
        if timeline is not None and timeline["gapless"]:
            break
        time.sleep(0.02)
    return timeline


# ------------------------------------------------------------- ids/headers
class TestIdsAndHeader:
    def test_ids_are_valid_and_unique(self):
        tids = {tracing.new_trace_id() for _ in range(64)}
        sids = {tracing.new_span_id() for _ in range(64)}
        assert len(tids) == 64 and len(sids) == 64
        assert all(tracing.valid_id(t) for t in tids | sids)

    def test_header_round_trip_and_forgeries_rejected(self):
        t, s = tracing.new_trace_id(), tracing.new_span_id()
        assert tracing.parse_header(
            tracing.format_header(t, s)) == (t, s)
        assert tracing.parse_header(tracing.format_header(t)) == (t,
                                                                  None)
        for forged in (None, "", "xyz", "UPPER0123456789",
                       "/deadbeef", "a" * 33,
                       "deadbeef;rm -rf", "..", "0x12"):
            assert tracing.parse_header(forged) is None, forged
        # junk PARENT only: the valid trace id survives, the parent is
        # dropped (a mid-chain hop still joins the right trace)
        for lenient in ("deadbeef/", "deadbeef/XYZ",
                        "deadbeef/deadbeef/deadbeef"):
            assert tracing.parse_header(lenient) == ("deadbeef", None)


# --------------------------------------------------------------- span store
class TestSpanStore:
    def test_ring_overflow_drops_oldest_with_counted_gauge(self):
        store = SpanStore(capacity=4, max_spans=8)
        tids = ["%016x" % i for i in range(1, 7)]
        for tid in tids:
            store.add(tid, "request")
        # oldest two traces evicted, newest four resident, each
        # eviction counted on the gauge
        assert store.dropped == 2
        assert store.spans(tids[0]) == [] and store.spans(tids[1]) == []
        assert all(store.spans(t) for t in tids[2:])

    def test_per_trace_span_cap_drops_excess(self):
        store = SpanStore(capacity=4, max_spans=3)
        tid = "%016x" % 7
        for i in range(5):
            store.add(tid, "s%d" % i)
        spans = store.spans(tid)
        # ring discipline: the OLDEST spans go first, each counted
        assert [s["name"] for s in spans] == ["s2", "s3", "s4"]
        assert store.dropped == 2

    def test_disabled_store_records_nothing(self):
        store = SpanStore(capacity=4, max_spans=8)
        store.enabled = False
        store.add("%016x" % 9, "request")
        assert store.spans("%016x" % 9) == []
        assert store.dropped == 0

    def test_span_add_overhead_under_budget(self):
        """Acceptance: span adds share the flight recorder's ~2 µs
        budget; assert the same generous CI bound and print the
        measured number (documented in docs/services.md "Request
        tracing").  The NON-evicting path is the budgeted one —
        eviction is rare by construction (capacity >> live traces)."""
        store = SpanStore(capacity=8, max_spans=50000)
        tid = tracing.new_trace_id()
        n = 20000
        t0 = time.perf_counter()
        for i in range(n):
            store.add(tid, "bench", i=i)
        per_span = (time.perf_counter() - t0) / n
        print("tracing span add overhead: %.2f us/span"
              % (per_span * 1e6))
        assert per_span < 50e-6      # ~25x the 2 µs target: CI headroom


# ---------------------------------------------------------------- validate
class TestValidate:
    def _chain(self):
        tid = tracing.new_trace_id()
        root = {"trace": tid, "span": "a" * 8, "parent": None,
                "name": "request", "ts": 1.0}
        child = {"trace": tid, "span": "b" * 8, "parent": "a" * 8,
                 "name": "router.leg", "ts": 1.1}
        term = {"trace": tid, "span": "c" * 8, "parent": "a" * 8,
                "name": "request.done", "ts": 1.2, "terminal": True}
        return [root, child, term]

    def test_gapless_chain_passes(self):
        v = tracing.validate(self._chain())
        assert v["ok"] and not v["problems"]

    def test_dangling_parent_multi_root_multi_terminal_dup_fail(self):
        chain = self._chain()
        assert not tracing.validate(chain[1:])["ok"]       # no root
        forged = dict(chain[1], parent="f" * 8)
        assert not tracing.validate(
            [chain[0], forged, chain[2]])["ok"]            # dangling
        dup = dict(chain[1])
        assert not tracing.validate(chain + [dup])["ok"]   # dup span id
        term2 = dict(chain[2], span="d" * 8)
        assert not tracing.validate(chain + [term2])["ok"]  # 2 terminals
        assert not tracing.validate(chain[:2])["ok"]       # no terminal


# ------------------------------------------------------------- edge minting
class TestForgedHeaderStrippedAtEdge:
    def test_router_ignores_incoming_trace_header(self, gen):
        """The router is the trust boundary: an incoming X-Veles-Trace
        is a forgery there (same rule as the resume-field strip) — the
        response must carry a freshly minted id, and the forged id
        must own no spans."""
        router = FleetRouter(port=0, health_interval_ms=10000)
        router.spawn_local(gen, 1, continuous_slots=2)
        router.start()
        try:
            forged = "deadbeefdeadbeef"
            resp, conn = _post(
                router, {"input": PROMPT, "generate": {"max_new": 2}},
                headers={tracing.TRACE_HEADER:
                         forged + "/0011223344556677"})
            assert resp.status == 200
            out = json.loads(resp.read())
            conn.close()
            minted = out.get("trace")
            assert minted and minted != forged
            assert tracing.store.spans(forged) == []
            spans = tracing.store.spans(minted)
            roots = [s for s in spans if s.get("parent") is None]
            assert len(roots) == 1 and roots[0].get("edge") == "router"
        finally:
            router.stop()


# ----------------------------------------------------- cross-hop timelines
class TestTraceAcrossFailover:
    def test_failover_timeline_gapless_one_terminal(self, gen,
                                                    f32_precision):
        """Mid-stream SIGKILL-equivalent (engine stop) on the pinned
        replica: the client still sees ONE byte-identical stream, and
        its ONE trace id reconstructs a gapless timeline — the
        failover span chain stays connected and exactly one terminal
        span closes it."""
        router = FleetRouter(port=0, health_interval_ms=10000,
                             affinity="session")
        rids = router.spawn_local(gen, 2, continuous_slots=2)
        router.start()
        try:
            resp, conn = _post(router, {"input": PROMPT,
                                        "session": "tfo",
                                        "generate": {"max_new": 8}})
            assert resp.status == 200
            expected = json.loads(resp.read())["result"][0]
            conn.close()
            for a in router._local_apis:
                a.engine.wait(a.engine.submit_async(PROMPT, 8))
            pinned = router._sessions["tfo"]
            victim = router._local_apis[rids.index(pinned)]
            orig = victim.engine.cb.tick

            def slow_tick():
                time.sleep(0.05)
                return orig()

            victim.engine.cb.tick = slow_tick
            resp, conn = _post(router, {
                "input": PROMPT, "session": "tfo",
                "generate": {"max_new": 8, "stream": True}})
            assert resp.status == 200
            got, done, killed = list(PROMPT), None, False
            while True:
                raw = resp.fp.readline()
                if not raw:
                    break
                msg = json.loads(raw)
                if "tokens" in msg:
                    got.extend(msg["tokens"])
                    if not killed:
                        killed = True
                        threading.Thread(target=victim.engine.stop,
                                         daemon=True).start()
                else:
                    assert msg.get("done"), msg
                    done = msg
                    break
            conn.close()
            assert killed and done and done.get("resumed")
            assert got == expected and list(done["result"]) == expected
            # the done line carries the trace id the edge minted
            tid = done.get("trace")
            assert tid and tracing.valid_id(tid)
            timeline = _settled_timeline(router, tid)
            assert timeline is not None
            assert timeline["gapless"], timeline["problems"]
            spans = timeline["spans"]
            names = [s["name"] for s in spans]
            assert "router.failover" in names
            assert names.count("router.leg") >= 2      # both attempts
            assert sum(1 for s in spans
                       if s.get("terminal")) == 1
            # phase decomposition survived the splice
            assert set(timeline["phases"]) >= {"queue", "prefill",
                                               "decode", "stream"}
        finally:
            router.stop()


class TestTraceAcrossPrefillHandoff:
    def test_handoff_timeline_gapless_one_terminal(self, gen,
                                                   f32_precision):
        """Two-phase prefill handoff (prefill tier -> decode tier via
        prefix-resume): byte-identical stream, ONE trace id, gapless
        chain through router.handoff, exactly one terminal span."""
        router = FleetRouter(port=0, rng_seed=3,
                             health_interval_ms=50,
                             prefill_prompt_min=8,
                             prefill_handoff_new=2)
        router.start()
        router.spawn_local(gen, 2, continuous_slots=2,
                           roles=["prefill", "decode"])
        try:
            long_prompt = list(range(1, 11))
            expected = gen.generate(
                np.asarray([long_prompt], np.int32), 5)[0].tolist()
            resp, conn = _post(router, {
                "input": long_prompt,
                "generate": {"max_new": 5, "stream": True}})
            assert resp.status == 200
            got, done = list(long_prompt), None
            while True:
                raw = resp.fp.readline()
                if not raw:
                    break
                msg = json.loads(raw)
                if "tokens" in msg:
                    got.extend(msg["tokens"])
                if msg.get("done"):
                    done = msg
                    break
            conn.close()
            assert got == expected
            assert done is not None and done["result"] == expected
            tid = done.get("trace")
            assert tid and tracing.valid_id(tid)
            timeline = _settled_timeline(router, tid)
            assert timeline is not None
            assert timeline["gapless"], timeline["problems"]
            spans = timeline["spans"]
            names = [s["name"] for s in spans]
            assert "router.handoff" in names
            assert names.count("router.leg") >= 2      # both tiers
            assert sum(1 for s in spans if s.get("terminal")) == 1
            # both tiers' spans share the ONE trace id
            assert {s["trace"] for s in spans} == {tid}
            # a rendered timeline ends with the gapless verdict
            text = tracing.render_timeline(spans)
            assert "gapless: yes" in text
        finally:
            router.stop()
