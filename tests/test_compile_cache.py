"""Persistent XLA compilation cache (veles_tpu/compile_cache.py).

The contract that matters on the tunneled chip: enabling the cache
makes compiled executables land on disk, so a later process (another
bench phase, the driver's end-of-round run) can reuse them instead of
re-paying first-compile out of TPU uptime.  Mirrors the reference's
on-disk kernel-binary cache behavior (build once, hit thereafter).
"""

import os
import subprocess
import sys

import pytest

import veles_tpu.compile_cache as cc


_CACHE_OPTS = ("jax_compilation_cache_dir",
               "jax_persistent_cache_min_compile_time_secs",
               "jax_persistent_cache_min_entry_size_bytes",
               "jax_persistent_cache_enable_xla_caches")


@pytest.fixture
def restore_cache_config():
    """The cache config is process-global jax state — put every option
    enable() touches back so later suites don't silently serialize
    every executable to a pytest tmp dir that may be garbage-collected
    under JAX."""
    import jax
    missing = object()
    before = {opt: getattr(jax.config, opt, missing) for opt in _CACHE_OPTS}
    saved_dir = cc._enabled_dir
    yield
    for opt, val in before.items():
        if val is not missing:
            jax.config.update(opt, val)
    cc._enabled_dir = saved_dir


def test_enable_writes_entries_and_is_idempotent(tmp_path,
                                                 restore_cache_config):
    cache = tmp_path / "xla"
    got = cc.enable(str(cache))
    assert got == str(cache)
    assert cc.enable(str(cache)) == str(cache)  # idempotent
    assert cc.enabled_dir() == str(cache)

    import jax
    import jax.numpy as jnp
    # a fresh program must produce at least one persisted entry once it
    # compiles
    x = jnp.ones((64, 64), jnp.float32)
    jax.block_until_ready(jax.jit(lambda a: (a @ a).sum())(x))
    entries = [p for p in cache.rglob("*") if p.is_file()]
    assert entries, "no cache entries persisted after a jit compile"


def test_env_kill_switch(tmp_path, monkeypatch):
    monkeypatch.setenv("VELES_COMPILE_CACHE", "off")
    assert cc.enable(str(tmp_path / "nope")) is None
    assert not (tmp_path / "nope").exists()


def test_cpu_backend_declines_the_automatic_default(monkeypatch):
    """On the CPU backend the AUTOMATIC default stays off — XLA:CPU
    executable deserialization can corrupt the heap in sandboxed
    environments (the ROADMAP "environment flake", root-caused in
    PR 9) — while an explicit path or env dir still opts in."""
    monkeypatch.delenv("VELES_COMPILE_CACHE", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    import jax
    prev = getattr(jax.config, "jax_platforms", None)
    try:
        jax.config.update("jax_platforms", "cpu")
        assert cc._cpu_backend()
        assert cc.enable() is None
    finally:
        jax.config.update("jax_platforms", prev)


def test_unpinned_run_resolves_backend_by_accelerator_evidence(
        monkeypatch):
    """Nothing pinned: jax auto-selects CPU on an accelerator-less
    machine, so the decline must cover that case too — an unpinned
    CPU-only run with the cache on is exactly the measured crash
    configuration.  With accelerator evidence the old default (cache
    on) stands."""
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    import jax
    prev = getattr(jax.config, "jax_platforms", None)
    try:
        jax.config.update("jax_platforms", None)
        monkeypatch.setattr(cc, "_accelerator_evidence", lambda: False)
        assert cc._cpu_backend()
        monkeypatch.setattr(cc, "_accelerator_evidence", lambda: True)
        assert not cc._cpu_backend()
    finally:
        jax.config.update("jax_platforms", prev)


def test_explicit_path_opts_in_even_on_cpu(tmp_path, monkeypatch,
                                           restore_cache_config):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert cc.enable(str(tmp_path / "xla")) == str(tmp_path / "xla")


def test_env_dir_opts_in_even_on_cpu(tmp_path, monkeypatch,
                                     restore_cache_config):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("VELES_COMPILE_CACHE", str(tmp_path / "envdir"))
    assert cc.enable() == str(tmp_path / "envdir")


def test_env_overrides_default_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("VELES_COMPILE_CACHE", str(tmp_path / "envdir"))
    assert cc.default_dir() == str(tmp_path / "envdir")


def test_env_boolean_on_means_default_dir(monkeypatch):
    # "=1" means on, not a cache directory literally named "1"
    monkeypatch.delenv("VELES_COMPILE_CACHE", raising=False)
    expect = cc.default_dir()
    for val in ("1", "on", "true", "yes", "TRUE"):
        monkeypatch.setenv("VELES_COMPILE_CACHE", val)
        assert cc.default_dir() == expect


def test_env_relative_path_is_absolutized(monkeypatch):
    monkeypatch.setenv("VELES_COMPILE_CACHE", "relcache")
    assert os.path.isabs(cc.default_dir())
    assert cc.default_dir().endswith(os.sep + "relcache")


@pytest.mark.slow
def test_second_process_hits_the_cache(tmp_path):
    """The cross-process contract, asserted end-to-end: process A
    compiles and persists; process B compiles the same program and
    must be served from disk (observed via JAX's cache-hit logger).

    Slow tier: two fresh-jax-init subprocesses (tens of seconds on the
    1-core CI box) — the conftest budget rule for subprocess modules.
    """
    cache = str(tmp_path / "xla")
    # NB: the platform flip must happen IN-PROCESS (the conftest
    # pattern): on this box a sitecustomize hook reads the startup env,
    # and an interpreter *started* with JAX_PLATFORMS=cpu routes even
    # CPU compiles through the (possibly dead) device tunnel and hangs.
    prog = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import logging, sys\n"
        "logging.basicConfig(level=logging.INFO)\n"
        "logging.getLogger('jax._src.compilation_cache')"
        ".setLevel(logging.DEBUG)\n"
        "logging.getLogger('jax._src.compiler').setLevel(logging.DEBUG)\n"
        "import veles_tpu.compile_cache as cc\n"
        "cc.enable(%r)\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "x = jnp.full((48, 48), 3.0, jnp.float32)\n"
        "v = jax.jit(lambda a: (a @ a.T).sum())(x)\n"
        "print('VAL', float(v))\n" % cache
    )
    env = dict(os.environ)
    # the conftest exports JAX_PLATFORMS=cpu for THIS process; a child
    # interpreter must not START with it (see sitecustomize note above)
    env.pop("JAX_PLATFORMS", None)
    outs = []
    for _ in range(2):
        p = subprocess.run([sys.executable, "-c", prog],
                           capture_output=True, text=True, timeout=240,
                           env=env, cwd=os.path.dirname(
                               os.path.dirname(os.path.abspath(__file__))))
        assert p.returncode == 0, p.stderr[-2000:]
        outs.append(p.stdout + p.stderr)
    assert "VAL" in outs[0] and "VAL" in outs[1]
    # same numeric result either path
    v0 = [l for l in outs[0].splitlines() if l.startswith("VAL")][0]
    v1 = [l for l in outs[1].splitlines() if l.startswith("VAL")][0]
    assert v0 == v1
    second = outs[1].lower()
    assert ("cache hit" in second or "persistent compilation cache hit"
            in second), "second process did not hit the persistent cache"
