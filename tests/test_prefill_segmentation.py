"""Segmented prefill admission (docs/services.md "Disaggregated
prefill"): long prompts admit through bounded chunk passes interleaved
with decode ticks.  THE bar: every segmented configuration's token
streams are byte-identical to the unsegmented admission (and to
token-by-token prompt forcing) — the segments reuse the prefix-cache
resume math, so a single drifted position would also break the PR 7
failover splice."""

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.generate import (ContinuousBatcher, LMGenerator,
                                       PagedContinuousBatcher)
from veles_tpu.models.standard_workflow import StandardWorkflow


def _lm_workflow(t=48, vocab=13, seed=31, **zoo_kwargs):
    prng.seed_all(seed)
    r = np.random.RandomState(5)
    n = 96
    toks = ((np.arange(t)[None, :] * 2 + r.randint(0, 4, n)[:, None])
            % vocab).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 48, 48])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32,
                                  n_heads=4, n_layers=2, lr=5e-3,
                                  dropout=0.0, **zoo_kwargs),
        loader=loader, loss="lm", decision_config={"max_epochs": 1},
        name="seg-lm")
    wf.initialize()
    return wf, toks


@pytest.fixture(scope="module")
def lm():
    wf, toks = _lm_workflow()
    return LMGenerator(wf.trainer, max_len=48), toks


@pytest.fixture(scope="module")
def lm_rolling():
    wf, toks = _lm_workflow(window=8)
    return LMGenerator(wf.trainer, max_len=48), toks


def _pool_results(cb, requests):
    rids = [cb.submit(*req) for req in requests]
    cb.run_all()
    return [cb.pop_result(r) for r in rids]


class TestSegmentedEquivalence:
    """The byte-identity matrix: odd segment sizes vs prompt lengths
    around PREFILL_MIN, rolling-window round-down, prefix-cache shared
    blocks, speculative pools, and paged (bf16 + int8) pools — all
    equal to the unsegmented path AND to token-by-token forcing."""

    @pytest.mark.parametrize("segment", [3, 5, 7])
    @pytest.mark.parametrize("plen", [31, 33])
    def test_dense_odd_segments_vs_prefill_min(self, lm, segment,
                                               plen, f32_precision):
        gen, toks = lm
        reqs = [(toks[i, :plen].tolist(), 6, 0.0, i) for i in range(2)]
        base = _pool_results(ContinuousBatcher(gen, slots=2), reqs)
        seg = _pool_results(
            ContinuousBatcher(gen, slots=2, prefill_segment=segment),
            reqs)
        forced = _pool_results(
            ContinuousBatcher(gen, slots=2, chunked_prefill=False),
            reqs)
        assert seg == base == forced

    def test_sampled_rows_identical(self, lm, f32_precision):
        gen, toks = lm
        reqs = [(toks[0, :30].tolist(), 6, 0.8, 7),
                (toks[1, :33].tolist(), 6, 0.0, 1)]
        base = _pool_results(ContinuousBatcher(gen, slots=2), reqs)
        seg = _pool_results(
            ContinuousBatcher(gen, slots=2, prefill_segment=5), reqs)
        assert seg == base

    def test_rolling_window_rounds_down_unsegmented(self, lm_rolling,
                                                    f32_precision):
        """A rolling-window model must keep the unsegmented round-DOWN
        prefill (a ring slot may never hold a position past its own
        start): _will_segment refuses, outputs stay byte-identical."""
        gen, toks = lm_rolling
        cb = ContinuousBatcher(gen, slots=2, prefill_segment=5)
        assert not cb._will_segment(33)
        reqs = [(toks[i, :33].tolist(), 6, 0.0, i) for i in range(2)]
        base = _pool_results(ContinuousBatcher(gen, slots=2), reqs)
        seg = _pool_results(
            ContinuousBatcher(gen, slots=2, prefill_segment=5), reqs)
        assert seg == base

    def test_speculative_pool_identical(self, lm, f32_precision):
        gen, toks = lm
        reqs = [(toks[i, :30].tolist(), 8, 0.0, i) for i in range(2)]
        base = _pool_results(
            ContinuousBatcher(gen, slots=2, speculative_k=4), reqs)
        seg = _pool_results(
            ContinuousBatcher(gen, slots=2, speculative_k=4,
                              prefill_segment=7), reqs)
        plain = _pool_results(ContinuousBatcher(gen, slots=2), reqs)
        assert seg == base == plain

    @pytest.mark.parametrize("fused", [True, False])
    def test_paged_pool_identical(self, lm, fused, f32_precision):
        gen, toks = lm
        reqs = [(toks[i, :31].tolist(), 6, 0.0, i) for i in range(2)]
        base = _pool_results(
            PagedContinuousBatcher(gen, slots=2, block=4,
                                   pool_tokens=96, fused=fused), reqs)
        cb = PagedContinuousBatcher(gen, slots=2, block=4,
                                    pool_tokens=96, fused=fused,
                                    prefill_segment=5)
        seg = _pool_results(cb, reqs)
        assert seg == base
        assert cb.free_blocks() == cb.pool_blocks

    def test_paged_int8_pool_identical(self, f32_precision):
        wf, toks = _lm_workflow(t=32)
        gen = LMGenerator(wf.trainer, max_len=32, cache_dtype="int8")
        reqs = [(toks[i, :22].tolist(), 5, 0.0, i) for i in range(2)]
        base = _pool_results(
            PagedContinuousBatcher(gen, slots=2, block=4,
                                   pool_tokens=64), reqs)
        seg = _pool_results(
            PagedContinuousBatcher(gen, slots=2, block=4,
                                   pool_tokens=64, prefill_segment=6),
            reqs)
        assert seg == base

    def test_prefix_cache_shared_blocks_identical(self, lm,
                                                  f32_precision):
        """Same-prefix requests under segmentation: results equal the
        no-sharing batcher's, the sharing accounting is exact, and
        every block returns to the free list."""
        gen, toks = lm
        prompt = toks[0, :33].tolist()
        reqs = [(prompt, 4, 0.0, 0), (prompt, 4, 0.0, 0)]
        base = _pool_results(
            PagedContinuousBatcher(gen, slots=2, block=4,
                                   pool_tokens=96), reqs)
        cb = PagedContinuousBatcher(gen, slots=2, block=4,
                                    pool_tokens=96, prefix_cache=True,
                                    prefill_segment=6)
        free0 = cb.free_blocks()
        r1 = cb.submit(*reqs[0])
        r2 = cb.submit(*reqs[1])
        cb.run_all()
        assert [cb.pop_result(r1), cb.pop_result(r2)] == base
        assert cb.free_blocks() == free0
        assert not cb._prefix_reg and not cb._prefix_ref

    def test_staged_blocks_not_matchable_until_finish(self, lm,
                                                      f32_precision):
        """Deferred prefix registration: while a staged admission is
        still prefilling, its new blocks hold no K/V — they must not
        appear in the prefix registry (a sharer matching them would
        attend garbage).  They publish at finish."""
        gen, toks = lm
        prompt = toks[0, :33].tolist()
        cb = PagedContinuousBatcher(gen, slots=2, block=4,
                                    pool_tokens=96, prefix_cache=True,
                                    prefill_segment=4,
                                    prefill_tick_budget=4)
        cb.submit(prompt, 4)
        cb.tick()                      # begins staging + 1 segment
        assert cb.staging_slots() == 1
        assert not cb._prefix_reg      # nothing matchable mid-staging
        cb.run_all()
        assert not cb._staging


class TestSegmentedMechanics:
    def test_budget_bounds_tokens_per_tick(self, lm, f32_precision):
        """Each tick advances at most the budget (pow2 bucketing may
        overshoot < 2x) — a 32-token prefill at segment 4 takes
        several ticks, decode ticks interleaved throughout."""
        gen, toks = lm
        events = []
        cb = ContinuousBatcher(gen, slots=2, prefill_segment=4)
        cb.prefill_observer = events.append
        # an in-flight decode stream the admission must not stall
        r_short = cb.submit(toks[1, :4].tolist(), 20)
        cb.tick()
        r_long = cb.submit(toks[0, :33].tolist(), 4)
        ticks = 0
        while cb.result(r_long) is None and ticks < 200:
            cb.tick()
            ticks += 1
        segs = [e for e in events if e["kind"] == "segment"]
        assert all(e["tokens"] <= 8 for e in segs)   # bucket(4)=4 or edge
        assert len(segs) >= 8                        # 32/4 passes
        # the staged prefill spanned multiple ticks (interleaving)
        assert ticks >= len(segs)
        cb.run_all()
        assert cb.result(r_short) is not None or \
            cb.pop_result(r_short) is not None

    def test_backlog_accounting(self, lm, f32_precision):
        gen, toks = lm
        cb = ContinuousBatcher(gen, slots=1, prefill_segment=4,
                               prefill_tick_budget=4)
        cb.submit(toks[0, :33].tolist(), 4)
        cb.submit(toks[1, :21].tolist(), 4)   # queued behind
        assert cb.prefill_backlog_tokens() == 33 + 21
        cb.tick()                             # stage + first segment
        backlog = cb.prefill_backlog_tokens()
        assert backlog < 33 + 21
        assert backlog >= 21                  # queued prompt untouched
        cb.run_all()
        assert cb.prefill_backlog_tokens() == 0

    def test_cancel_mid_staging_frees_slot_and_blocks(self, lm,
                                                      f32_precision):
        gen, toks = lm
        cb = PagedContinuousBatcher(gen, slots=1, block=4,
                                    pool_tokens=48,
                                    prefill_segment=4,
                                    prefill_tick_budget=4)
        free0 = cb.free_blocks()
        rid = cb.submit(toks[0, :33].tolist(), 4)
        cb.tick()
        assert cb.staging_slots() == 1 and cb.free_blocks() < free0
        assert cb.cancel(rid)
        assert cb.staging_slots() == 0
        assert cb.free_blocks() == free0
        # the freed slot admits the next request normally
        r2 = cb.submit(toks[1, :9].tolist(), 4)
        cb.run_all()
        assert cb.pop_result(r2) == gen.generate(
            np.asarray([toks[1, :9].tolist()], np.int32),
            4)[0].tolist()

    def test_reset_pool_clears_staging(self, lm, f32_precision):
        gen, toks = lm
        cb = ContinuousBatcher(gen, slots=1, prefill_segment=4,
                               prefill_tick_budget=4)
        cb.submit(toks[0, :33].tolist(), 4)
        cb.tick()
        assert cb.staging_slots() == 1
        cb.reset_pool()
        assert cb.staging_slots() == 0 and cb.idle()


class TestEnginePrefill:
    @pytest.fixture(scope="class")
    def engine(self, lm):
        from veles_tpu.services.restful import ContinuousEngine
        gen, toks = lm
        eng = ContinuousEngine(gen, slots=2, prefill_segment=6)
        yield eng, toks
        eng.stop()

    def test_metrics_and_flight_events(self, engine, f32_precision):
        from veles_tpu.telemetry import flight
        eng, toks = engine
        out = eng.wait(eng.submit_async(toks[0, :33].tolist(), 4))
        assert len(out) == 37
        m = eng.metrics()
        assert m["prefill_segments_total"] >= 4
        assert m["prefill_tokens_total"] >= 32
        assert m["prefill_ms_per_tok"] > 0
        assert "p99_decode_stall_ms" in m
        assert m["queued_prefill_tokens"] == 0
        phases = {e.get("phase") for e in flight.recorder.snapshot()
                  if e["kind"] == "serve.prefill"}
        assert {"begin", "segment", "admit"} <= phases

    def test_predictive_deadline_includes_prefill(self, engine,
                                                  f32_precision):
        """A long prompt with a deadline its own PREFILL cannot meet
        504s at submit — before burning the prefill (the old check
        only priced decode)."""
        from veles_tpu.services.lifecycle import DeadlineExceeded
        eng, toks = engine
        eng.wait(eng.submit_async(toks[0, :33].tolist(), 4))  # warm
        assert eng._prefill_ms_per_tok > 0
        # a deadline smaller than the measured prefill estimate alone
        est_ms = eng._prefill_ms_per_tok * 33
        h = eng.submit_async(toks[0, :33].tolist(), 4,
                             deadline_ms=max(est_ms * 0.2, 0.1))
        with pytest.raises(DeadlineExceeded):
            eng.wait(h)

    def test_health_status_carries_prefill_surface(self, lm,
                                                   f32_precision):
        from veles_tpu.services.restful import RESTfulAPI
        gen, toks = lm
        api = RESTfulAPI(lambda x: x, (gen.max_len,), port=0,
                         generator=gen, continuous_slots=2,
                         prefill_segment=6)
        try:
            h = api.health_status()
            assert "queued_prefill_tokens" in h
            assert "p50_ms_per_tok" in h
            assert "prefill_ms_per_tok" in h
        finally:
            api.stop()
