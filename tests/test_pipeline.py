"""GPipe-style pipeline parallelism: schedule correctness vs sequential,
gradients, the pipelined-transformer layer, and end-to-end training."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from veles_tpu.parallel import pipeline  # noqa: E402
from veles_tpu.parallel.mesh import make_mesh  # noqa: E402


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stacked_params(s=4, d=8, seed=0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(s, d, d).astype(np.float32) * 0.5),
            "b": jnp.asarray(r.randn(s, d).astype(np.float32) * 0.1)}


def _sequential(params, x):
    h, _ = jax.lax.scan(lambda h, p: (_stage_fn(p, h), None), x, params)
    return h


class TestPipelineSchedule:
    @pytest.mark.parametrize("s,m", [(4, 4), (8, 2), (2, 8)])
    def test_matches_sequential(self, s, m):
        params = _stacked_params(s)
        x = jnp.asarray(np.random.RandomState(1).randn(16, 8)
                        .astype(np.float32))
        ref = _sequential(params, x)
        mesh = make_mesh({"pipe": s})
        out = pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh,
                                              n_microbatches=m)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("s,pipe", [(8, 4), (8, 2), (12, 4)])
    def test_multiple_stages_per_device(self, s, pipe):
        """n_blocks = k * pipe_size: each device runs its k local stages
        sequentially (regression: earlier code silently ran only the
        first local stage)."""
        params = _stacked_params(s)
        x = jnp.asarray(np.random.RandomState(4).randn(8, 8)
                        .astype(np.float32))
        ref = _sequential(params, x)
        mesh = make_mesh({"pipe": pipe})
        out = pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh,
                                              n_microbatches=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-5, atol=5e-5)

    def test_rejects_indivisible_stage_count(self):
        params = _stacked_params(6)
        x = jnp.zeros((8, 8), jnp.float32)
        mesh = make_mesh({"pipe": 4})
        with pytest.raises(ValueError, match="stage dim"):
            pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh)

    def test_rejects_indivisible_microbatches(self):
        params = _stacked_params(4)
        x = jnp.zeros((10, 8), jnp.float32)
        mesh = make_mesh({"pipe": 4})
        with pytest.raises(ValueError, match="microbatch"):
            pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh,
                                            n_microbatches=3)

    def test_gradients_match_sequential(self):
        params = _stacked_params(4)
        x = jnp.asarray(np.random.RandomState(2).randn(8, 8)
                        .astype(np.float32))
        mesh = make_mesh({"pipe": 4})

        g_ref = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(params)
        g_pp = jax.grad(lambda p: jnp.sum(pipeline.pipeline_apply_sharded(
            _stage_fn, p, x, mesh, n_microbatches=4) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g_pp["w"]),
                                   np.asarray(g_ref["w"]),
                                   rtol=5e-4, atol=5e-4)

    def test_data_pipe_combined_matches_sequential(self):
        """Combined {data, pipe} mesh: each data slice pipelines its own
        batch rows (batch_axis) — outputs AND gradients must match the
        sequential scan (round-1's stage-dropping bug was exactly a
        combined-config class; this pins the data x pipe member)."""
        params = _stacked_params(4)
        x = jnp.asarray(np.random.RandomState(5).randn(16, 8)
                        .astype(np.float32))
        mesh = make_mesh({"data": 2, "pipe": 4})

        def pp(p, xx):
            return pipeline.pipeline_apply_sharded(
                _stage_fn, p, xx, mesh, n_microbatches=2,
                batch_axis="data")

        ref = _sequential(params, x)
        np.testing.assert_allclose(np.asarray(pp(params, x)),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)
        g_ref = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(params)
        g_pp = jax.grad(lambda p: jnp.sum(pp(p, x) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g_pp["w"]),
                                   np.asarray(g_ref["w"]),
                                   rtol=5e-4, atol=5e-4)


class TestPipelinedTransformerLayer:
    def test_sharded_matches_sequential_scan(self):
        from veles_tpu import prng
        from veles_tpu.models.layers import make_layer
        prng.seed_all(7)
        cfg = {"type": "pipelined_transformer", "n_blocks": 4,
               "n_heads": 2, "d_ff": 32, "n_microbatches": 2}
        seq = make_layer(dict(cfg))
        par = make_layer(dict(cfg))
        assert seq.setup((8, 16)) == (8, 16)
        par.setup((8, 16))
        params = seq.init_params(prng.get("pp"))
        x = jnp.asarray(np.random.RandomState(3).randn(4, 8, 16)
                        .astype(np.float32))
        ref = seq.apply(params, x)
        par.mesh = make_mesh({"pipe": 4})
        out = par.apply(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestPipelinedTraining:
    def test_trains_on_pipe_mesh(self):
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        from veles_tpu.parallel import MeshConfig
        prng.seed_all(55)
        n = 16
        x = np.random.RandomState(0).rand(2 * n, 8, 4).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 3, 2 * n).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=8,
                                 class_lengths=[0, n, n])
        gd = {"learning_rate": 0.01, "gradient_moment": 0.9,
              "solver": "adam"}
        wf = StandardWorkflow(
            layers=[dict({"type": "timestep_dense",
                          "output_sample_shape": 16}, **gd),
                    {"type": "positional_encoding"},
                    dict({"type": "pipelined_transformer", "n_blocks": 4,
                          "n_heads": 2, "n_microbatches": 2}, **gd),
                    {"type": "seq_pool", "mode": "mean"},
                    dict({"type": "softmax", "output_sample_shape": 3},
                         **gd)],
            loader=loader, decision_config={"max_epochs": 2},
            mesh_config=MeshConfig(make_mesh({"data": 1, "pipe": 4})),
            name="pp-train")
        wf.initialize()
        wf.run()
        res = wf.gather_results()
        assert res["epochs"] == 2 and res["best_metric"] is not None


    def test_trains_on_combined_data_pipe_mesh(self):
        """The full hot loop on {data: 2, pipe: 2}: training converges to
        the same metrics as the meshless run (float-reorder tolerance)."""
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        from veles_tpu.parallel import MeshConfig

        def run(mesh_config):
            prng.seed_all(55)
            n = 16
            x = np.random.RandomState(0).rand(2 * n, 8, 4)\
                .astype(np.float32)
            y = np.random.RandomState(1).randint(0, 3, 2 * n)\
                .astype(np.int32)
            loader = FullBatchLoader(None, data=x, labels=y,
                                     minibatch_size=8,
                                     class_lengths=[0, n, n])
            gd = {"learning_rate": 0.01, "gradient_moment": 0.9,
                  "solver": "adam"}
            wf = StandardWorkflow(
                layers=[dict({"type": "timestep_dense",
                              "output_sample_shape": 16}, **gd),
                        {"type": "positional_encoding"},
                        dict({"type": "pipelined_transformer",
                              "n_blocks": 4, "n_heads": 2,
                              "n_microbatches": 2}, **gd),
                        {"type": "seq_pool", "mode": "mean"},
                        dict({"type": "softmax",
                              "output_sample_shape": 3}, **gd)],
                loader=loader, decision_config={"max_epochs": 2},
                mesh_config=mesh_config, name="dp-pp-train")
            wf.initialize()
            wf.run()
            return wf.gather_results()

        res = run(MeshConfig(make_mesh({"data": 2, "pipe": 2},
                                       jax.devices()[:4])))
        ref = run(None)
        assert res["epochs"] == ref["epochs"] == 2
        assert res["best_metric"] == pytest.approx(ref["best_metric"],
                                                   rel=1e-3)


class TestParamSharding:
    def test_pipe_and_expert_params_actually_shard(self):
        """Each device must hold ONLY its stage / its experts (the memory
        scaling PP/EP exist for), not a full replica."""
        from veles_tpu import prng
        from veles_tpu.models.layers import make_layer
        from veles_tpu.parallel import MeshConfig, sharding
        prng.seed_all(70)

        pp = make_layer({"type": "pipelined_transformer", "n_blocks": 8,
                         "n_heads": 2, "d_ff": 32})
        pp.setup((8, 16))
        params = {pp.name: pp.init_params(prng.get("x"))}
        mc = MeshConfig(make_mesh({"data": 1, "pipe": 8}))
        ov = {pp.name: pp.param_partition_specs(dict(mc.mesh.shape))}
        placed = jax.tree_util.tree_map(
            lambda x: x, sharding.shard_params(params, mc, ov))
        w1 = placed[pp.name]["stages"]["w1"]
        assert w1.shape[0] == 8
        assert w1.addressable_shards[0].data.shape[0] == 1

        moe_layer = make_layer({"type": "moe", "n_experts": 8,
                                "d_ff": 32})
        moe_layer.setup((8, 16))
        mparams = {moe_layer.name: moe_layer.init_params(prng.get("y"))}
        emc = MeshConfig(make_mesh({"data": 1, "expert": 8}))
        eov = {moe_layer.name:
               moe_layer.param_partition_specs(dict(emc.mesh.shape))}
        eplaced = sharding.shard_params(mparams, emc, eov)
        ew1 = eplaced[moe_layer.name]["w1"]
        assert ew1.addressable_shards[0].data.shape[0] == 1
        # router replicates
        router = eplaced[moe_layer.name]["router"]
        assert router.addressable_shards[0].data.shape == router.shape


def test_pipelined_transformer_propagates_gqa():
    """n_kv_heads must reach the inner TransformerBlock (not be dropped)."""
    from veles_tpu import prng
    from veles_tpu.models.layers import make_layer

    prng.seed_all(3)
    layer = make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                        "n_heads": 4, "n_kv_heads": 2})
    layer.setup((8, 16))
    params = layer.init_params(prng.get("t"))
    wk = params["stages"]["mha"]["wk"]       # [n_blocks, d_model, d_kv]
    assert wk.shape == (2, 16, 8), wk.shape  # 2 kv heads of dim 4


def test_pipelined_transformer_propagates_rope_and_window():
    from veles_tpu import prng
    from veles_tpu.models.layers import make_layer

    prng.seed_all(4)
    layer = make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                        "n_heads": 4, "causal": True, "rope": True,
                        "window": 4})
    layer.setup((8, 16))
    assert layer._block.cfg["rope"] is True
    assert layer._block.cfg["window"] == 4


def test_pipelined_transformer_rejects_unsupported_options():
    """Options the pipeline wrapper cannot honor fail loudly instead of
    silently degrading (MoE aux loss can't cross the stage scan;
    seq-parallel attention can't nest inside the pipe shard_map)."""
    from veles_tpu.models.layers import make_layer

    with pytest.raises(ValueError, match="MoE"):
        make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                    "n_heads": 4, "n_experts": 2}).setup((8, 16))
    with pytest.raises(ValueError, match="sequence-"):
        make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                    "n_heads": 4, "impl": "ring"}).setup((8, 16))
