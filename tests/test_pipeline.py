"""GPipe-style pipeline parallelism: schedule correctness vs sequential,
gradients, the pipelined-transformer layer, and end-to-end training."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from veles_tpu.parallel import pipeline  # noqa: E402
from veles_tpu.parallel.mesh import make_mesh  # noqa: E402


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stacked_params(s=4, d=8, seed=0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(s, d, d).astype(np.float32) * 0.5),
            "b": jnp.asarray(r.randn(s, d).astype(np.float32) * 0.1)}


def _sequential(params, x):
    h, _ = jax.lax.scan(lambda h, p: (_stage_fn(p, h), None), x, params)
    return h


class TestPipelineSchedule:
    @pytest.mark.parametrize("s,m", [(4, 4), (8, 2), (2, 8)])
    def test_matches_sequential(self, s, m):
        params = _stacked_params(s)
        x = jnp.asarray(np.random.RandomState(1).randn(16, 8)
                        .astype(np.float32))
        ref = _sequential(params, x)
        mesh = make_mesh({"pipe": s})
        out = pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh,
                                              n_microbatches=m)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("s,pipe", [(8, 4), (8, 2), (12, 4)])
    def test_multiple_stages_per_device(self, s, pipe):
        """n_blocks = k * pipe_size: each device runs its k local stages
        sequentially (regression: earlier code silently ran only the
        first local stage)."""
        params = _stacked_params(s)
        x = jnp.asarray(np.random.RandomState(4).randn(8, 8)
                        .astype(np.float32))
        ref = _sequential(params, x)
        mesh = make_mesh({"pipe": pipe})
        out = pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh,
                                              n_microbatches=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=5e-5, atol=5e-5)

    def test_rejects_indivisible_stage_count(self):
        params = _stacked_params(6)
        x = jnp.zeros((8, 8), jnp.float32)
        mesh = make_mesh({"pipe": 4})
        with pytest.raises(ValueError, match="stage dim"):
            pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh)

    def test_rejects_indivisible_microbatches(self):
        params = _stacked_params(4)
        x = jnp.zeros((10, 8), jnp.float32)
        mesh = make_mesh({"pipe": 4})
        with pytest.raises(ValueError, match="microbatch"):
            pipeline.pipeline_apply_sharded(_stage_fn, params, x, mesh,
                                            n_microbatches=3)

    def test_gradients_match_sequential(self):
        params = _stacked_params(4)
        x = jnp.asarray(np.random.RandomState(2).randn(8, 8)
                        .astype(np.float32))
        mesh = make_mesh({"pipe": 4})

        g_ref = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(params)
        g_pp = jax.grad(lambda p: jnp.sum(pipeline.pipeline_apply_sharded(
            _stage_fn, p, x, mesh, n_microbatches=4) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g_pp["w"]),
                                   np.asarray(g_ref["w"]),
                                   rtol=5e-4, atol=5e-4)

    def test_data_pipe_combined_matches_sequential(self):
        """Combined {data, pipe} mesh: each data slice pipelines its own
        batch rows (batch_axis) — outputs AND gradients must match the
        sequential scan (round-1's stage-dropping bug was exactly a
        combined-config class; this pins the data x pipe member)."""
        params = _stacked_params(4)
        x = jnp.asarray(np.random.RandomState(5).randn(16, 8)
                        .astype(np.float32))
        mesh = make_mesh({"data": 2, "pipe": 4})

        def pp(p, xx):
            return pipeline.pipeline_apply_sharded(
                _stage_fn, p, xx, mesh, n_microbatches=2,
                batch_axis="data")

        ref = _sequential(params, x)
        np.testing.assert_allclose(np.asarray(pp(params, x)),
                                   np.asarray(ref), rtol=2e-5, atol=2e-5)
        g_ref = jax.grad(lambda p: jnp.sum(_sequential(p, x) ** 2))(params)
        g_pp = jax.grad(lambda p: jnp.sum(pp(p, x) ** 2))(params)
        np.testing.assert_allclose(np.asarray(g_pp["w"]),
                                   np.asarray(g_ref["w"]),
                                   rtol=5e-4, atol=5e-4)


class TestPipelinedTransformerLayer:
    def test_sharded_matches_sequential_scan(self):
        from veles_tpu import prng
        from veles_tpu.models.layers import make_layer
        prng.seed_all(7)
        cfg = {"type": "pipelined_transformer", "n_blocks": 4,
               "n_heads": 2, "d_ff": 32, "n_microbatches": 2}
        seq = make_layer(dict(cfg))
        par = make_layer(dict(cfg))
        assert seq.setup((8, 16)) == (8, 16)
        par.setup((8, 16))
        params = seq.init_params(prng.get("pp"))
        x = jnp.asarray(np.random.RandomState(3).randn(4, 8, 16)
                        .astype(np.float32))
        ref = seq.apply(params, x)
        par.mesh = make_mesh({"pipe": 4})
        out = par.apply(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestPipelinedTraining:
    def test_trains_on_pipe_mesh(self):
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        from veles_tpu.parallel import MeshConfig
        prng.seed_all(55)
        n = 16
        x = np.random.RandomState(0).rand(2 * n, 8, 4).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 3, 2 * n).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=8,
                                 class_lengths=[0, n, n])
        gd = {"learning_rate": 0.01, "gradient_moment": 0.9,
              "solver": "adam"}
        wf = StandardWorkflow(
            layers=[dict({"type": "timestep_dense",
                          "output_sample_shape": 16}, **gd),
                    {"type": "positional_encoding"},
                    dict({"type": "pipelined_transformer", "n_blocks": 4,
                          "n_heads": 2, "n_microbatches": 2}, **gd),
                    {"type": "seq_pool", "mode": "mean"},
                    dict({"type": "softmax", "output_sample_shape": 3},
                         **gd)],
            loader=loader, decision_config={"max_epochs": 2},
            mesh_config=MeshConfig(make_mesh({"data": 1, "pipe": 4})),
            name="pp-train")
        wf.initialize()
        wf.run()
        res = wf.gather_results()
        assert res["epochs"] == 2 and res["best_metric"] is not None


    def test_trains_on_combined_data_pipe_mesh(self):
        """The full hot loop on {data: 2, pipe: 2}: training converges to
        the same metrics as the meshless run (float-reorder tolerance)."""
        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        from veles_tpu.parallel import MeshConfig

        def run(mesh_config):
            prng.seed_all(55)
            n = 16
            x = np.random.RandomState(0).rand(2 * n, 8, 4)\
                .astype(np.float32)
            y = np.random.RandomState(1).randint(0, 3, 2 * n)\
                .astype(np.int32)
            loader = FullBatchLoader(None, data=x, labels=y,
                                     minibatch_size=8,
                                     class_lengths=[0, n, n])
            gd = {"learning_rate": 0.01, "gradient_moment": 0.9,
                  "solver": "adam"}
            wf = StandardWorkflow(
                layers=[dict({"type": "timestep_dense",
                              "output_sample_shape": 16}, **gd),
                        {"type": "positional_encoding"},
                        dict({"type": "pipelined_transformer",
                              "n_blocks": 4, "n_heads": 2,
                              "n_microbatches": 2}, **gd),
                        {"type": "seq_pool", "mode": "mean"},
                        dict({"type": "softmax",
                              "output_sample_shape": 3}, **gd)],
                loader=loader, decision_config={"max_epochs": 2},
                mesh_config=mesh_config, name="dp-pp-train")
            wf.initialize()
            wf.run()
            return wf.gather_results()

        res = run(MeshConfig(make_mesh({"data": 2, "pipe": 2},
                                       jax.devices()[:4])))
        ref = run(None)
        assert res["epochs"] == ref["epochs"] == 2
        assert res["best_metric"] == pytest.approx(ref["best_metric"],
                                                   rel=1e-3)


class TestParamSharding:
    def test_pipe_and_expert_params_actually_shard(self):
        """Each device must hold ONLY its stage / its experts (the memory
        scaling PP/EP exist for), not a full replica."""
        from veles_tpu import prng
        from veles_tpu.models.layers import make_layer
        from veles_tpu.parallel import MeshConfig, sharding
        prng.seed_all(70)

        pp = make_layer({"type": "pipelined_transformer", "n_blocks": 8,
                         "n_heads": 2, "d_ff": 32})
        pp.setup((8, 16))
        params = {pp.name: pp.init_params(prng.get("x"))}
        mc = MeshConfig(make_mesh({"data": 1, "pipe": 8}))
        ov = {pp.name: pp.param_partition_specs(dict(mc.mesh.shape))}
        placed = jax.tree_util.tree_map(
            lambda x: x, sharding.shard_params(params, mc, ov))
        w1 = placed[pp.name]["stages"]["w1"]
        assert w1.shape[0] == 8
        assert w1.addressable_shards[0].data.shape[0] == 1

        moe_layer = make_layer({"type": "moe", "n_experts": 8,
                                "d_ff": 32})
        moe_layer.setup((8, 16))
        mparams = {moe_layer.name: moe_layer.init_params(prng.get("y"))}
        emc = MeshConfig(make_mesh({"data": 1, "expert": 8}))
        eov = {moe_layer.name:
               moe_layer.param_partition_specs(dict(emc.mesh.shape))}
        eplaced = sharding.shard_params(mparams, emc, eov)
        ew1 = eplaced[moe_layer.name]["w1"]
        assert ew1.addressable_shards[0].data.shape[0] == 1
        # router replicates
        router = eplaced[moe_layer.name]["router"]
        assert router.addressable_shards[0].data.shape == router.shape


def test_pipelined_transformer_propagates_gqa():
    """n_kv_heads must reach the inner TransformerBlock (not be dropped)."""
    from veles_tpu import prng
    from veles_tpu.models.layers import make_layer

    prng.seed_all(3)
    layer = make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                        "n_heads": 4, "n_kv_heads": 2})
    layer.setup((8, 16))
    params = layer.init_params(prng.get("t"))
    wk = params["stages"]["mha"]["wk"]       # [n_blocks, d_model, d_kv]
    assert wk.shape == (2, 16, 8), wk.shape  # 2 kv heads of dim 4


def test_pipelined_transformer_propagates_rope_and_window():
    from veles_tpu import prng
    from veles_tpu.models.layers import make_layer

    prng.seed_all(4)
    layer = make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                        "n_heads": 4, "causal": True, "rope": True,
                        "window": 4})
    layer.setup((8, 16))
    assert layer._block.cfg["rope"] is True
    assert layer._block.cfg["window"] == 4


def test_pipelined_transformer_rejects_unsupported_options():
    """Options the pipeline wrapper cannot honor fail loudly instead of
    silently degrading (MoE aux loss can't cross the stage scan;
    seq-parallel attention can't nest inside the pipe shard_map)."""
    from veles_tpu.models.layers import make_layer

    with pytest.raises(ValueError, match="MoE"):
        make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                    "n_heads": 4, "n_experts": 2}).setup((8, 16))
    with pytest.raises(ValueError, match="sequence-"):
        make_layer({"type": "pipelined_transformer", "n_blocks": 2,
                    "n_heads": 4, "impl": "ring"}).setup((8, 16))


class Test1F1B:
    """1F1B training schedule: grad/loss parity vs single-device
    autodiff AND vs GPipe, uneven stages (embed→blocks→head), and the
    O(M)→O(S) activation-memory win (compiled temp bytes)."""

    D, V, T = 8, 12, 6

    def _params(self, n_blocks=4, seed=0):
        r = np.random.RandomState(seed)
        f32 = np.float32
        p_first = {"emb": jnp.asarray(r.randn(self.V, self.D)
                                      .astype(f32) * 0.5)}
        p_blocks = {"w": jnp.asarray(r.randn(n_blocks, self.D, self.D)
                                     .astype(f32) * 0.5),
                    "b": jnp.asarray(r.randn(n_blocks, self.D)
                                     .astype(f32) * 0.1)}
        p_last = {"head": jnp.asarray(r.randn(self.D, self.V)
                                      .astype(f32) * 0.5)}
        return p_first, p_blocks, p_last

    @staticmethod
    def _first(p, x_mb):
        return p["emb"][x_mb]                      # int tokens -> h

    @staticmethod
    def _last(p, h, y_mb):
        logits = h @ p["head"]
        logp = jax.nn.log_softmax(logits, axis=-1)
        oh = jax.nn.one_hot(y_mb, logits.shape[-1])
        return -jnp.mean(jnp.sum(logp * oh, axis=-1))

    def _data(self, batch=8, seed=1):
        r = np.random.RandomState(seed)
        x = jnp.asarray(r.randint(0, self.V, (batch, self.T))
                        .astype(np.int32))
        y = jnp.asarray(r.randint(0, self.V, (batch, self.T))
                        .astype(np.int32))
        return x, y

    def _ref_loss(self, params, x, y):
        pf, pb, pl = params
        h, _ = jax.lax.scan(lambda hh, pk: (_stage_fn(pk, hh), None),
                            self._first(pf, x), pb)
        return self._last(pl, h, y)

    @pytest.mark.parametrize("pipe,m", [(4, 4), (4, 8), (2, 4), (8, 8)])
    def test_loss_and_grads_match_single_device(self, pipe, m):
        params = self._params(n_blocks=pipe)
        x, y = self._data(batch=2 * m)
        mesh = make_mesh({"pipe": pipe})
        loss, grads = pipeline.pipeline_train_1f1b_sharded(
            _stage_fn, self._first, self._last, params, x, y, mesh,
            n_microbatches=m)
        ref_loss, ref_grads = jax.value_and_grad(self._ref_loss)(
            (params[0], params[1], params[2]), x, y)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        for g, r in zip(jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=2e-4, atol=2e-4)

    def test_block_grads_match_gpipe(self):
        """Same blocks, same loss: 1F1B's block grads == autodiff
        through the GPipe schedule (first/last outside the pipe)."""
        params = self._params()
        pf, pb, pl = params
        x, y = self._data(batch=8)
        mesh = make_mesh({"pipe": 4})

        def gpipe_loss(pb_):
            h = pipeline.pipeline_apply_sharded(
                _stage_fn, pb_, self._first(pf, x), mesh,
                n_microbatches=4)
            return self._last(pl, h, y)

        g_gpipe = jax.grad(gpipe_loss)(pb)
        _, (_, g_blocks, _) = pipeline.pipeline_train_1f1b_sharded(
            _stage_fn, self._first, self._last, params, x, y, mesh,
            n_microbatches=4)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(g_blocks[k]),
                                       np.asarray(g_gpipe[k]),
                                       rtol=2e-4, atol=2e-4)

    def test_multiple_blocks_per_device(self):
        params = self._params(n_blocks=8)
        x, y = self._data(batch=8)
        mesh = make_mesh({"pipe": 4})           # 2 blocks per device
        loss, grads = pipeline.pipeline_train_1f1b_sharded(
            _stage_fn, self._first, self._last, params, x, y, mesh,
            n_microbatches=4)
        ref_loss, ref_grads = jax.value_and_grad(self._ref_loss)(
            params, x, y)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads[1]["w"]), np.asarray(ref_grads[1]["w"]),
            rtol=2e-4, atol=2e-4)

    def test_data_pipe_combined(self):
        params = self._params()
        x, y = self._data(batch=16)
        mesh = make_mesh({"data": 2, "pipe": 4})
        loss, grads = pipeline.pipeline_train_1f1b_sharded(
            _stage_fn, self._first, self._last, params, x, y, mesh,
            n_microbatches=4, batch_axis="data")
        ref_loss, ref_grads = jax.value_and_grad(self._ref_loss)(
            params, x, y)
        # each data slice averages its half-batch; mean of means ==
        # full-batch mean here because the halves are equal-sized
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads[1]["w"]), np.asarray(ref_grads[1]["w"]),
            rtol=2e-4, atol=2e-4)

    def test_activation_memory_m_to_s(self):
        """THE 1F1B selling point: compiled temp memory stays ~flat as
        M grows (O(S) stash) while autodiff-through-GPipe grows with M
        (O(M) residuals).  Fixed microbatch size, growing batch."""
        pf, pb, pl = self._params()
        mesh = make_mesh({"pipe": 4})
        mbsz = 4

        def temp_bytes_1f1b(m):
            x, y = self._data(batch=mbsz * m)
            f = jax.jit(lambda p, xx, yy:
                        pipeline.pipeline_train_1f1b_sharded(
                            _stage_fn, self._first, self._last, p,
                            xx, yy, mesh, n_microbatches=m))
            mem = f.lower((pf, pb, pl), x, y).compile().memory_analysis()
            return mem.temp_size_in_bytes

        def temp_bytes_gpipe(m):
            x, y = self._data(batch=mbsz * m)

            def loss_fn(p, xx, yy):
                pf_, pb_, pl_ = p
                h = pipeline.pipeline_apply_sharded(
                    _stage_fn, pb_, self._first(pf_, xx), mesh,
                    n_microbatches=m)
                return self._last(pl_, h, yy)

            f = jax.jit(jax.grad(loss_fn))
            mem = f.lower((pf, pb, pl), x, y).compile().memory_analysis()
            return mem.temp_size_in_bytes

        one_small, one_big = temp_bytes_1f1b(4), temp_bytes_1f1b(32)
        gp_small, gp_big = temp_bytes_gpipe(4), temp_bytes_gpipe(32)
        # GPipe residuals grow ~linearly in M; the 1F1B stash does not
        # (only the raw token/output buffers scale with batch)
        assert gp_big / gp_small > 3.0, (gp_small, gp_big)
        assert one_big / one_small < 2.0, (one_small, one_big)
        assert one_big < gp_big / 2, (one_big, gp_big)


class TestInterleaved1F1B:
    """Megatron-style virtual-stage schedule: verified tables, parity
    with single-device autodiff, and the bubble reduction over plain
    1F1B."""

    def test_schedule_tables_verify_and_v1_matches_plain(self):
        from veles_tpu.parallel.interleave import build_schedule
        tab = build_schedule(4, 1, 8)
        # v=1 degenerates to the plain 1F1B tick count m + 2(S-1)
        assert tab["n_ticks"] == 8 + 2 * 3
        tab2 = build_schedule(4, 2, 8)
        assert tab2["n_ticks"] > 0 and tab2["n_stash"] >= 2
        # every unit appears exactly once per direction per device
        for d in range(4):
            for name in ("fwd_mb", "bwd_mb"):
                row = tab2[name][d]
                assert (row >= 0).sum() == 8 * 2

    def test_bubble_shrinks_with_chunks(self):
        """The reason interleaving exists: wall-clock in chunk-compute
        units drops vs plain 1F1B on the same work (plain runs v
        chunks per tick over m + 2(S-1) ticks; interleaved runs one)."""
        from veles_tpu.parallel.interleave import build_schedule
        for s, m in ((4, 8), (8, 8), (4, 16)):
            for v in (2, 4):
                t_int = build_schedule(s, v, m)["n_ticks"]
                t_plain = (m + 2 * (s - 1)) * v
                assert t_int < t_plain, (s, v, m, t_int, t_plain)

    def test_rejects_microbatches_not_multiple_of_pipe(self):
        from veles_tpu.parallel.interleave import build_schedule
        with pytest.raises(ValueError, match="multiple"):
            build_schedule(4, 2, 6)

    @pytest.mark.parametrize("pipe,v,m,nb", [(4, 2, 8, 8), (2, 2, 4, 4),
                                             (4, 2, 8, 16), (4, 4, 8, 16),
                                             (8, 2, 8, 16)])
    def test_loss_and_grads_match_single_device(self, pipe, v, m, nb):
        t = Test1F1B()
        pf, _, pl = t._params()
        r = np.random.RandomState(8)
        pb = {"w": jnp.asarray(r.randn(nb, t.D, t.D)
                               .astype(np.float32) * 0.5),
              "b": jnp.asarray(r.randn(nb, t.D).astype(np.float32) * 0.1)}
        x, y = t._data(batch=2 * m)
        mesh = make_mesh({"pipe": pipe})
        loss, grads = pipeline.pipeline_train_interleaved_sharded(
            _stage_fn, t._first, t._last, (pf, pb, pl), x, y, mesh,
            n_microbatches=m, n_chunks=v)
        ref_loss, ref_grads = jax.value_and_grad(t._ref_loss)(
            (pf, pb, pl), x, y)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        for g, rg in zip(jax.tree_util.tree_leaves(grads),
                         jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                       rtol=2e-4, atol=2e-4)


class Test1F1BTrainsEndToEnd:
    """The 1F1B schedules drive a REAL training loop: the repo's own
    optimizer (models.optimizer) consumes pipeline grads on the
    virtual mesh and the loss trajectory tracks single-device autodiff
    training step for step — the schedules are a drop-in gradient
    engine, not just a parity demo."""

    @pytest.mark.parametrize("interleaved", [False, True])
    def test_lm_loss_tracks_single_device_training(self, interleaved):
        from veles_tpu.models import optimizer

        t = Test1F1B()
        pf, pb, pl = t._params(n_blocks=8 if interleaved else 4)
        x, y = t._data(batch=8)
        mesh = make_mesh({"pipe": 4})
        hypers = {"m": optimizer.resolve_hyper(
            {"solver": "adam", "learning_rate": 0.01,
             "gradient_moment": 0.9})}

        def train(grad_fn, params):
            params = {"m": dict(zip("fbl", params))}
            state = optimizer.init_state(params)
            losses = []
            for _ in range(12):
                p = tuple(params["m"][k] for k in "fbl")
                loss, grads = grad_fn(p)
                losses.append(float(loss))
                g = {"m": dict(zip("fbl", grads))}
                params, state = optimizer.update(params, g, state,
                                                 hypers)
            return losses

        if interleaved:
            pp_grads = jax.jit(lambda p: pipeline.pipeline_train_interleaved_sharded(  # noqa: E731,E501
                _stage_fn, t._first, t._last, p, x, y, mesh,
                n_microbatches=4, n_chunks=2))
        else:
            pp_grads = jax.jit(lambda p: pipeline.pipeline_train_1f1b_sharded(  # noqa: E731,E501
                _stage_fn, t._first, t._last, p, x, y, mesh,
                n_microbatches=4))
        ref_grads = jax.jit(jax.value_and_grad(
            lambda p: t._ref_loss(p, x, y)))

        pp_losses = train(pp_grads, (pf, pb, pl))
        ref_losses = train(ref_grads, (pf, pb, pl))
        # same grads + same deterministic optimizer => same trajectory
        np.testing.assert_allclose(pp_losses, ref_losses, rtol=1e-4)
        assert pp_losses[-1] < pp_losses[0] * 0.8   # it actually learns


def test_1f1b_composes_with_tensor_parallel_stages():
    """pipe x model mesh: each 1F1B stage is ALSO Megatron
    column-parallel (w sharded over the model axis inside the pipe
    shard_map, all_gather reassembling activations) — loss and grads
    still match single-device autodiff.  The composition the
    multi-axis story needs: the schedule owns the pipe axis, the
    stage owns the model axis."""
    t = Test1F1B()
    pf, pb, pl = t._params()
    x, y = t._data(batch=8)
    mesh = make_mesh({"pipe": 4, "model": 2})

    # Megatron's conjugate f/g pair, written explicitly because
    # check_vma=False autodiff won't track replication: f = identity
    # fwd / psum bwd (the stage input is replicated over model, its
    # partial cotangents must sum); g = all_gather fwd / slice-own-
    # part bwd (the gathered activation is replicated, so the
    # default psum-scatter transpose would double-count).
    @jax.custom_vjp
    def f_ident_psum(h):
        return h

    f_ident_psum.defvjp(lambda h: (h, None),
                        lambda _, g: (jax.lax.psum(g, "model"),))

    @jax.custom_vjp
    def g_gather(y_part):
        return jax.lax.all_gather(y_part, "model", axis=-1, tiled=True)

    def _g_fwd(y_part):
        return g_gather(y_part), y_part.shape[-1]

    def _g_bwd(width, g):
        lo = jax.lax.axis_index("model") * width
        return (jax.lax.dynamic_slice_in_dim(g, lo, width, axis=-1),)

    g_gather.defvjp(_g_fwd, _g_bwd)

    def tp_stage(p, h):
        y_part = f_ident_psum(h) @ p["w"]   # local output columns
        return jnp.tanh(g_gather(y_part) + p["b"])

    from jax.sharding import PartitionSpec as P
    loss, grads = pipeline.pipeline_train_1f1b_sharded(
        tp_stage, t._first, t._last, (pf, pb, pl), x, y, mesh,
        n_microbatches=4,
        block_specs={"w": P("pipe", None, "model"), "b": P("pipe")})
    ref_loss, ref_grads = jax.value_and_grad(t._ref_loss)(
        (pf, pb, pl), x, y)
    assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
    for g, rg in zip(jax.tree_util.tree_leaves(grads),
                     jax.tree_util.tree_leaves(ref_grads)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   rtol=2e-4, atol=2e-4)


def test_block_specs_must_shard_stage_dim_over_pipe():
    """A block_specs leaf missing pipe on dim 0 would replicate the
    whole stack to every device (each stage runs the full network —
    silently wrong numbers); it must fail loudly instead."""
    from jax.sharding import PartitionSpec as P
    t = Test1F1B()
    pf, pb, pl = t._params()
    x, y = t._data(batch=8)
    mesh = make_mesh({"pipe": 4})
    with pytest.raises(ValueError, match="leading"):
        pipeline.pipeline_train_1f1b_sharded(
            _stage_fn, t._first, t._last, (pf, pb, pl), x, y, mesh,
            n_microbatches=4,
            block_specs={"w": P(None, None), "b": P("pipe")})
