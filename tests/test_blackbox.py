"""Flight recorder, crash forensics & health watchdog
(veles_tpu.telemetry.flight / .health / .blackbox): ring semantics under
overflow and concurrency, atomic crashdump production (including from
the fault-injection crash path), watchdog stall detection, multi-host
desync detection, the /api/health surface, the Launcher service-leak
fix, and the veles-tpu-blackbox merge CLI."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from veles_tpu.telemetry import blackbox, flight, health
from veles_tpu.telemetry.flight import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def blackbox_dir(tmp_path):
    """Point crashdumps at tmp and restore the config + any armed
    watchdog afterwards (dumps must never land in the repo's
    artifacts/ from a test)."""
    from veles_tpu.config import root
    prev = root.common.blackbox.get("dir", "artifacts")
    root.common.blackbox.dir = str(tmp_path)
    try:
        yield tmp_path
    finally:
        root.common.blackbox.dir = prev
        health.disarm_watchdog()


class TestRing:
    def test_overflow_keeps_newest_and_counts_dropped(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("e", i=i)
        events = rec.snapshot()
        assert len(events) == 8
        assert [e["i"] for e in events] == list(range(12, 20))
        assert rec.dropped == 12
        assert rec.appended == 20

    def test_concurrent_appends_no_corruption(self):
        rec = FlightRecorder(capacity=100000)
        n = 5000

        def writer(tag):
            for i in range(n):
                rec.record("e", tag=tag, i=i)

        threads = [threading.Thread(target=writer, args=(t,))
                   for t in ("a", "b")]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        events = rec.snapshot()
        assert len(events) == 2 * n and rec.appended == 2 * n
        # per-thread order survives interleaving
        for tag in ("a", "b"):
            seq = [e["i"] for e in events if e["tag"] == tag]
            assert seq == list(range(n))

    def test_set_capacity_keeps_newest(self):
        rec = FlightRecorder(capacity=16)
        for i in range(16):
            rec.record("e", i=i)
        rec.set_capacity(4)
        assert [e["i"] for e in rec.snapshot()] == [12, 13, 14, 15]

    def test_record_reentrant_under_signal(self, blackbox_dir):
        """A SIGTERM/SIGABRT handler records+dumps from the main thread
        and may land while the interrupted frame is inside record()'s
        critical section — the ring lock must be re-entrant or the
        handler deadlocks its own thread."""
        rec = FlightRecorder(capacity=8)
        with rec._lock:             # simulate the interrupted section
            rec.record("from-handler")
            assert rec.dump(directory=str(blackbox_dir)) is not None
        assert rec.snapshot()[-1]["kind"] == "from-handler"

    def test_record_overhead_under_budget(self):
        """Acceptance: ~2 µs/event budgeted; assert a generous CI bound
        and print the measured number (documented in docs/services.md
        next to the PR 3 span overhead)."""
        rec = FlightRecorder(capacity=4096)
        n = 20000
        t0 = time.perf_counter()
        for i in range(n):
            rec.record("bench", i=i)
        per_event = (time.perf_counter() - t0) / n
        print("flight.record overhead: %.2f us/event" % (per_event * 1e6))
        assert per_event < 50e-6     # ~25x the 2 µs target: CI headroom


class TestDump:
    def test_dump_contents_and_atomicity(self, blackbox_dir):
        rec = FlightRecorder(capacity=4)
        for i in range(6):
            rec.record("e", i=i)
        d = rec.dump(directory=str(blackbox_dir), reason="unit-test",
                     error=ValueError("boom"))
        assert d and os.path.basename(d).startswith("crashdump-")
        assert not [n for n in os.listdir(str(blackbox_dir))
                    if n.endswith(".tmp-%d" % os.getpid())]
        lines = [json.loads(l) for l in
                 open(os.path.join(d, "events.jsonl"))]
        assert lines[0]["kind"] == "flight.meta"
        assert lines[0]["dropped"] == 2 and lines[0]["events"] == 4
        assert [l["i"] for l in lines[1:]] == [2, 3, 4, 5]
        meta = json.load(open(os.path.join(d, "meta.json")))
        assert meta["reason"] == "unit-test"
        assert meta["error"] == {"type": "ValueError",
                                 "message": "boom"}
        cfg = json.load(open(os.path.join(d, "config.json")))
        assert "common" in cfg
        metrics = json.load(open(os.path.join(d, "metrics.json")))
        assert "metrics" in metrics and "records" in metrics
        stacks = open(os.path.join(d, "stacks.txt")).read()
        assert "MainThread" in stacks

    def test_dump_reentrant_safe(self, blackbox_dir):
        rec = FlightRecorder(capacity=4)
        rec.record("e")
        # a dump already in progress (watchdog racing an excepthook, or
        # a crash inside the dump itself) degrades to None, no deadlock
        assert rec._dump_lock.acquire(blocking=False)
        try:
            assert rec.dump(directory=str(blackbox_dir)) is None
        finally:
            rec._dump_lock.release()
        assert rec.dump(directory=str(blackbox_dir)) is not None

    def test_same_second_dumps_get_distinct_dirs(self, blackbox_dir):
        rec = FlightRecorder(capacity=4)
        rec.record("e")
        d1 = rec.dump(directory=str(blackbox_dir))
        d2 = rec.dump(directory=str(blackbox_dir))
        assert d1 != d2 and os.path.isdir(d1) and os.path.isdir(d2)
        assert rec.dump_count == 2 and rec.last_dump == d2

    def test_dump_never_raises(self):
        rec = FlightRecorder(capacity=4)
        rec.record("e")
        # unwritable target: black boxes fail soft, not loudly
        assert rec.dump(directory="/proc/definitely-not-writable") \
            is None


class TestFaultInjectionCrashdump:
    def test_fault_injected_run_writes_parseable_crashdump(
            self, blackbox_dir, tmp_path):
        """The existing simulated-crash path (death_probability →
        os._exit(1)) must leave a black box behind — exercised end to
        end in a subprocess, since the injected death takes the
        interpreter with it."""
        script = tmp_path / "crashy.py"
        script.write_text(
            "import sys\n"
            "from veles_tpu.config import root\n"
            "root.common.blackbox.dir = sys.argv[1]\n"
            "from veles_tpu.workflow import Workflow\n"
            "from veles_tpu.units import TrivialUnit\n"
            "wf = Workflow(name='crashy', death_probability=1.0)\n"
            "u = TrivialUnit(wf)\n"
            "u.link_from(wf.start_point)\n"
            "wf.initialize()\n"
            "wf.run()\n")
        out = tmp_path / "dumps"
        out.mkdir()
        r = subprocess.run(
            [sys.executable, str(script), str(out)],
            env=dict(os.environ, JAX_PLATFORMS="cpu",
                     PYTHONPATH=REPO), cwd=REPO,
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 1, r.stderr[-2000:]
        assert "Traceback" not in r.stderr, r.stderr[-2000:]
        dumps = [n for n in os.listdir(str(out))
                 if n.startswith("crashdump-")]
        assert len(dumps) == 1
        d = blackbox.load_dump(str(out / dumps[0]))
        assert d["meta"]["reason"] == "fault-injection"
        kinds = [e["kind"] for e in d["events"]]
        assert "fault.injected" in kinds and "workflow.start" in kinds
        assert d["stacks"] and "MainThread" in d["stacks"]


class TestWatchdog:
    def test_stall_dumps_without_killing(self, blackbox_dir):
        before = flight.recorder.dump_count
        wd = health.arm_watchdog(0.25)
        try:
            deadline = time.time() + 5.0
            while not wd.tripped and time.time() < deadline:
                time.sleep(0.05)
            assert wd.tripped, "watchdog never tripped on a stall"
            assert flight.recorder.dump_count == before + 1
            dumps = [n for n in os.listdir(str(blackbox_dir))
                     if n.startswith("crashdump-")]
            assert dumps, "no crashdump written by the watchdog"
            meta = json.load(open(
                str(blackbox_dir / dumps[0] / "meta.json")))
            assert meta["reason"] == "watchdog"
            # the run was not killed, and progress re-arms it
            health.note_progress(step=123)
            deadline = time.time() + 5.0
            while wd.tripped and time.time() < deadline:
                time.sleep(0.05)
            assert not wd.tripped, "watchdog did not re-arm on progress"
            # one dump per stall, not one per poll
            assert flight.recorder.dump_count == before + 1
        finally:
            health.disarm_watchdog()

    def test_disarmed_by_default_and_zero_window(self):
        assert health.watchdog() is None
        assert health.arm_watchdog(0) is None
        assert health.watchdog() is None


class TestMultihost:
    def test_desync_detected_and_latched(self, blackbox_dir,
                                         monkeypatch):
        import numpy as np

        import jax
        from jax.experimental import multihost_utils
        from veles_tpu.telemetry import MetricsRegistry
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            multihost_utils, "process_allgather",
            lambda local: np.asarray([[0.0, 5.0, 0.1],
                                      [1.0, 7.0, 0.4]]))
        reg = MetricsRegistry()
        before = flight.recorder.dump_count
        health.enable_multihost()
        try:
            out = health.multihost_check(5, 0.1, registry=reg)
            assert out["desync"] is True
            assert out["skew_s"] == pytest.approx(0.3)
            assert reg.gauge("veles_host_step", "", ("proc",)).value(
                proc=1) == 7.0
            assert reg.gauge(
                "veles_step_wall_skew_seconds").value() \
                == pytest.approx(0.3)
            assert flight.recorder.dump_count == before + 1
            kinds = [e["kind"] for e in flight.recorder.snapshot()]
            assert "desync" in kinds
            # latched: a second divergent heartbeat does not re-dump
            health.multihost_check(6, 0.1, registry=reg)
            assert flight.recorder.dump_count == before + 1
        finally:
            health.enable_multihost(False)

    def test_agreeing_hosts_are_clean(self, monkeypatch):
        import numpy as np

        import jax
        from jax.experimental import multihost_utils
        from veles_tpu.telemetry import MetricsRegistry
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            multihost_utils, "process_allgather",
            lambda local: np.asarray([[0.0, 5.0, 0.1],
                                      [1.0, 5.0, 0.12]]))
        health.enable_multihost()
        try:
            out = health.multihost_check(5, 0.1,
                                         registry=MetricsRegistry())
            assert out["desync"] is False
        finally:
            health.enable_multihost(False)

    def test_disabled_is_free(self):
        assert health.multihost_check(1, 0.1) is None


class TestHealthEndpoint:
    def test_api_health_and_503_on_trip(self, blackbox_dir):
        from urllib.error import HTTPError
        from urllib.request import urlopen

        from veles_tpu.services.web_status import WebStatusServer
        server = WebStatusServer(port=0)
        server.start()
        try:
            url = "http://127.0.0.1:%d/api/health" % server.port
            state = json.load(urlopen(url))
            assert state["pid"] == os.getpid()
            assert state["watchdog"]["armed"] is False
            assert "crashdumps" in state and "last_progress_age_s" \
                in state
            wd = health.arm_watchdog(0.2)
            deadline = time.time() + 5.0
            while not wd.tripped and time.time() < deadline:
                time.sleep(0.05)
            with pytest.raises(HTTPError) as err:
                urlopen(url)
            assert err.value.code == 503
            body = json.load(err.value)
            assert body["watchdog"]["tripped"] is True
        finally:
            health.disarm_watchdog()
            server.stop()

    def test_api_health_carries_pod_size_block(self, blackbox_dir):
        """The pod master threads root.common.pod.size/total/degraded/
        lost_hosts into every worker — probing any survivor's
        /api/health answers "how big is the pod, who is missing"."""
        from urllib.request import urlopen

        from veles_tpu.config import root
        from veles_tpu.services.web_status import WebStatusServer
        root.common.pod.update({"size": 1, "total": 2,
                                "degraded": True, "lost_hosts": [1]})
        server = WebStatusServer(port=0)
        server.start()
        try:
            state = json.load(urlopen(
                "http://127.0.0.1:%d/api/health" % server.port))
            assert state["pod"] == {"size": 1, "total": 2,
                                    "degraded": True,
                                    "lost_hosts": [1]}
        finally:
            server.stop()
            for key in ("size", "total", "degraded", "lost_hosts"):
                delattr(root.common.pod, key)
        # without the master's block, no pod key at all
        server = WebStatusServer(port=0)
        server.start()
        try:
            state = json.load(urlopen(
                "http://127.0.0.1:%d/api/health" % server.port))
            assert "pod" not in state
        finally:
            server.stop()


class TestLauncherIntegration:
    def test_initialize_failure_stops_services(self, blackbox_dir):
        from veles_tpu.launcher import Launcher
        from veles_tpu.workflow import Workflow

        class Boom(Workflow):
            def initialize(self, **kwargs):
                raise RuntimeError("boom in initialize")

        launcher = Launcher(workflow=Boom(name="boom"),
                            web_status_port=0)
        with pytest.raises(RuntimeError, match="boom in initialize"):
            launcher.initialize()
        # the satellite fix: web-status must not leak a live server
        assert launcher.web_server is None
        assert not launcher._initialized
        kinds = [e["kind"] for e in flight.recorder.snapshot()]
        assert "launcher.initialize_failed" in kinds

    def test_boot_relies_on_initialize_cleanup(self, blackbox_dir):
        from veles_tpu.launcher import Launcher
        from veles_tpu.workflow import Workflow

        class Boom(Workflow):
            def initialize(self, **kwargs):
                raise RuntimeError("boot boom")

        launcher = Launcher(workflow=Boom(name="boom2"),
                            web_status_port=0)
        with pytest.raises(RuntimeError, match="boot boom"):
            launcher.boot()
        assert launcher.web_server is None

    def test_standalone_does_not_arm_watchdog(self, blackbox_dir):
        from veles_tpu.launcher import Launcher
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="quiet")
        launcher = Launcher(workflow=wf)
        launcher.initialize()
        try:
            assert health.watchdog() is None
        finally:
            launcher.stop()

    def test_watchdog_config_arms_and_stop_disarms(self, blackbox_dir):
        from veles_tpu.config import root
        from veles_tpu.launcher import Launcher
        from veles_tpu.workflow import Workflow
        root.common.blackbox.watchdog_seconds = 30
        try:
            launcher = Launcher(workflow=Workflow(name="wd"))
            launcher.initialize()
            wd = health.watchdog()
            assert wd is not None and wd.window == 30
            launcher.stop()
            assert health.watchdog() is None
        finally:
            root.common.blackbox.watchdog_seconds = None

    def test_spmd_auto_arms_and_explicit_zero_disarms(self,
                                                      blackbox_dir):
        from veles_tpu.config import root
        from veles_tpu.launcher import Launcher
        from veles_tpu.workflow import Workflow
        # unset → spmd arms at the spmd default window
        launcher = Launcher(workflow=Workflow(name="spmd-wd"),
                            mode="spmd")
        launcher.initialize()
        wd = health.watchdog()
        assert wd is not None and wd.window == 300
        launcher.stop()
        # an EXPLICIT 0 (--watchdog 0) disarms even spmd
        root.common.blackbox.watchdog_seconds = 0
        try:
            launcher = Launcher(workflow=Workflow(name="spmd-wd0"),
                                mode="spmd")
            launcher.initialize()
            assert health.watchdog() is None
            launcher.stop()
        finally:
            root.common.blackbox.watchdog_seconds = None


class TestHealthInstall:
    def test_install_uninstall_restores_hooks(self):
        # an earlier Launcher test may have installed already — start
        # from a known-clean state
        health.uninstall()
        prev_except = sys.excepthook
        prev_thread = threading.excepthook
        health.install(mode="test")
        try:
            assert sys.excepthook is not prev_except
            assert threading.excepthook is not prev_thread
            # idempotent: a second install only refreshes the mode
            hook = sys.excepthook
            health.install(mode="test2")
            assert sys.excepthook is hook
            assert health.status()["mode"] == "test2"
        finally:
            health.uninstall()
        assert sys.excepthook is prev_except
        assert threading.excepthook is prev_thread

    def test_note_signal_records_and_dumps(self, blackbox_dir):
        before = flight.recorder.dump_count
        health.note_signal("SIGTERM")
        assert flight.recorder.dump_count == before + 1
        ev = [e for e in flight.recorder.snapshot()
              if e["kind"] == "signal"][-1]
        assert ev["signal"] == "SIGTERM"

    def test_note_progress_and_age(self):
        health.note_progress(step=42)
        age = health.last_progress_age()
        assert age is not None and age < 1.0
        assert health.status()["last_step"] == 42


class TestBlackboxCLI:
    @staticmethod
    def _make_dump(directory, proc, events):
        rec = FlightRecorder(capacity=64)
        for ts, kind, fields in events:
            ev = rec.record(kind, **fields)
            ev["ts"] = ts                   # deterministic timeline
        d = rec.dump(directory=str(directory), reason="test")
        meta_path = os.path.join(d, "meta.json")
        meta = json.load(open(meta_path))
        meta["process_index"] = proc
        json.dump(meta, open(meta_path, "w"))
        return d

    def test_merge_two_process_dumps_one_timeline(self, tmp_path,
                                                  capsys):
        d0 = self._make_dump(tmp_path, 0,
                             [(100.0, "step", {"step": 1}),
                              (103.0, "step", {"step": 2})])
        d1 = self._make_dump(tmp_path, 1,
                             [(101.0, "step", {"step": 1}),
                              (109.0, "hang", {"stalled_s": 6.0})])
        dumps = [blackbox.load_dump(d0), blackbox.load_dump(d1)]
        merged = blackbox.merge_timeline(dumps)
        assert [(e["proc"], e["kind"]) for e in merged] == [
            (0, "step"), (1, "step"), (0, "step"), (1, "hang")]
        assert blackbox.main([d0, d1, "--format", "json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert len(out["dumps"]) == 2 and len(out["events"]) == 4
        assert blackbox.main([d0, d1]) == 0
        text = capsys.readouterr().out
        assert "[p0]" in text and "[p1]" in text and "hang" in text

    def test_parent_dir_expansion_and_filters(self, tmp_path, capsys):
        self._make_dump(tmp_path, 0, [(1.0, "step", {"step": 1}),
                                      (2.0, "snapshot", {})])
        assert blackbox.main([str(tmp_path), "--kind", "snapshot",
                              "--format", "json"]) == 0
        out = json.loads(capsys.readouterr().out)
        assert [e["kind"] for e in out["events"]] == ["snapshot"]

    def test_not_a_dump_is_exit_2(self, tmp_path, capsys):
        assert blackbox.main([str(tmp_path / "nope")]) == 2
        assert blackbox.main([str(tmp_path)]) == 2


class TestStepTelemetryIntegration:
    def test_training_run_populates_flight_ring(self):
        import numpy as np

        from veles_tpu import prng
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        prng.seed_all(7)
        flight.recorder.clear()
        x = np.random.RandomState(0).rand(48, 6).astype(np.float32)
        y = np.random.RandomState(1).randint(0, 3, 48)
        loader = FullBatchLoader(None, data=x, labels=y,
                                 minibatch_size=16,
                                 class_lengths=[0, 16, 32])
        wf = StandardWorkflow(
            layers=[{"type": "all2all_tanh", "output_sample_shape": 6},
                    {"type": "softmax", "output_sample_shape": 3}],
            loader=loader, decision_config={"max_epochs": 2},
            name="bb-smoke")
        wf.initialize()
        wf.run()
        kinds = {e["kind"] for e in flight.recorder.snapshot()}
        assert {"workflow.start", "workflow.stop", "unit.start",
                "unit.stop", "step"} <= kinds
        steps = [e for e in flight.recorder.snapshot()
                 if e["kind"] == "step"]
        assert all("wall_s" in e and "class" in e for e in steps)
        assert health.status()["last_step"] is not None
