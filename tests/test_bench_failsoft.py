"""bench.py's one-JSON-line contract must hold even when no device ever
answers (VERDICT r1 missing #1: the driver needs a parseable line, with
an ``error`` field, not a stack trace or silence)."""

import json
import os
import pytest
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_kohonen_phase_runs_and_sweep_wins():
    """Keep bench.py's phase code from rotting: the kohonen phase runs
    on CPU in seconds and must show the fused sweep beating the
    per-sample scan (VERDICT r1 weak #3's >=10x target holds even on
    CPU)."""
    # the axon sitecustomize force-registers the TPU platform over the
    # JAX_PLATFORMS env var, so the CPU pin must happen through the live
    # config before the phase imports anything
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import runpy; sys.argv = ['bench.py', '--phase', 'kohonen']\n"
        "runpy.run_path(%r, run_name='__main__')\n"
        % (REPO, os.path.join(REPO, "bench.py")))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, r.stderr[-1500:]
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith("PHASE_RESULT "))
    res = json.loads(line[len("PHASE_RESULT "):])
    # >3 not >10: this is a TIMING assertion on shared CI hardware —
    # concurrent suites have been observed to halve the measured ratio
    # (the real CPU number is 12-13x, BENCH_SESSION.md)
    assert res["sweep_speedup"] > 3, res
    assert res["quantization_error"] == pytest.approx(
        res["sweep_quantization_error"], rel=1e-4)


def test_emits_one_json_line_when_budget_exhausted(tmp_path):
    # BENCH_BUDGET=0: the probe hits the global deadline immediately —
    # the orchestrator must still print exactly one JSON object on
    # stdout with the error recorded
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_BUDGET="0")
    # (bench.py's last-known-good cache lives next to bench.py itself,
    # so the line may legitimately carry a last_known_good field)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    out = json.loads(lines[0])
    assert out["metric"] == "gemm_3001x3001_f32_gflops"
    assert out["value"] == 0.0
    assert out["error"] and "probe" in out["error"]


def test_lm_large_oom_ladder(monkeypatch):
    """The lm_large phase walks its MFU ladder — selective remat
    ("dots") at batch 16 first, full remat, then batch 8 — stepping
    down only on OOM and raising anything else."""
    sys.path.insert(0, REPO)
    import bench
    calls = []

    def fake_run_lm(tag, zoo_kwargs, batch, seq, steps,
                    steps_per_dispatch, vocab):
        calls.append((zoo_kwargs["remat"], batch))
        if len(calls) < 3:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return {"tokens_per_sec": 1.0, "ms_per_step": 1.0, "mfu": 0.5,
                "n_params": 124, "peak_bf16_tflops": 197.0}

    monkeypatch.setattr(bench, "_run_lm", fake_run_lm)
    out = bench.phase_lm_large()
    assert calls == [("dots", 16), (True, 16), (True, 8)]
    assert out["batch"] == 8 and out["remat"] == "True"
    # a non-OOM failure at the first rung must propagate, not step down
    calls.clear()

    def fake_boom(*a, **k):
        calls.append(1)
        raise RuntimeError("Mosaic lowering failed")

    monkeypatch.setattr(bench, "_run_lm", fake_boom)
    with pytest.raises(RuntimeError, match="Mosaic"):
        bench.phase_lm_large()
    assert len(calls) == 1


@pytest.mark.slow
def test_serve_phase_runs_on_cpu(monkeypatch):
    """CPU CI gate for the serve phase (f32/bf16/int8 decode timing):
    a tiny config must produce all three timings.  No speedup assertion
    here — CPUs have no int8 matmul unit; the ordering only means
    something on the TPU run."""
    monkeypatch.setenv("BENCH_SERVE_D", "64")
    monkeypatch.setenv("BENCH_SERVE_L", "2")
    sys.path.insert(0, REPO)
    import bench
    out = bench.phase_serve()
    for k in ("ms_per_tok_f32", "ms_per_tok_bf16", "ms_per_tok_int8"):
        assert out[k] > 0, out
