"""bench.py's one-JSON-line contract must hold even when no device ever
answers (VERDICT r1 missing #1: the driver needs a parseable line, with
an ``error`` field, not a stack trace or silence)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_emits_one_json_line_when_budget_exhausted(tmp_path):
    # BENCH_BUDGET=0: the probe hits the global deadline immediately —
    # the orchestrator must still print exactly one JSON object on
    # stdout with the error recorded
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_BUDGET="0")
    # (bench.py's last-known-good cache lives next to bench.py itself,
    # so the line may legitimately carry a last_known_good field)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env,
        cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-1500:]
    lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, r.stdout
    out = json.loads(lines[0])
    assert out["metric"] == "gemm_3001x3001_f32_gflops"
    assert out["value"] == 0.0
    assert out["error"] and "probe" in out["error"]
