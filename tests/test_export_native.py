"""Python→C++ package round-trip tests (ref: libVeles GoogleTest suite
loading real exported packages, SURVEY.md §4 — 'the Python→C++ package
contract is round-trip tested')."""

import shutil
import subprocess

import numpy as np
import pytest
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.services.export import export_workflow, import_workflow

HAS_GXX = shutil.which("g++") is not None


def train_small(layers, epochs=4, img=False, seed=13):
    prng.seed_all(seed)
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    if img:
        x = x.reshape(-1, 8, 8, 1)
    y = d.target.astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(layers=layers, loader=loader,
                          decision_config={"max_epochs": epochs},
                          name="export-test")
    wf.initialize()
    wf.run()
    return wf, x


MLP_LAYERS = [
    {"type": "all2all_tanh", "output_sample_shape": 32,
     "learning_rate": 0.1, "gradient_moment": 0.9},
    {"type": "softmax", "output_sample_shape": 10,
     "learning_rate": 0.1, "gradient_moment": 0.9},
]

CONV_LAYERS = [
    {"type": "conv_strict_relu", "n_kernels": 6, "kx": 3, "ky": 3,
     "padding": (1, 1, 1, 1), "learning_rate": 0.1,
     "gradient_moment": 0.9},
    {"type": "max_pooling", "kx": 2, "ky": 2},
    {"type": "norm", "alpha": 1e-4, "beta": 0.75, "n": 5, "k": 2.0},
    {"type": "softmax", "output_sample_shape": 10,
     "learning_rate": 0.1, "gradient_moment": 0.9},
]


class TestExport:
    def test_package_roundtrip_python(self, tmp_path):
        wf, _ = train_small(MLP_LAYERS, epochs=1)
        path = str(tmp_path / "model.zip")
        export_workflow(wf, path)
        manifest, arrays = import_workflow(path)
        assert manifest["loss"] == "softmax"
        assert len(manifest["units"]) == 2
        w_file = manifest["units"][0]["arrays"]["weights"]
        got = arrays[w_file]
        want = np.asarray(
            wf.trainer.params[wf.trainer.layers[0].name]["weights"])
        np.testing.assert_allclose(got, want, rtol=1e-6)


    def test_unflatten_inverts_flatten(self):
        from veles_tpu.services.export import (_flatten_params,
                                               unflatten_params)
        tree = {"gn1": {"gamma": 1, "beta": 2},
                "conv1": {"weights": 3, "bias": 4},
                "weights": 5}
        flat = _flatten_params(tree)
        assert flat == {"gn1/gamma": 1, "gn1/beta": 2,
                        "conv1/weights": 3, "conv1/bias": 4,
                        "weights": 5}
        assert unflatten_params(flat) == tree


@pytest.mark.skipif(not HAS_GXX, reason="no g++ toolchain")
class TestNativeRuntime:
    def test_mlp_native_matches_jax(self, tmp_path):
        from veles_tpu.services.native import NativeWorkflow
        wf, x = train_small(MLP_LAYERS)
        path = str(tmp_path / "mlp.zip")
        export_workflow(wf, path)
        native = NativeWorkflow(path)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:64]))
        got = native(x[:64])
        # JAX computes in bf16 (policy), native in f32: ~1e-2 agreement
        np.testing.assert_allclose(got, want, atol=1e-2)
        np.testing.assert_array_equal(got.argmax(1), want.argmax(1))
        assert native.unit_names[0].startswith("l00")
        native.close()

    def test_conv_native_matches_jax(self, tmp_path):
        from veles_tpu.services.native import NativeWorkflow
        wf, x = train_small(CONV_LAYERS, img=True, epochs=2)
        path = str(tmp_path / "conv.zip")
        export_workflow(wf, path)
        native = NativeWorkflow(path)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:16]))
        got = native(x[:16].reshape(16, -1))
        np.testing.assert_allclose(got, want, atol=1e-2)
        np.testing.assert_array_equal(got.argmax(1), want.argmax(1))
        native.close()

    def test_resnet_gn_native_matches_jax(self, tmp_path):
        """Composite layers export with flattened array names
        ("gn1/gamma") and the native runtime executes the full
        pre-activation residual block — group norm, strided 3x3 convs,
        1x1 projection skip — bit-close to the jax forward."""
        from veles_tpu.models.zoo import resnet_gn
        from veles_tpu.services.native import NativeWorkflow
        wf, x = train_small(
            resnet_gn(n_classes=10, width=8, blocks_per_stage=1,
                      stages=2, pool=4, lr=0.05),
            img=True, epochs=3)
        path = str(tmp_path / "resnet.zip")
        export_workflow(wf, path)
        manifest, arrays = import_workflow(path)
        rb = next(u for u in manifest["units"]
                  if u["type"] == "conv_residual_block")
        assert "gn1/gamma" in rb["arrays"] and "conv2/weights" in \
            rb["arrays"]
        native = NativeWorkflow(path)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:16]))
        got = native(x[:16].reshape(16, -1))
        np.testing.assert_allclose(got, want, atol=1e-2)
        np.testing.assert_array_equal(got.argmax(1), want.argmax(1))
        native.close()
        # int8 package: the composite sub-arrays ("conv1/weights")
        # quantize with per-channel scales and the native loader folds
        # them back
        path8 = str(tmp_path / "resnet8.zip")
        export_workflow(wf, path8, dtype="int8")
        native8 = NativeWorkflow(path8)
        got8 = native8(x[:16].reshape(16, -1))
        np.testing.assert_allclose(got8, want, atol=3e-2)
        # int8 per-channel quantization perturbs this barely-trained
        # net's outputs by ~2e-4 while several samples sit at top-2
        # margins BELOW that — those near-ties legitimately flip under
        # quantization noise.  Gate argmax agreement on the samples
        # whose f32 margin clears the measured quantization error.
        err = np.abs(got8 - want).max(axis=1)
        top2 = np.sort(want, axis=1)
        margin = top2[:, -1] - top2[:, -2]
        decided = margin > 4 * err
        assert decided.sum() >= 8, (margin, err)
        np.testing.assert_array_equal(got8.argmax(1)[decided],
                                      want.argmax(1)[decided])
        native8.close()

    def test_group_norm_native_matches_jax(self, tmp_path):
        from veles_tpu.services.native import NativeWorkflow
        layers = [
            {"type": "conv_strict_relu", "n_kernels": 6, "kx": 3,
             "ky": 3, "padding": (1, 1, 1, 1), "learning_rate": 0.1,
             "gradient_moment": 0.9},
            {"type": "group_norm", "groups": 3, "learning_rate": 0.1},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.1, "gradient_moment": 0.9},
        ]
        wf, x = train_small(layers, img=True, epochs=2)
        path = str(tmp_path / "gn.zip")
        export_workflow(wf, path)
        native = NativeWorkflow(path)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:16]))
        got = native(x[:16].reshape(16, -1))
        np.testing.assert_allclose(got, want, atol=1e-2)
        native.close()

    def test_arena_is_smaller_than_naive(self, tmp_path):
        """The memory optimizer packs lifetimes: arena < sum of all
        buffers (ref libVeles memory_optimizer 'minimal height')."""
        from veles_tpu.services.native import NativeWorkflow
        wf, _ = train_small(CONV_LAYERS, img=True, epochs=1)
        path = str(tmp_path / "arena.zip")
        export_workflow(wf, path)
        native = NativeWorkflow(path)
        batch = 8
        naive = sum(
            int(np.prod(lay.output_shape)) * batch * 4
            for lay in wf.trainer.layers)
        arena = native.arena_bytes(batch)
        assert arena < naive
        assert arena >= max(int(np.prod(lay.output_shape)) * batch * 4
                            for lay in wf.trainer.layers)
        native.close()

    def test_bad_package_error(self, tmp_path):
        from veles_tpu.services.native import NativeWorkflow
        bad = tmp_path / "bad.zip"
        bad.write_bytes(b"not a zip")
        with pytest.raises(RuntimeError, match="native load failed"):
            NativeWorkflow(str(bad))

    def test_unsupported_type_fails_at_load_with_name(self, tmp_path):
        """A package with a type the C++ engine lacks fails at LOAD
        with the type named — not a generic failure at first infer."""
        from veles_tpu.services.native import NativeWorkflow
        import json
        import zipfile
        wf, _ = train_small(MLP_LAYERS, epochs=1)
        path = str(tmp_path / "mlp.zip")
        export_workflow(wf, path)
        # rewrite the manifest so the loader sees an lstm unit
        with zipfile.ZipFile(path) as zf:
            manifest = json.loads(zf.read("contents.json"))
            blobs = {n: zf.read(n) for n in zf.namelist()
                     if n != "contents.json"}
        manifest["units"][0]["type"] = "lstm"
        bad = str(tmp_path / "lstm.zip")
        with zipfile.ZipFile(bad, "w") as zf:
            zf.writestr("contents.json", json.dumps(manifest))
            for n, b in blobs.items():
                zf.writestr(n, b)
        with pytest.raises(RuntimeError, match="lstm"):
            NativeWorkflow(bad)

    def test_wrong_input_size_raises(self, tmp_path):
        from veles_tpu.services.native import NativeWorkflow
        wf, x = train_small(MLP_LAYERS, epochs=1)
        path = str(tmp_path / "m.zip")
        export_workflow(wf, path)
        native = NativeWorkflow(path)
        with pytest.raises(ValueError, match="input features"):
            native(np.zeros((2, 10), np.float32))
        native.close()


AE_LAYERS = [
    {"type": "conv_relu", "n_kernels": 4, "kx": 3, "ky": 3,
     "learning_rate": 0.05, "gradient_moment": 0.9},
    {"type": "max_pooling", "kx": 2, "ky": 2},
    {"type": "depooling", "kx": 2, "ky": 2},
    {"type": "deconv", "n_kernels": 1, "kx": 3, "ky": 3,
     "learning_rate": 0.05, "gradient_moment": 0.9},
]


@pytest.mark.skipif(not HAS_GXX, reason="no g++ toolchain")
class TestNativeDeconv:
    def test_conv_autoencoder_native_matches_jax(self, tmp_path):
        """The decoder half (depooling + transposed conv) must serve
        natively — the exported conv AE round-trips."""
        from veles_tpu.services.native import NativeWorkflow
        prng.seed_all(19)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32).reshape(-1, 8, 8, 1)
        loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                 class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(layers=AE_LAYERS, loader=loader, loss="mse",
                              decision_config={"max_epochs": 2},
                              name="ae-export")
        wf.initialize()
        wf.run()
        path = str(tmp_path / "ae.zip")
        export_workflow(wf, path)
        native = NativeWorkflow(path)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:16])).reshape(16, -1)
        got = native(x[:16].reshape(16, -1))
        np.testing.assert_allclose(got, want, atol=1e-2)
        native.close()


@pytest.mark.skipif(not HAS_GXX, reason="no g++")
class TestHalfPrecisionPackages:
    def test_f16_package_halves_weights_and_roundtrips(self, tmp_path):
        """dtype='float16' export: smaller package, native runtime widens
        <f2 to f32 on load (ref libVeles fp16->fp32 transform)."""
        from veles_tpu.services.native import NativeWorkflow

        wf, x = train_small(MLP_LAYERS)
        p32 = str(tmp_path / "m32.zip")
        p16 = str(tmp_path / "m16.zip")
        export_workflow(wf, p32)
        export_workflow(wf, p16, dtype="float16")
        import os
        assert os.path.getsize(p16) < 0.65 * os.path.getsize(p32)

        # python-side import preserves the declared dtype
        _, arrays = import_workflow(p16)
        assert all(a.dtype == np.float16 for a in arrays.values())

        native = NativeWorkflow(p16)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:64]))
        got = native(x[:64])
        # f16 weights + bf16 jax policy: compare at ~1e-2
        np.testing.assert_allclose(got, want, atol=2e-2)
        np.testing.assert_array_equal(got.argmax(1), want.argmax(1))
        native.close()

    def test_bad_dtype_rejected(self, tmp_path):
        wf, _ = train_small(MLP_LAYERS, epochs=1)
        with pytest.raises(ValueError, match="float32, float16 or int8"):
            export_workflow(wf, str(tmp_path / "x.zip"), dtype="int4")

    def test_int8_package_native_and_python(self, tmp_path):
        """dtype='int8': ~4x smaller weight payloads (per-output-channel
        symmetric scales); the native runtime widens <i1 via the
        __scales companions and import_workflow dequantizes
        transparently — consumers never see the quantization."""
        import os

        from veles_tpu.services.native import NativeWorkflow

        wf, x = train_small(MLP_LAYERS)
        p32 = str(tmp_path / "m32.zip")
        p8 = str(tmp_path / "m8.zip")
        export_workflow(wf, p32)
        export_workflow(wf, p8, dtype="int8")
        assert os.path.getsize(p8) < 0.55 * os.path.getsize(p32)
        # the weight PAYLOAD itself quarters (manifest/bias overhead
        # dominates this tiny model's total)
        import zipfile
        with zipfile.ZipFile(p32) as z32, zipfile.ZipFile(p8) as z8:
            w32 = next(i.file_size for i in z32.infolist()
                       if i.filename.endswith("weights.npy"))
            w8 = next(i.file_size for i in z8.infolist()
                      if i.filename.endswith("weights.npy")
                      and "scales" not in i.filename)
            assert w8 < 0.3 * w32, (w8, w32)

        manifest, arrays = import_workflow(p8)
        assert all(not p.endswith("__scales")
                   for u in manifest["units"] for p in u["arrays"])
        assert all(a.dtype != np.int8 for a in arrays.values())
        w_file = manifest["units"][0]["arrays"]["weights"]
        want_w = np.asarray(
            wf.trainer.params[wf.trainer.layers[0].name]["weights"])
        err = np.abs(arrays[w_file] - want_w).max()
        assert err <= np.abs(want_w).max() / 127 + 1e-7, err

        native = NativeWorkflow(p8)
        fwd = wf.forward_fn()
        want = np.asarray(fwd(wf.trainer.params, x[:64]))
        got = native(x[:64])
        np.testing.assert_allclose(got, want, atol=3e-2)
        assert (got.argmax(1) == want.argmax(1)).mean() > 0.98
        native.close()

    def test_f16_subnormals_decode_exactly(self, tmp_path):
        """HalfToFloat must match numpy bit-for-bit incl. subnormals
        (values below 6.1e-05 — the renormalization branch)."""
        from veles_tpu.services.native import NativeWorkflow

        wf, x = train_small(MLP_LAYERS, epochs=1)
        # plant exact subnormal + boundary values into the weights
        specials = np.array([3.0518e-05, 5.9605e-08, 6.1035e-05,
                             -3.0518e-05, 65504.0, 0.0], np.float16)
        w = wf.trainer.host_params()
        name = wf.trainer.layers[0].name
        wm = np.array(w[name]["weights"])        # host copy is read-only
        wm[:len(specials), 0] = specials.astype(np.float32)
        w[name]["weights"] = wm
        wf.trainer.load_params(w)
        p16 = str(tmp_path / "sub.zip")
        export_workflow(wf, p16, dtype="float16")
        native = NativeWorkflow(p16)
        # native returns probabilities; instead verify the loaded array
        # round-trips by comparing forward outputs on a probe input that
        # isolates the planted column
        probe = np.zeros((1, 64), np.float32)
        probe[0, :len(specials)] = 1.0
        _, arrays = import_workflow(p16)
        stored = [a for f, a in arrays.items() if "weights" in f][0]
        np.testing.assert_array_equal(
            stored[:len(specials), 0], specials)
        native.close()


class TestStableHLOExport:
    """export_stablehlo: a compiled-forward artifact (jax.export) that
    reproduces the live forward_fn bit-for-bit, with a symbolic batch
    dim, loadable without the model-building code."""

    def test_roundtrip_matches_forward(self, tmp_path):
        from veles_tpu.services.export import (export_stablehlo,
                                               load_stablehlo)
        wf, x = train_small(MLP_LAYERS, epochs=2)
        path = str(tmp_path / "m.stablehlo.zip")
        meta = export_stablehlo(wf, path, platforms=("cpu",))
        assert meta["platforms"] == ["cpu"] and meta["input_shape"] == [64]
        fn, meta2 = load_stablehlo(path)
        assert meta2 == meta
        live = np.asarray(wf.forward_fn()(wf.trainer.params, x[:5]))
        np.testing.assert_allclose(np.asarray(fn(x[:5])), live,
                                   rtol=1e-6, atol=1e-6)
        # symbolic batch: the same artifact serves other batch sizes
        assert np.asarray(fn(x[:3])).shape == (3, 10)
        assert np.asarray(fn(x[:11])).shape == (11, 10)

    def test_conv_stack_exports(self, tmp_path):
        from veles_tpu.services.export import (export_stablehlo,
                                               load_stablehlo)
        wf, x = train_small(CONV_LAYERS, epochs=1, img=True)
        path = str(tmp_path / "c.zip")
        export_stablehlo(wf, path, platforms=("cpu",))
        fn, _ = load_stablehlo(path)
        live = np.asarray(wf.forward_fn()(wf.trainer.params, x[:4]))
        np.testing.assert_allclose(np.asarray(fn(x[:4])), live,
                                   rtol=1e-6, atol=1e-6)
