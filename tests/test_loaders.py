"""Loader breadth tests (ref SURVEY §4: loader tests live in
veles/tests/test_loader.py with HDF5 fixtures; streaming covered by
test_zmq_loader.py)."""

import gzip
import os
import pickle
import threading

import numpy as np
import pytest

from veles_tpu.loader.base import TEST, TRAIN, VALID
from veles_tpu.loader.formats import (HDF5Loader, MinibatchesSaver,
                                      PickleLoader, read_minibatches)
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.image import (FullBatchImageLoader, auto_label,
                                    decode_image, scan_files)
from veles_tpu.loader.streaming import InteractiveLoader, ZeroMQLoader


def make_png(path, color, size=(10, 8)):
    from PIL import Image
    Image.new("RGB", size, color).save(path)


class TestImageLoader:
    def test_scan_and_auto_label(self, tmp_path):
        for cls, color in (("cats", (255, 0, 0)), ("dogs", (0, 255, 0))):
            d = tmp_path / cls
            d.mkdir()
            for i in range(3):
                make_png(str(d / ("%d.png" % i)), color)
        files = scan_files(str(tmp_path))
        assert len(files) == 6
        labels, names = auto_label(files)
        assert names == ["cats", "dogs"]
        assert (np.bincount(labels) == [3, 3]).all()

    def test_decode_resize_gray(self, tmp_path):
        p = str(tmp_path / "img.png")
        make_png(p, (128, 128, 128), size=(20, 14))
        arr = decode_image(p, size=(7, 5), grayscale=True)
        assert arr.shape == (7, 5, 1)
        assert 0.4 < arr.mean() < 0.6

    def test_fullbatch_image_loader_trains_shape(self, tmp_path):
        for cls, color in (("a", (250, 10, 10)), ("b", (10, 250, 10))):
            d = tmp_path / "train" / cls
            d.mkdir(parents=True)
            for i in range(8):
                make_png(str(d / ("%d.png" % i)), color)
        loader = FullBatchImageLoader(
            None, train_paths=str(tmp_path / "train"), size=(8, 8),
            minibatch_size=4, class_lengths=None)
        loader.class_lengths = [0, 0, 0]
        loader.load_data()
        assert loader.class_lengths == [0, 0, 16]
        assert loader.original_data.shape == (16, 8, 8, 3)
        assert loader.label_names == ["a", "b"]


class TestFormatLoaders:
    def test_hdf5_loader(self, tmp_path):
        import h5py
        for name, n in (("train", 20), ("validation", 8)):
            with h5py.File(str(tmp_path / (name + ".h5")), "w") as f:
                f["data"] = np.random.rand(n, 6).astype(np.float32)
                f["labels"] = np.arange(n, dtype=np.int32) % 3
        loader = HDF5Loader(
            None, files={"train": str(tmp_path / "train.h5"),
                         "validation": str(tmp_path / "validation.h5")},
            minibatch_size=10)
        loader.initialize()
        assert loader.class_lengths == [0, 8, 20]
        assert loader.data.shape == (28, 6)

    def test_pickle_loader_gz(self, tmp_path):
        path = str(tmp_path / "train.pkl.gz")
        with gzip.open(path, "wb") as f:
            pickle.dump({"data": np.ones((12, 4), np.float32),
                         "labels": np.zeros(12, np.int64)}, f)
        loader = PickleLoader(None, files={"train": path},
                              minibatch_size=6)
        loader.initialize()
        assert loader.class_lengths == [0, 0, 12]

    def test_minibatches_saver_roundtrip(self, tmp_path):
        x = np.arange(40, dtype=np.float32).reshape(20, 2)
        y = (np.arange(20) % 4).astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=8,
                                 class_lengths=[0, 4, 16], shuffle=False)
        loader.initialize()
        path = str(tmp_path / "stream.sav.gz")
        saver = MinibatchesSaver(None, path=path)
        saver.loader = loader
        saver.initialize()
        served = 0
        while True:
            loader.run()
            saver.run()
            served += 1
            if bool(loader.epoch_ended):
                break
        saver.stop()
        header, records = read_minibatches(path)
        assert header["minibatch_size"] == 8
        assert len(records) == served
        assert records[0]["cls"] == VALID
        assert records[-1]["cls"] == TRAIN
        np.testing.assert_array_equal(
            records[0]["data"][0], x[0])


class TestStreaming:
    def test_interactive_loader_feeds(self):
        loader = InteractiveLoader(None, sample_shape=(3,),
                                   minibatch_size=4)
        loader.initialize()
        for i in range(2):
            loader.feed(np.full(3, float(i)))
        loader.run()
        assert loader.minibatch_valid.sum() == 2
        np.testing.assert_array_equal(loader.minibatch_data[1],
                                      np.ones(3))

    def test_zeromq_loader_receives(self):
        import zmq
        loader = ZeroMQLoader(None, sample_shape=(2,), minibatch_size=2)
        loader.initialize()
        ctx = zmq.Context.instance()
        push = ctx.socket(zmq.PUSH)
        push.connect(loader.endpoint)
        push.send_pyobj(np.array([1.0, 2.0], np.float32))
        push.send_pyobj(np.array([3.0, 4.0], np.float32))
        loader.run()
        assert loader.minibatch_valid.sum() == 2
        np.testing.assert_array_equal(loader.minibatch_data,
                                      [[1, 2], [3, 4]])
        push.close(0)


class TestDataCarryingIntegration:
    def test_minibatches_loader_drives_standard_workflow(self, tmp_path):
        """Replay stream drives real training (the integration path the
        reference's MinibatchesLoader supported)."""
        from veles_tpu import prng
        from veles_tpu.loader.formats import MinibatchesLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        prng.seed_all(41)
        g = np.random.RandomState(0)
        x = g.rand(200, 8).astype(np.float32)
        y = (x.sum(1) > 4).astype(np.int32)
        src = FullBatchLoader(None, data=x, labels=y, minibatch_size=20,
                              class_lengths=[0, 40, 160], shuffle=False)
        src.initialize()
        path = str(tmp_path / "stream.sav.gz")
        saver = MinibatchesSaver(None, path=path)
        saver.loader = src
        saver.initialize()
        while True:
            src.run()
            saver.run()
            if bool(src.epoch_ended):
                break
        saver.stop()

        replay = MinibatchesLoader(None, path=path, minibatch_size=20)
        wf = StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 2,
                     "learning_rate": 0.3, "gradient_moment": 0.9}],
            loader=replay, decision_config={"max_epochs": 12},
            name="replay-train")
        wf.initialize()
        wf.run()
        assert wf.decision.best_metric < 0.35

    def test_interactive_loader_eval_path(self):
        from veles_tpu import prng
        from veles_tpu.models.nn_units import StagedTrainer
        from veles_tpu.models.layers import make_layer
        prng.seed_all(2)
        loader = InteractiveLoader(None, sample_shape=(4,),
                                   minibatch_size=2)
        loader.initialize()
        trainer = StagedTrainer(
            None, [make_layer({"type": "softmax",
                               "output_sample_shape": 3})])
        trainer.loader = loader
        trainer.initialize()
        loader.feed(np.ones(4))
        loader.feed(np.zeros(4))
        loader.run()
        trainer.run()   # TEST class -> eval step, no crash
        stats = trainer.read_class_stats(TEST)
        assert stats["count"] == 2


class TestImageBreadth:
    @staticmethod
    def _make_images(tmp_path, per_class=3):
        from PIL import Image
        rng = np.random.RandomState(0)
        files = {}
        for cls_name in ("cats", "dogs"):
            d = tmp_path / "train" / cls_name
            d.mkdir(parents=True)
            for i in range(per_class):
                arr = (rng.rand(10, 12, 3) * 255).astype(np.uint8)
                Image.fromarray(arr).save(d / ("%d.png" % i))
            files[cls_name] = d
        return files

    def test_decode_transforms(self, tmp_path):
        from PIL import Image
        from veles_tpu.loader.image import decode_image
        arr = np.zeros((8, 8, 3), np.uint8)
        arr[:, :4] = 255
        p = str(tmp_path / "half.png")
        Image.fromarray(arr).save(p)
        plain = decode_image(p)
        assert plain.shape == (8, 8, 3)
        mirrored = decode_image(p, mirror=True)
        np.testing.assert_allclose(mirrored[:, ::-1], plain)
        rot = decode_image(p, rotation=90)
        assert rot.shape == (8, 8, 3)
        hsv = decode_image(p, color_space="HSV")
        assert hsv.shape == (8, 8, 3)
        gray = decode_image(p, color_space="L")
        assert gray.shape == (8, 8, 1)

    def test_augmentation_multiplies_train_class(self, tmp_path):
        from veles_tpu.loader.image import FullBatchImageLoader
        self._make_images(tmp_path)
        loader = FullBatchImageLoader(
            None, train_paths=str(tmp_path / "train"), size=(8, 8),
            minibatch_size=4,
            augment={"mirror": True, "rotations": [15]})
        loader.initialize()
        # 6 originals x (1 + 1 rotation) x2 mirror = 24
        assert loader.class_lengths == [0, 0, 24]
        assert loader.original_labels.shape == (24,)
        assert set(loader.label_names) == {"cats", "dogs"}

    def test_file_list_loader(self, tmp_path):
        from veles_tpu.loader.image import FileListImageLoader
        self._make_images(tmp_path)
        lines = []
        for label, cls_name in enumerate(("cats", "dogs")):
            for i in range(3):
                lines.append("train/%s/%d.png %d" % (cls_name, i, label))
        lst = tmp_path / "train.lst"
        lst.write_text("\n".join(lines) + "\n# comment\n")
        loader = FileListImageLoader(
            None, train_list=str(lst), size=(8, 8), minibatch_size=2)
        loader.initialize()
        assert loader.class_lengths == [0, 0, 6]
        np.testing.assert_array_equal(loader.original_labels,
                                      [0, 0, 0, 1, 1, 1])

    def test_image_mse_loader_pairs_targets(self, tmp_path):
        from veles_tpu.loader.image import ImageMSELoader
        self._make_images(tmp_path)
        # identity pairing (targets = inputs): augmented variants must get
        # the SAME transform on both sides, so data == targets exactly
        loader = ImageMSELoader(
            None, train_paths=str(tmp_path / "train"),
            target_paths=str(tmp_path / "train"), size=(8, 8),
            minibatch_size=2, augment={"mirror": True})
        loader.initialize()
        assert loader.original_targets.shape == loader.original_data.shape
        np.testing.assert_allclose(loader.original_targets,
                                   loader.original_data)

    def test_image_mse_loader_rejects_unpairable(self, tmp_path):
        from veles_tpu.loader.image import ImageMSELoader
        self._make_images(tmp_path)
        with pytest.raises(ValueError, match="target_paths"):
            ImageMSELoader(None, train_paths=str(tmp_path / "train"))
        loader = ImageMSELoader(
            None, train_paths=str(tmp_path / "train"),
            target_paths=str(tmp_path / "train" / "cats"), size=(8, 8),
            minibatch_size=2)
        with pytest.raises(ValueError, match="1:1"):
            loader.initialize()

    def test_file_list_space_in_filename(self, tmp_path):
        from PIL import Image
        from veles_tpu.loader.image import FileListImageLoader
        arr = np.zeros((4, 4, 3), np.uint8)
        (tmp_path / "imgs").mkdir()
        Image.fromarray(arr).save(tmp_path / "imgs" / "my image.png")
        lst = tmp_path / "l.lst"
        lst.write_text("imgs/my image.png 3\nimgs/my image.png\n")
        loader = FileListImageLoader(None, train_list=str(lst),
                                     size=(4, 4), minibatch_size=1)
        loader.initialize()
        # raw labels [3, 0] dense-map to class indices via the base
        # analysis (ref label mapping, veles/loader/base.py:755-819)
        np.testing.assert_array_equal(loader.original_labels, [1, 0])
        assert loader.labels_mapping == {0: 0, 3: 1}


class TestFullBatchHostFallback:
    """VERDICT r1 #2c: OOM fallback — the fullbatch loader degrades to a
    host-streaming (data-carrying) loader instead of dying (ref
    veles/loader/fullbatch.py:164-242 numpy fallback)."""

    def _loader(self, **kw):
        x = np.arange(40, dtype=np.float32).reshape(10, 4)
        y = np.arange(10, dtype=np.int32)
        return FullBatchLoader(None, data=x, labels=y, minibatch_size=4,
                               class_lengths=[0, 2, 8], **kw)

    def test_host_mode_serves_gathered_minibatches(self):
        loader = self._loader(on_device="host", shuffle=False)
        loader.initialize()
        assert loader.carries_data
        assert loader.sample_shape == (4,)
        loader.run()   # valid class first (offsets walk test->valid->train)
        np.testing.assert_array_equal(loader.minibatch_labels[:2], [0, 1])
        np.testing.assert_array_equal(
            loader.minibatch_data[0], np.arange(4, dtype=np.float32))

    def test_oom_triggers_fallback(self, monkeypatch):
        import veles_tpu.loader.fullbatch as fb

        class FakeJnp:
            @staticmethod
            def asarray(x):
                raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                                   "allocating 742 GB")

        monkeypatch.setattr(fb, "jnp", FakeJnp)
        loader = self._loader(on_device=True)
        loader.initialize()
        assert loader.carries_data
        assert loader.data is None

    def test_non_oom_error_propagates(self, monkeypatch):
        import veles_tpu.loader.fullbatch as fb

        class FakeJnp:
            @staticmethod
            def asarray(x):
                raise RuntimeError("INVALID_ARGUMENT: bad dtype")

        monkeypatch.setattr(fb, "jnp", FakeJnp)
        loader = self._loader(on_device=True)
        with pytest.raises(RuntimeError, match="INVALID_ARGUMENT"):
            loader.initialize()

    def test_host_mode_trains_like_device_mode(self):
        from sklearn.datasets import load_digits
        from veles_tpu import prng
        from veles_tpu.models.standard_workflow import StandardWorkflow
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)

        def run(on_device):
            prng.seed_all(99)
            loader = FullBatchLoader(None, data=x, labels=y,
                                     minibatch_size=100,
                                     class_lengths=[0, 297, 1500],
                                     on_device=on_device)
            wf = StandardWorkflow(
                layers=[{"type": "all2all_tanh", "output_sample_shape": 32,
                         "learning_rate": 0.1},
                        {"type": "softmax", "output_sample_shape": 10,
                         "learning_rate": 0.1}],
                loader=loader, decision_config={"max_epochs": 3},
                name="host-fb")
            wf.initialize()
            wf.run()
            return wf.decision.epoch_metrics[1]

    # same shuffles (same prng stream), so metrics must agree exactly
        dev = run(True)
        host = run("host")
        assert dev["n_errors"] == host["n_errors"]
        np.testing.assert_allclose(dev["loss"], host["loss"], rtol=1e-4)


    def test_on_device_false_keeps_index_mode(self):
        """on_device=False is the numpy *index* mode Kohonen/RBM gather
        from — it must NOT become a data-carrying loader."""
        loader = self._loader(on_device=False)
        loader.initialize()
        assert not loader.carries_data
        assert isinstance(loader.data, np.ndarray)
        assert loader.data.shape == (10, 4)

    def test_defer_mode_keeps_numpy_for_trainer_sharding(self):
        loader = self._loader(on_device="defer")
        loader.initialize()
        assert not loader.carries_data
        assert isinstance(loader.data, np.ndarray)


class TestGeneratorLoader:
    def test_epoch_flags_and_stream(self):
        from veles_tpu.loader.streaming import GeneratorLoader
        calls = []

        def gen(step, size):
            calls.append(step)
            return (np.full((size, 3), step, np.float32),
                    np.full((size,), step, np.int64))

        loader = GeneratorLoader(None, generator=gen, sample_shape=(3,),
                                 steps_per_epoch=3, minibatch_size=5)
        loader.initialize()
        for i in range(3):
            loader.run()
            assert loader.minibatch_class == TRAIN
            np.testing.assert_array_equal(loader.minibatch_data,
                                          np.full((5, 3), i))
            assert loader.minibatch_labels.dtype == np.int32
        assert bool(loader.epoch_ended)
        assert loader.epoch_number == 1
        assert calls == [0, 1, 2]

    def test_bad_shape_raises(self):
        from veles_tpu.loader.streaming import GeneratorLoader
        loader = GeneratorLoader(None, generator=lambda s, n:
                                 np.zeros((n, 7), np.float32),
                                 sample_shape=(3,), steps_per_epoch=2,
                                 minibatch_size=4)
        loader.initialize()
        with pytest.raises(ValueError, match="expected"):
            loader.run()

    def test_prefetch_same_stream_and_resume(self):
        """prefetch=2 must deliver the identical batch sequence, and the
        snapshot state must record the CONSUMED position (pending
        prefetched batches regenerate after restore)."""
        from veles_tpu.loader.streaming import GeneratorLoader

        def gen(step, size):
            return (np.full((size, 3), step, np.float32),
                    np.full((size,), step, np.int64))

        def make(prefetch):
            loader = GeneratorLoader(None, generator=gen, sample_shape=(3,),
                                     steps_per_epoch=4, minibatch_size=5,
                                     prefetch=prefetch)
            loader.initialize()
            return loader

        sync, pre = make(0), make(2)
        for i in range(4):
            sync.run()
            pre.run()
            np.testing.assert_array_equal(pre.minibatch_data,
                                          sync.minibatch_data)
            np.testing.assert_array_equal(pre.minibatch_labels,
                                          sync.minibatch_labels)
        # 4 consumed; the worker has submitted ahead — state must say 4
        assert pre.state["generator_step"] == 4
        fresh = make(2)
        fresh.state = pre.state
        fresh.run()
        assert fresh.minibatch_data[0, 0] == 4.0
        # stop() discards pending batches AND rolls the counter back —
        # a post-stop state read still reports the consumed position
        pre.stop()
        assert pre.state["generator_step"] == 4


class TestDatasetAnalysis:
    """VERDICT r1 #7: label mapping + per-class distribution analysis in
    the Loader base (ref veles/loader/base.py:755-819)."""

    def test_string_labels_map_to_dense_indices(self):
        x = np.zeros((6, 3), np.float32)
        y = np.array(["dog", "cat", "cat", "bird", "dog", "cat"])
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=3,
                                 class_lengths=[0, 0, 6])
        loader.initialize()
        assert loader.labels_mapping == {"bird": 0, "cat": 1, "dog": 2}
        np.testing.assert_array_equal(np.asarray(loader.labels),
                                      [2, 1, 1, 0, 2, 1])
        assert loader.labels.dtype == np.int32 or \
            str(loader.labels.dtype) == "int32"

    def test_sparse_int_labels_remapped(self):
        x = np.zeros((4, 2), np.float32)
        y = np.array([10, 500, 10, 500])
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=2,
                                 class_lengths=[0, 0, 4])
        loader.initialize()
        assert loader.labels_mapping == {10: 0, 500: 1}
        np.testing.assert_array_equal(np.asarray(loader.labels),
                                      [0, 1, 0, 1])

    def test_distribution_and_metrics(self):
        x = np.zeros((10, 2), np.float32)
        y = np.array([0, 1, 0, 1, 1, 0, 1, 1, 1, 1])
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=2,
                                 class_lengths=[0, 4, 6])
        loader.initialize()
        d = loader.label_distribution
        assert d["validation"] == {"0": 2, "1": 2}
        assert d["train"] == {"0": 1, "1": 5}
        m = loader.get_metric_values()
        assert m["labels"]["n_classes"] == 2

    def test_untrained_class_warns(self, caplog):
        import logging
        x = np.zeros((6, 2), np.float32)
        y = np.array([0, 1, 2, 0, 1, 0])   # class 2 only in validation
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=3,
                                 class_lengths=[0, 3, 3])
        with caplog.at_level(logging.WARNING):
            loader.initialize()
        assert any("never seen in training" in r.message
                   for r in caplog.records)

    def test_skew_warns(self, caplog):
        import logging
        x = np.zeros((120, 2), np.float32)
        y = np.array([0] * 110 + [1] * 10)
        loader = FullBatchLoader(None, data=x, labels=y,
                                 minibatch_size=10,
                                 class_lengths=[0, 0, 120])
        with caplog.at_level(logging.WARNING):
            loader.initialize()
        assert any("skewed class distribution" in r.message
                   for r in caplog.records)

    def test_base_normalization_fits_on_train_only(self):
        rng = np.random.default_rng(0)
        x = np.concatenate([np.full((4, 3), 100.0, np.float32),
                            rng.normal(5.0, 2.0, (8, 3)).astype(np.float32)])
        loader = FullBatchLoader(None, data=x, minibatch_size=4,
                                 class_lengths=[0, 4, 8],
                                 normalization="mean_disp")
        loader.initialize()
        got = np.asarray(loader.data)
        # train span normalized around 0; the outlier valid span is not
        # folded into the statistics
        assert abs(got[4:].mean()) < 0.5
        assert got[:4].mean() > 5.0


class TestDatasetReaders:
    """Offline coverage of the canonical-format readers behind the
    accuracy gates (tests/test_accuracy_gates.py)."""

    def _write_idx(self, path, arr):
        import struct
        with open(path, "wb") as f:
            dtype_code = 0x08   # ubyte
            f.write(struct.pack(">HBB", 0, dtype_code, arr.ndim))
            f.write(struct.pack(">" + "I" * arr.ndim, *arr.shape))
            f.write(arr.astype(np.uint8).tobytes())

    def test_mnist_reader(self, tmp_path):
        from veles_tpu.loader.datasets import load_mnist, mnist_available
        d = tmp_path / "mnist"
        d.mkdir()
        rng = np.random.RandomState(0)
        self._write_idx(str(d / "train-images-idx3-ubyte"),
                        rng.randint(0, 256, (20, 28, 28)))
        self._write_idx(str(d / "train-labels-idx1-ubyte"),
                        rng.randint(0, 10, (20,)))
        # gz variant for the test split
        import gzip, struct
        arr = rng.randint(0, 256, (5, 28, 28)).astype(np.uint8)
        with gzip.open(str(d / "t10k-images-idx3-ubyte.gz"), "wb") as f:
            f.write(struct.pack(">HBB", 0, 8, 3)
                    + struct.pack(">III", *arr.shape) + arr.tobytes())
        lab = rng.randint(0, 10, (5,)).astype(np.uint8)
        with gzip.open(str(d / "t10k-labels-idx1-ubyte.gz"), "wb") as f:
            f.write(struct.pack(">HBB", 0, 8, 1)
                    + struct.pack(">I", 5) + lab.tobytes())
        assert mnist_available(str(tmp_path))
        tx, ty, ex, ey = load_mnist(str(tmp_path))
        assert tx.shape == (20, 784) and tx.dtype == np.float32
        assert tx.max() <= 1.0
        assert ex.shape == (5, 784)
        np.testing.assert_array_equal(ey, lab)

    def test_cifar_reader(self, tmp_path):
        import pickle as pkl
        from veles_tpu.loader.datasets import (cifar10_available,
                                               load_cifar10)
        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        rng = np.random.RandomState(1)
        for name, n in [("data_batch_%d" % i, 4) for i in range(1, 6)] + \
                [("test_batch", 2)]:
            with open(str(d / name), "wb") as f:
                pkl.dump({b"data": rng.randint(0, 256, (n, 3072),
                                               dtype=np.uint8),
                          b"labels": list(rng.randint(0, 10, n))}, f)
        assert cifar10_available(str(tmp_path))
        tx, ty, ex, ey = load_cifar10(str(tmp_path))
        assert tx.shape == (20, 32, 32, 3)
        assert ex.shape == (2, 32, 32, 3)
        assert tx.dtype == np.float32 and tx.max() <= 1.0

    def test_stl10_reader(self, tmp_path):
        from veles_tpu.loader.datasets import (load_stl10,
                                               stl10_available)
        d = tmp_path / "stl10_binary"
        d.mkdir()
        rng = np.random.RandomState(2)
        for name, n in (("train_X.bin", 3), ("test_X.bin", 2)):
            rng.randint(0, 256, (n, 3, 96, 96),
                        dtype=np.uint8).tofile(str(d / name))
        for name, n in (("train_y.bin", 3), ("test_y.bin", 2)):
            (rng.randint(0, 10, n, dtype=np.uint8) + 1).tofile(
                str(d / name))
        assert stl10_available(str(tmp_path))
        tx, ty, ex, ey = load_stl10(str(tmp_path))
        assert tx.shape == (3, 96, 96, 3) and ex.shape == (2, 96, 96, 3)
        assert ty.min() >= 0 and ty.max() <= 9   # 1..10 → 0..9
        assert tx.dtype == np.float32 and tx.max() <= 1.0
