"""Multi-host SPMD: 2 local processes handshake through
``jax.distributed.initialize`` (CPU backend), build one cross-process
8-device mesh and train data-parallel — the TPU-era equivalent of the
reference's in-process Server+Client test
(veles/tests/test_network.py:52-120).  VERDICT r1 #4."""

import json
import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_job(n_processes, extra=(), _retry=True):
    from veles_tpu.services.supervisor import is_startup_flake

    coord = "127.0.0.1:%d" % _free_port()
    # the workers pin their own platform/devices; don't leak the parent's
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    procs = [subprocess.Popen(
        [sys.executable, WORKER, coord, str(n_processes), str(i)]
        + list(extra),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(n_processes)]
    outcomes = []
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError("worker %d timed out" % i)
        outcomes.append((p.returncode, out, err))
    if _retry and any(is_startup_flake(*o) for o in outcomes):
        # the documented sandbox XLA-startup abort (ROADMAP "Known
        # environment flake"): one worker died inside backend init
        # before any output — respawn the WHOLE job once (the peers
        # exit nonzero too, stuck waiting on the dead coordinator)
        return _spawn_job(n_processes, extra, _retry=False)
    results = []
    for i, (rc, out, err) in enumerate(outcomes):
        assert rc == 0, "worker %d failed:\n%s" % (i, err[-3000:])
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("METRICS "))
        results.append(json.loads(line[len("METRICS "):]))
    return results


def test_two_process_spmd_trains_with_matching_metrics():
    r0, r1 = _spawn_job(2)
    # the job really spanned processes
    assert r0["process_count"] == 2 and r1["process_count"] == 2
    assert r0["n_global_devices"] == 8
    # process 0 owns master duties, process 1 does not
    assert r0["is_master"] and not r1["is_master"]
    # SPMD: every process computes the same global metrics, bit for bit
    assert r0["loss"] == r1["loss"]
    assert r0["n_errors"] == r1["n_errors"]
    assert r0["best_metric"] == r1["best_metric"]

    # and the 2-process job must match a single-process run of the same
    # seeded workflow on the same 8-device mesh
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.parallel import MeshConfig, make_mesh

    prng.seed_all(1234)
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)[:800]
    y = d.target.astype(np.int32)[:800]
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=80,
                             class_lengths=[0, 160, 640])
    wf = StandardWorkflow(
        layers=[{"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": 0.1},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1}],
        loader=loader, decision_config={"max_epochs": 2},
        mesh_config=MeshConfig(make_mesh({"data": 8})),
        name="singlehost-digits")
    wf.initialize()
    wf.run()
    m = wf.decision.epoch_metrics[1]
    assert m["n_errors"] == r0["n_errors"]
    np.testing.assert_allclose(m["loss"], r0["loss"], rtol=1e-5)


def test_multihost_tensor_parallel_checkpoint(tmp_path):
    """Params sharded ACROSS processes (model axis spanning both hosts)
    checkpoint correctly: every process joins the process_allgather
    inside collect(), only process 0 writes, and the snapshot holds the
    full unsharded tensors."""
    from veles_tpu.services.snapshotter import SnapshotterBase

    snap_dir = str(tmp_path / "snaps")
    r0, r1 = _spawn_job(2, extra=(snap_dir,))
    # weights really were sharded across processes
    assert r0["weights_addressable"] is False
    assert r0["loss"] == r1["loss"]
    # only the master wrote
    assert r0["snapshot"] and r0["snapshot"].startswith(snap_dir)
    assert r1["snapshot"] is None
    snap = SnapshotterBase.import_(
        os.path.join(snap_dir, "multihost-digits_current"))
    assert snap["epoch"] == 2
    w = snap["params"]["l00_all2all_tanh"]["weights"]
    assert w.shape == (64, 32)     # full tensor, not a local shard


def test_multihost_orbax_sharded_checkpoint(tmp_path):
    """The orbax backend under a REAL 2-process job: the save is the
    collective (all_processes_export) — both processes enter it, each
    writes its own cross-process shards, and the resulting directory
    imports to the full unsharded tensors."""
    from veles_tpu.services.snapshotter import SnapshotterBase

    snap_dir = str(tmp_path / "snaps")
    r0, r1 = _spawn_job(2, extra=(snap_dir, "--orbax"))
    assert r0["weights_addressable"] is False   # sharded across procs
    assert r0["loss"] == r1["loss"]
    # BOTH processes report the checkpoint (both entered the save)
    assert r0["snapshot"] and r0["snapshot"].endswith(".orbax")
    assert r1["snapshot"] and r1["snapshot"].endswith(".orbax")
    snap = SnapshotterBase.import_(
        os.path.join(snap_dir, "multihost-digits_current"))
    assert snap["epoch"] == 2
    import numpy as np
    w = np.asarray(snap["params"]["l00_all2all_tanh"]["weights"])
    assert w.shape == (64, 32) and np.isfinite(w).all()


def test_multihost_fsdp_shards_params_and_checkpoints(tmp_path):
    """ZeRO-3 over a cross-process data axis: each process holds only its
    1/8 parameter shards (not fully addressable), metrics still match,
    and the snapshotter gathers the shards into one checkpoint (the
    process_allgather path ZeRO sharding makes interesting)."""
    r0, r1 = _spawn_job(2, extra=("--fsdp", str(tmp_path)))
    assert r0["n_global_devices"] == 8
    assert r0["loss"] == r1["loss"]
    assert r0["n_errors"] == r1["n_errors"]
    for r in (r0, r1):
        assert r["weights_addressable"] is False, r
        assert "data" in r["weights_spec"], r["weights_spec"]
    # only process 0 wrote; the checkpoint holds the FULL gathered params
    assert r0["snapshot"] and os.path.exists(r0["snapshot"])
    from veles_tpu.services.snapshotter import SnapshotterBase
    snap = SnapshotterBase.import_(r0["snapshot"])
    w = np.asarray(snap["params"]["l00_all2all_tanh"]["weights"])
    assert w.shape == (64, 32)


def test_multihost_sequence_parallel_ring_attention():
    """Ring attention spanning BOTH processes: the 'seq' axis covers all
    8 devices across the 2-process job, so every ppermute step sends
    across the process boundary at the two ring seams (DCN on a real
    pod).  Metrics must
    bit-match across processes AND equal a single-process run of the
    same seeded workflow on a local {seq: 8} mesh."""
    r0, r1 = _spawn_job(2, extra=("--seq",))
    assert r0["process_count"] == 2 and r0["n_global_devices"] == 8
    assert r0["loss"] == r1["loss"]
    assert r0["n_errors"] == r1["n_errors"]

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import transformer_classifier
    from veles_tpu.parallel import MeshConfig, make_mesh

    prng.seed_all(1234)
    xs = np.random.RandomState(0).rand(320, 16, 8).astype(np.float32)
    ys = np.random.RandomState(1).randint(0, 4, 320).astype(np.int32)
    loader = FullBatchLoader(None, data=xs, labels=ys, minibatch_size=80,
                             class_lengths=[0, 80, 240])
    wf = StandardWorkflow(
        layers=transformer_classifier(n_classes=4, d_model=8, n_heads=4,
                                      n_layers=1, dropout=0.0,
                                      impl="ring", lr=0.01),
        loader=loader, decision_config={"max_epochs": 2},
        mesh_config=MeshConfig(make_mesh({"data": 1, "seq": 8})),
        name="singlehost-seq")
    wf.initialize()
    wf.run()
    m = wf.decision.epoch_metrics[1]
    assert m["n_errors"] == r0["n_errors"]
    np.testing.assert_allclose(m["loss"], r0["loss"], rtol=1e-5)


def test_multihost_preemption_agreement(tmp_path):
    """Staggered preemption: ONLY process 0 raises the flag mid-run; the
    snapshotter's unconditional per-cycle agreement allgather must stop
    BOTH processes at the same cycle, with process 0 writing the
    checkpoint — the SIGTERM-races-unit-boundaries scenario that would
    deadlock the pod if the agreement were gated on per-process state."""
    results = _spawn_job(2, extra=["--preempt", str(tmp_path)])
    assert all(r["preempted"] for r in results), results
    # far from the 100000-epoch horizon: they stopped because of the
    # flag, not completion — and at the SAME cycle (the agreement
    # property itself; a stale-broadcast regression would diverge here)
    assert all(r["epochs"] < 90000 for r in results), results
    assert results[0]["epochs"] == results[1]["epochs"], results
    master = next(r for r in results if r["process_id"] == 0)
    assert master.get("snapshot"), results
    assert os.path.exists(master["snapshot"])
    # the checkpoint is complete and loadable, not truncated
    from veles_tpu.services.snapshotter import SnapshotterBase
    snap = SnapshotterBase.import_(master["snapshot"])
    assert "params" in snap and "loader" in snap
