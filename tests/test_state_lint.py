"""State-plane contract auditors (ISSUE 19): the VK10xx serialized-
state contract audit and the VB11xx host-determinism lint.

The PR 16/17 test pattern: per-rule seeded-hazard fixtures where each
rule fires exactly once, cross-module writer/reader matching, the
clean-path idioms (.get default, membership probe, version guard,
exempted metadata), the suppression contract, real-tree zero-findings
gates, the generated docs/state_reference.md pin, the never-imports-
what-it-scans purity pin, and the CLI gates in-process."""

import os
import textwrap

import pytest

from veles_tpu.analysis import determinism_audit, state_audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return [f.rule for f in findings]


def _state(tmp_path, *sources):
    paths = []
    for i, src in enumerate(sources):
        p = tmp_path / ("mod%d.py" % i)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return state_audit.lint_state(paths=paths)


def _determinism(tmp_path, *sources):
    paths = []
    for i, src in enumerate(sources):
        p = tmp_path / ("det%d.py" % i)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return determinism_audit.lint_determinism(paths=paths)


# --------------------------------------------------------------------------
# VK10xx — seeded hazards, each rule fires exactly once
# --------------------------------------------------------------------------

VK_SEEDS = {
    "VK1000": """
        class Snapshotter:
            def collect(self):
                return {"params": 1, "debug_blob": 2}

            def restore(self, workflow, snapshot):
                return snapshot["params"]
        """,
    "VK1001": """
        class Snapshotter:
            def collect(self):
                return {"params": 1}

            def restore(self, workflow, snapshot):
                return snapshot.get("params"), snapshot.get("momentum")
        """,
    "VK1002": """
        class Snapshotter:
            def collect(self):
                snap = {"params": 1}
                if self.extended:
                    snap["extra"] = 2
                return snap

            def restore(self, workflow, snapshot):
                return snapshot["params"], snapshot["extra"]
        """,
    "VK1003": """
        import hashlib
        import json

        def tree_digest(tree):
            return hashlib.sha256(
                json.dumps(tree).encode()).hexdigest()
        """,
    "VK1004": """
        import threading

        class Snapshotter:
            def collect(self):
                return {"params": 1, "guard": threading.Lock()}
        """,
}


class TestSeededVK:
    @pytest.mark.parametrize("rule", sorted(VK_SEEDS))
    def test_rule_fires_exactly_once(self, rule, tmp_path):
        findings = _state(tmp_path, VK_SEEDS[rule])
        assert _rules(findings) == [rule], findings

    def test_all_vk_rules_covered(self):
        assert tuple(sorted(VK_SEEDS)) == state_audit.RULES

    def test_vk1000_reader_in_other_module_clears(self, tmp_path):
        """The scanned files are ONE state universe — a key written
        here and restored there is matched across modules."""
        reader = """
            class Trainer:
                def restore(self, workflow, snapshot):
                    return snapshot["params"], snapshot["debug_blob"]
            """
        findings = _state(tmp_path, VK_SEEDS["VK1000"], reader)
        assert findings == [], findings

    def test_vk1001_writer_in_other_module_clears(self, tmp_path):
        writer = """
            class Momentum:
                def collect(self):
                    return {"momentum": 0.9}
            """
        findings = _state(tmp_path, VK_SEEDS["VK1001"], writer)
        assert findings == [], findings

    def test_vk1002_get_default_clears(self, tmp_path):
        findings = _state(tmp_path, """
            class Snapshotter:
                def collect(self):
                    snap = {"params": 1}
                    if self.extended:
                        snap["extra"] = 2
                    return snap

                def restore(self, workflow, snapshot):
                    return snapshot["params"], snapshot.get("extra")
            """)
        assert findings == [], findings

    def test_vk1002_membership_probe_clears(self, tmp_path):
        findings = _state(tmp_path, """
            class Snapshotter:
                def collect(self):
                    snap = {"params": 1}
                    if self.extended:
                        snap["extra"] = 2
                    return snap

                def restore(self, workflow, snapshot):
                    out = snapshot["params"]
                    if "extra" in snapshot:
                        out += snapshot["extra"]
                    return out
            """)
        assert findings == [], findings

    def test_vk1002_version_guard_clears(self, tmp_path):
        """A reader comparing the contract's version key is guarded:
        old payloads take the version branch, not the KeyError."""
        findings = _state(tmp_path, """
            class Snapshotter:
                def state_manifest(self):
                    man = {"format": 2}
                    if self.arrays:
                        man["arrays"] = list(self.arrays)
                    return man

                def validate_state_manifest(self, manifest):
                    if manifest.get("format") != 2:
                        return None
                    return manifest["arrays"]
            """)
        assert findings == [], findings

    def test_vk1003_sort_keys_is_canonical(self, tmp_path):
        findings = _state(tmp_path, """
            import hashlib
            import json

            def tree_digest(tree):
                return hashlib.sha256(json.dumps(
                    tree, sort_keys=True).encode()).hexdigest()
            """)
        assert findings == [], findings

    def test_vk1003_dict_order_into_digest_update(self, tmp_path):
        findings = _state(tmp_path, """
            import hashlib

            def tree_digest(leaves):
                h = hashlib.sha256()
                for name, blob in leaves.items():
                    h.update(blob)
                return h.hexdigest()
            """)
        assert _rules(findings) == ["VK1003"], findings

    def test_meta_keys_are_not_dead_freight(self, tmp_path):
        """Wall-clock provenance keys (META_KEYS) are written for
        operators, read by no restore path — and exempt by design."""
        findings = _state(tmp_path, """
            import time

            class Snapshotter:
                def collect(self):
                    return {"params": 1, "created": time.time()}

                def restore(self, workflow, snapshot):
                    return snapshot["params"]
            """)
        assert findings == [], findings

    def test_reader_side_augmentation_registers_key(self, tmp_path):
        """``msg["resumed"] = True`` in a reader is a (reader-side)
        writer: a later strict read of it is VK1002, not VK1001."""
        findings = _state(tmp_path, """
            class Router:
                def _do_work_post(self, wfile):
                    wfile.write(json.dumps({"done": True}) + "\\n")

                def _pump_stream(self, resp, msg):
                    msg["resumed"] = True
                    return msg["resumed"], msg.get("done")
            """)
        assert _rules(findings) == ["VK1002"], findings


# --------------------------------------------------------------------------
# VB11xx — seeded hazards, each rule fires exactly once
# --------------------------------------------------------------------------

VB_SEEDS = {
    "VB1100": """
        import time

        class Snapshotter:
            def collect(self):
                return {"params": 1, "stamp": time.time()}
        """,
    "VB1101": """
        import os

        def newest(directory):
            return os.listdir(directory)[0]
        """,
    "VB1102": """
        def dedupe(names):
            out = []
            for name in set(names):
                out.append(name)
            return out
        """,
    "VB1103": """
        import uuid

        def commit_tag():
            return uuid.uuid4().hex
        """,
    "VB1104": """
        import json
        import threading

        def gather(hosts):
            results = []

            def probe():
                results.append(1)

            for host in hosts:
                threading.Thread(target=probe).start()
            return json.dumps(results)
        """,
}


class TestSeededVB:
    @pytest.mark.parametrize("rule", sorted(VB_SEEDS))
    def test_rule_fires_exactly_once(self, rule, tmp_path):
        findings = _determinism(tmp_path, VB_SEEDS[rule])
        assert _rules(findings) == [rule], findings

    def test_all_vb_rules_covered(self):
        assert tuple(sorted(VB_SEEDS)) == determinism_audit.RULES

    def test_vb1100_exempt_metadata_key(self, tmp_path):
        """"created"-style provenance is the sanctioned wall-clock in
        a payload — allowlisted with a rationale, not suppressed."""
        findings = _determinism(tmp_path, """
            import time

            class Snapshotter:
                def collect(self):
                    return {"params": 1, "created": time.time()}
            """)
        assert findings == [], findings
        assert "created" in determinism_audit.EXEMPT_WALLCLOCK_KEYS

    def test_vb1101_sorted_wrap_clears(self, tmp_path):
        findings = _determinism(tmp_path, """
            import os

            def newest(directory):
                return sorted(os.listdir(directory))[0]
            """)
        assert findings == [], findings

    def test_vb1101_sorted_genexp_clears(self, tmp_path):
        """The podmaster idiom: enumeration inside a genexp that is
        itself the sorted() argument is ordered."""
        findings = _determinism(tmp_path, """
            import os

            def logs(directory):
                return sorted(n for n in os.listdir(directory)
                              if n.endswith(".log"))
            """)
        assert findings == [], findings

    def test_vb1102_sorted_set_clears(self, tmp_path):
        findings = _determinism(tmp_path, """
            def dedupe(names):
                out = []
                for name in sorted(set(names)):
                    out.append(name)
                return out
            """)
        assert findings == [], findings

    def test_vb1103_seeded_instance_is_sanctioned(self, tmp_path):
        findings = _determinism(tmp_path, """
            import random

            def shuffled(names, seed):
                rng = random.Random(seed)
                rng.shuffle(names)
                return names
            """)
        assert findings == [], findings

    def test_vb1103_unseeded_ctor_flagged(self, tmp_path):
        findings = _determinism(tmp_path, """
            import random

            def shuffled(names):
                rng = random.Random()
                rng.shuffle(names)
                return names
            """)
        assert _rules(findings) == ["VB1103"], findings

    def test_vb1104_sort_before_escape_clears(self, tmp_path):
        findings = _determinism(tmp_path, """
            import json
            import threading

            def gather(hosts):
                results = []

                def probe():
                    results.append(1)

                for host in hosts:
                    threading.Thread(target=probe).start()
                results.sort()
                return json.dumps(results)
            """)
        assert findings == [], findings


# --------------------------------------------------------------------------
# suppression — the lint-ok contract, shared with VT/VW/VC
# --------------------------------------------------------------------------

class TestSuppression:
    def test_rationale_suppresses_vk(self, tmp_path):
        findings = _state(tmp_path, """
            class Snapshotter:
                def collect(self):
                    # lint-ok: VK1000 — staged key; the reader lands
                    # with the registry PR
                    return {"params": 1, "debug_blob": 2}

                def restore(self, workflow, snapshot):
                    return snapshot["params"]
            """)
        assert findings == [], findings

    def test_rationale_suppresses_vb(self, tmp_path):
        findings = _determinism(tmp_path, """
            import os

            def count(directory):
                # lint-ok: VB1101 — only the COUNT is used; order
                # never escapes this function
                return len(os.listdir(directory))
            """)
        assert findings == [], findings

    def test_bare_lint_ok_suppresses_nothing(self, tmp_path):
        findings = _determinism(tmp_path, """
            import os

            def newest(directory):
                # lint-ok:
                return os.listdir(directory)[0]
            """)
        assert _rules(findings) == ["VB1101"], findings

    def test_wrong_rule_tag_suppresses_nothing(self, tmp_path):
        findings = _determinism(tmp_path, """
            import os

            def newest(directory):
                # lint-ok: VB1103 — wrong family member
                return os.listdir(directory)[0]
            """)
        assert _rules(findings) == ["VB1101"], findings


# --------------------------------------------------------------------------
# the shipped tree — both contracts hold at zero findings
# --------------------------------------------------------------------------

class TestRealTree:
    def test_state_contracts_are_clean(self):
        findings = state_audit.lint_state()
        assert findings == [], findings

    def test_determinism_is_clean(self):
        findings = determinism_audit.lint_determinism()
        assert findings == [], findings

    def test_reference_doc_is_fresh(self):
        """docs/state_reference.md is generated — regenerating it must
        reproduce the checked-in file byte for byte (the CI staleness
        gate)."""
        with open(os.path.join(REPO, "docs",
                               "state_reference.md")) as fh:
            checked_in = fh.read()
        assert state_audit.build_reference() == checked_in

    def test_reference_is_deterministic(self):
        assert state_audit.build_reference() == \
            state_audit.build_reference()

    def test_exemption_maps_stay_in_lockstep(self):
        """Every VB1100 wall-clock allowlist key is also a VK1000
        metadata exemption — one rationale, two rules."""
        for key in determinism_audit.EXEMPT_WALLCLOCK_KEYS:
            assert key in state_audit.META_KEYS

    def test_audits_never_import_what_they_scan(self):
        """Pure AST: auditing the state plane must not execute it —
        the lints and the reference builder import NOTHING beyond what
        loading the analyzers themselves already did."""
        import subprocess
        import sys
        code = (
            "import sys\n"
            "from veles_tpu.analysis import (determinism_audit,\n"
            "                                state_audit)\n"
            "before = set(sys.modules)\n"
            "state_audit.lint_state()\n"
            "determinism_audit.lint_determinism()\n"
            "state_audit.build_reference()\n"
            "grew = sorted(m for m in set(sys.modules) - before\n"
            "              if m.startswith('veles_tpu'))\n"
            "scanned = [m for m in sys.modules if m.startswith((\n"
            "    'veles_tpu.services', 'veles_tpu.loader',\n"
            "    'veles_tpu.models', 'veles_tpu.tuner'))]\n"
            "print('GREW', grew, 'SCANNED', scanned)\n")
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO,
            capture_output=True, text=True, check=True)
        assert "GREW [] SCANNED []" in out.stdout, \
            out.stdout + out.stderr


# --------------------------------------------------------------------------
# CLI — exit codes 0/1/2 through the shared findings gate
# --------------------------------------------------------------------------

class TestCLI:
    def test_state_and_determinism_clean(self, capsys):
        from veles_tpu.analysis.cli import main
        rc = main(["--state", "--determinism"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no findings" in out

    def test_all_runs_every_ast_family(self, capsys):
        from veles_tpu.analysis.cli import main
        rc = main(["--all"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no findings" in out

    def test_markdown_prints_the_state_reference(self, capsys):
        from veles_tpu.analysis.cli import main
        rc = main(["--state", "--format", "markdown"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.startswith("# Serialized-state contract reference")

    def test_markdown_pairs_with_one_reference_family(self):
        from veles_tpu.analysis.cli import main
        with pytest.raises(SystemExit) as e:
            main(["--state", "--determinism", "--format", "markdown"])
        assert e.value.code == 2
        with pytest.raises(SystemExit) as e:
            main(["--state", "--config-audit", "--format", "markdown"])
        assert e.value.code == 2

    def test_fail_on_unifies_state_findings(self, capsys, monkeypatch):
        """A VK1000 dead-freight warning flips the exit only under
        --fail-on warning — threshold_reached is the one gate."""
        import veles_tpu.analysis as analysis
        from veles_tpu.analysis.cli import main
        from veles_tpu.analysis.findings import WARNING, Finding
        monkeypatch.setattr(
            analysis, "lint_state",
            lambda paths=None, root=None: [Finding(
                "VK1000", WARNING, "x.py:1", "seeded")])
        assert main(["--state"]) == 0
        assert main(["--state", "--fail-on", "warning"]) == 1
        out = capsys.readouterr().out
        assert "VK1000" in out
