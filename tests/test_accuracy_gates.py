"""Accuracy gates against the reference's PUBLISHED rows (BASELINE.md;
ref docs/source/manualrst_veles_algorithms.rst:32-52) — skipped, not
absent, when the datasets are not mounted (VERDICT r1 #10).  The digits
thresholds in tests/test_training.py are the always-on offline proxies
derived from these.

Mount points (zero-egress; nothing downloads):
  <datasets>/mnist/{train,t10k}-{images-idx3,labels-idx1}-ubyte[.gz]
  <datasets>/cifar-10-batches-py/{data_batch_1..5,test_batch}
"""

import json
import os
import subprocess
import sys

import pytest

from veles_tpu.loader.datasets import (cifar10_available, mnist_available,
                                       stl10_available)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: published 1.48 % + margin for the different backend/optimizer stack
MNIST_GATE = 0.02
#: published 17.21 % + margin
CIFAR_GATE = 0.20
#: published 35.10 % + margin
STL10_GATE = 0.40
#: published validation RMSE 0.5478 + margin
MNIST_AE_GATE = 0.60


def _run_config(workflow, config, result, extra=(), timeout=5400):
    argv = [sys.executable, "-m", "veles_tpu", workflow]
    if config:
        argv.append(config)
    argv += ["--random-seed", "1234", "--result-file", result]
    r = subprocess.run(argv + list(extra), cwd=REPO, env=dict(os.environ),
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, r.stderr[-3000:]
    return json.load(open(result))


@pytest.mark.skipif(not mnist_available(),
                    reason="MNIST idx files not mounted under datasets/")
def test_mnist_mlp_matches_published_row(tmp_path):
    res = _run_config("samples/mnist_mlp.py", "samples/mnist_config.py",
                      str(tmp_path / "mnist.json"))
    assert res["best_metric"] <= MNIST_GATE, res["best_metric"]


@pytest.mark.skipif(not cifar10_available(),
                    reason="CIFAR-10 python batches not mounted under "
                           "datasets/")
def test_cifar_conv_matches_published_row(tmp_path):
    res = _run_config("samples/cifar_conv.py", "samples/cifar_config.py",
                      str(tmp_path / "cifar.json"))
    assert res["best_metric"] <= CIFAR_GATE, res["best_metric"]


@pytest.mark.skipif(not stl10_available(),
                    reason="STL-10 binary files not mounted under "
                           "datasets/")
def test_stl10_conv_matches_published_row(tmp_path):
    res = _run_config("samples/stl10_conv.py", None,
                      str(tmp_path / "stl10.json"))
    assert res["best_metric"] <= STL10_GATE, res["best_metric"]


@pytest.mark.skipif(not mnist_available(),
                    reason="MNIST idx files not mounted under datasets/")
def test_mnist_autoencoder_matches_published_rmse(tmp_path):
    res = _run_config("samples/mnist_ae.py", None,
                      str(tmp_path / "ae.json"))
    assert res["best_metric"] <= MNIST_AE_GATE, res["best_metric"]
