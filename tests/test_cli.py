"""CLI end-to-end: the `python -m veles_tpu workflow.py config.py`
contract, config layering, result files, and package export — run as real
subprocesses against the shipped samples."""

import json
import os
import subprocess
import sys
import zipfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(args, timeout=420):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)


class TestCLI:
    def test_digits_mlp_sample_trains_and_writes_results(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu", "--random-seed", "5",
                  "--config-list", "root.digits.max_epochs=2",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        res = json.load(open(out))
        assert res["epochs"] == 2
        assert res["best_metric"] is not None

    def test_export_flag_writes_package(self, tmp_path):
        pkg = str(tmp_path / "model.zip")
        r = _cli(["samples/digits_mlp.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits.max_epochs=1",
                  "--export", pkg])
        assert r.returncode == 0, r.stderr[-2000:]
        with zipfile.ZipFile(pkg) as zf:
            assert "contents.json" in zf.namelist()

    def test_char_lm_sample(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/char_lm.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.char_lm.max_epochs=1",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1

    def test_kohonen_sample(self):
        r = _cli(["samples/digits_kohonen.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits_kohonen.n_epochs=1"])
        assert r.returncode == 0, r.stderr[-2000:]

    def test_conv_sample(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/digits_conv.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits_conv.max_epochs=1",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1

    def test_missing_run_contract_errors(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1\n")
        r = _cli([str(bad), "--backend", "cpu"])
        assert r.returncode != 0
        assert "run(load, main)" in r.stderr + r.stdout
