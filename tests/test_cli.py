"""CLI end-to-end: the `python -m veles_tpu workflow.py config.py`
contract, config layering, result files, and package export — run as real
subprocesses against the shipped samples."""

import json
import os
import subprocess
import sys
import zipfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(args, timeout=420, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu"] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)


class TestCLI:
    def test_digits_mlp_sample_trains_and_writes_results(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu", "--random-seed", "5",
                  "--config-list", "root.digits.max_epochs=2",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        res = json.load(open(out))
        assert res["epochs"] == 2
        assert res["best_metric"] is not None

    def test_steps_per_dispatch_flag_matches_per_step(self, tmp_path):
        def run(extra):
            out = str(tmp_path / ("res%d.json" % len(extra)))
            r = _cli(["samples/digits_mlp.py", "--backend", "cpu",
                      "--random-seed", "5",
                      "--config-list", "root.digits.max_epochs=2",
                      "--result-file", out] + extra)
            assert r.returncode == 0, r.stderr[-2000:]
            return json.load(open(out))["best_metric"]

        import pytest
        # fused lax.scan is a different XLA program: ulp-level drift is
        # legal, bitwise equality is not guaranteed
        assert run([]) == pytest.approx(
            run(["--steps-per-dispatch", "4"]), abs=5e-3)

    def test_export_flag_writes_package(self, tmp_path):
        pkg = str(tmp_path / "model.zip")
        r = _cli(["samples/digits_mlp.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits.max_epochs=1",
                  "--export", pkg])
        assert r.returncode == 0, r.stderr[-2000:]
        with zipfile.ZipFile(pkg) as zf:
            assert "contents.json" in zf.namelist()

    def test_export_stablehlo_flag(self, tmp_path):
        """--export-stablehlo writes a loadable compiled-forward
        artifact whose predictions are valid probabilities."""
        pkg = str(tmp_path / "model.stablehlo.zip")
        r = _cli(["samples/digits_mlp.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits.max_epochs=1",
                  "--export-stablehlo", pkg])
        assert r.returncode == 0, r.stderr[-2000:]
        assert "stablehlo (" in r.stdout + r.stderr
        import numpy as np
        from veles_tpu.services.export import load_stablehlo
        fn, meta = load_stablehlo(pkg)
        assert meta["input_shape"] == [64]
        probs = np.asarray(fn(np.zeros((3, 64), np.float32)))
        assert probs.shape == (3, 10)
        np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-5)

    def test_char_lm_sample(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/char_lm.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.char_lm.max_epochs=1",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1

    def test_gpt_lm_sample(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/gpt_lm.py", "--backend", "cpu",
                  "--random-seed", "5", "--steps-per-dispatch", "4",
                  "--config-list", "root.gpt.max_epochs=1",
                  "root.gpt.n_layers=1", "root.gpt.d_model=32",
                  "root.gpt.seq_len=32", "root.gpt.n_heads=4",
                  "--generate", "the quick:8",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1
        # repr may single- or double-quote depending on content
        assert "generated: " in r.stdout and "the quick" in r.stdout

    def test_gpt_lm_preset(self, tmp_path):
        """preset=large applies its entries (remat/adamw survive) while
        explicit --config-list values win over the preset's dims."""
        out = str(tmp_path / "res.json")
        r = _cli(["samples/gpt_lm.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.gpt.preset='large'",
                  "root.gpt.max_epochs=1", "root.gpt.n_layers=1",
                  "root.gpt.d_model=32", "root.gpt.seq_len=32",
                  "root.gpt.n_heads=4", "root.gpt.n_kv_heads=4",
                  "root.gpt.minibatch_size=16",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1
        r = _cli(["samples/gpt_lm.py", "--backend", "cpu",
                  "--config-list", "root.gpt.preset='nope'"])
        assert r.returncode != 0
        assert "unknown preset" in r.stderr

    def test_kohonen_sample(self):
        r = _cli(["samples/digits_kohonen.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits_kohonen.n_epochs=1"])
        assert r.returncode == 0, r.stderr[-2000:]

    def test_imagenet_alexnet_sample_synthetic(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/imagenet_alexnet.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.imagenet.minibatch_size=4",
                  "root.imagenet.steps_per_epoch=2",
                  "root.imagenet.max_epochs=1",
                  "root.imagenet.n_classes=10",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1

    def test_imagenet_alexnet_sample_from_directory(self, tmp_path):
        from PIL import Image
        import numpy as np
        rs = np.random.RandomState(0)
        for cls in ("n01", "n02"):
            d = tmp_path / "train" / cls
            d.mkdir(parents=True)
            for j in range(3):
                Image.fromarray(
                    rs.randint(0, 255, (32, 48, 3), np.uint8)).save(
                        str(d / ("img%d.jpg" % j)))
        out = str(tmp_path / "res.json")
        r = _cli(["samples/imagenet_alexnet.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list",
                  "root.imagenet.data_dir='%s'" % (tmp_path / "train"),
                  "root.imagenet.minibatch_size=4",
                  "root.imagenet.steps_per_epoch=2",
                  "root.imagenet.max_epochs=1",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1

    def test_conv_sample(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/digits_conv.py", "--backend", "cpu",
                  "--random-seed", "5",
                  "--config-list", "root.digits_conv.max_epochs=1",
                  "--result-file", out])
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 1

    def test_missing_run_contract_errors(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("x = 1\n")
        r = _cli([str(bad), "--backend", "cpu"])
        assert r.returncode != 0
        assert "run(load, main)" in r.stderr + r.stdout


class TestCLIMeta:
    """r2: the meta flags the reference's single CLI drives
    (VERDICT #3 — ref veles/__main__.py:334-345, launcher.py:199-267)."""

    def test_mesh_flag_runs_spmd(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu", "--random-seed", "5",
                  "--mesh", "data=8",
                  "--config-list", "root.digits.max_epochs=2",
                  "root.digits.minibatch_size=96",
                  "--result-file", out],
                 env_extra={"XLA_FLAGS":
                            "--xla_force_host_platform_device_count=8"})
        assert r.returncode == 0, r.stderr[-2000:]
        res = json.load(open(out))
        assert res["epochs"] == 2
        assert res["best_metric"] is not None

    def test_fsdp_flag_runs(self, tmp_path):
        out = str(tmp_path / "res.json")
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu", "--random-seed", "5",
                  "--mesh", "data=8", "--fsdp",
                  "--config-list", "root.digits.max_epochs=2",
                  "root.digits.minibatch_size=96",
                  "--result-file", out],
                 env_extra={"XLA_FLAGS":
                            "--xla_force_host_platform_device_count=8"})
        assert r.returncode == 0, r.stderr[-2000:]
        assert json.load(open(out))["epochs"] == 2

    def test_mesh_flag_bad_spec(self):
        r = _cli(["samples/digits_mlp.py", "--backend", "cpu",
                  "--mesh", "data"])
        assert r.returncode != 0
        assert "axis=size" in r.stderr

    def test_optimize_genetics_over_range_config(self, tmp_path):
        out = str(tmp_path / "opt.json")
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu", "--random-seed", "7",
                  "--config-list", "root.digits.max_epochs=1",
                  "root.digits.learning_rate=Range(0.05, 0.3)",
                  "--optimize", "3:2", "--optimize-workers", "2",
                  "--result-file", out], timeout=900)
        assert r.returncode == 0, r.stderr[-2000:]
        res = json.load(open(out))["optimize"]
        lr = res["best_config"]["root.digits.learning_rate"]
        assert 0.05 <= lr <= 0.3
        assert len(res["history"]) == 2
        assert res["best_fitness"] > -1.0   # a real error rate, not -inf

    def test_optimize_distributed_workers(self, tmp_path):
        """VERDICT r2 #7: GA fitness spread over SEPARATE worker
        processes — coordinator serves the chromosome queue (0 local
        evaluators), two --optimize-worker processes pull and evaluate
        concurrently, and BOTH must do real work."""
        import socket
        import time as _time

        with socket.socket() as s:      # pick a free port up front
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        out = str(tmp_path / "opt.json")
        wf_args = ["samples/digits_mlp.py", "samples/digits_config.py",
                   "--backend", "cpu", "--random-seed", "7",
                   "--config-list", "root.digits.max_epochs=1",
                   "root.digits.learning_rate=Range(0.05, 0.3)"]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        coord = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu"] + wf_args +
            ["--optimize", "4:2",
             "--optimize-workers", "0@127.0.0.1:%d" % port,
             "--result-file", out],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        _time.sleep(2)                  # let the queue come up
        workers = [subprocess.Popen(
            [sys.executable, "-m", "veles_tpu"] + wf_args +
            ["--optimize-worker", "127.0.0.1:%d" % port],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for _ in range(2)]
        try:
            c_out, c_err = coord.communicate(timeout=900)
            assert coord.returncode == 0, c_err[-2000:]
            counts = []
            for w in workers:
                w_out, w_err = w.communicate(timeout=120)
                assert w.returncode == 0, w_err[-2000:]
                counts.append(json.loads(
                    w_out.splitlines()[-1])["optimize_worker"]["evaluated"])
            # every evaluation ran on a worker, and both workers worked
            assert sum(counts) >= 4 and all(c >= 1 for c in counts), counts
            res = json.load(open(out))["optimize"]
            assert 0.05 <= res["best_config"][
                "root.digits.learning_rate"] <= 0.3
            assert res["best_fitness"] > -1.0
        finally:
            for p in [coord] + workers:
                if p.poll() is None:
                    p.kill()

    def test_optimize_without_ranges_fails_clearly(self):
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu", "--optimize", "2:1"])
        assert r.returncode != 0
        assert "Range()" in r.stderr

    def test_ensemble_train_then_test(self, tmp_path):
        out = str(tmp_path / "ens.json")
        r = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                  "--backend", "cpu",
                  "--config-list", "root.digits.max_epochs=1",
                  "--ensemble-train", "3:0.7", "--ensemble-workers", "2",
                  "--result-file", out], timeout=900)
        assert r.returncode == 0, r.stderr[-2000:]
        res = json.load(open(out))
        assert res["n_models"] == 3
        assert all("package" in m and os.path.exists(m["package"])
                   for m in res["members"])
        # members trained on distinct subsets -> distinct results
        metrics = [m["result"]["best_metric"] for m in res["members"]]
        assert len(set(metrics)) > 1

        r2 = _cli(["samples/digits_mlp.py", "samples/digits_config.py",
                   "--backend", "cpu", "--random-seed", "5",
                   "--config-list", "root.digits.max_epochs=1",
                   "--ensemble-test", out], timeout=600)
        assert r2.returncode == 0, r2.stderr[-2000:]
        payload = json.loads(next(
            ln for ln in r2.stdout.splitlines()
            if ln.startswith('{"ensemble_test"')))
        assert payload["ensemble_test"]["n_members"] == 3
        assert 0.0 <= payload["ensemble_test"]["error"] < 0.5
