"""End-to-end transformer workflows: encoder classifier on synthetic
sequences and the causal LM objective — exercises embedding, transformer
blocks (attention + MLP), layer norm, seq pooling, timestep dense, and the
per-timestep LM loss through the standard staged trainer."""

import numpy as np

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.standard_workflow import StandardWorkflow


def _seq_classification_data(n=512, t=12, f=8, seed=0):
    """Class = which third of the sequence carries the energy burst."""
    r = np.random.RandomState(seed)
    x = r.randn(n, t, f).astype(np.float32) * 0.1
    y = r.randint(0, 3, n).astype(np.int32)
    for i in range(n):
        lo = y[i] * (t // 3)
        x[i, lo:lo + t // 3] += 1.0
    return x, y


def test_transformer_classifier_trains():
    prng.seed_all(42)
    x, y = _seq_classification_data()
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=64,
                             class_lengths=[0, 128, 384])
    wf = StandardWorkflow(
        layers=zoo.transformer_classifier(
            n_classes=3, d_model=32, n_heads=4, n_layers=1, lr=0.003,
            dropout=0.0),
        loader=loader,
        decision_config={"max_epochs": 30},
        name="tfm-cls")
    wf.initialize()
    wf.run()
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.2, \
        "validation error %.3f not < 20%%" % wf.decision.best_metric


def test_transformer_lm_trains():
    prng.seed_all(43)
    # deterministic periodic token streams — trivially learnable
    r = np.random.RandomState(1)
    n, t, vocab = 256, 16, 17
    phase = r.randint(0, 5, n)
    tokens = ((np.arange(t)[None, :] * 3 + phase[:, None]) % vocab
              ).astype(np.int32)
    loader = FullBatchLoader(None, data=tokens, labels=tokens,
                             minibatch_size=64,
                             class_lengths=[0, 64, 192])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32, n_heads=4,
                                  n_layers=1, lr=0.005),
        loader=loader,
        loss="lm",
        decision_config={"max_epochs": 25},
        name="tfm-lm")
    wf.initialize()
    wf.run()
    # best_metric for DecisionGD = validation error rate (token-level here)
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.15, \
        "token error %.3f not < 15%%" % wf.decision.best_metric


def _lm_tokens(n=256, t=16, vocab=17, seed=1):
    r = np.random.RandomState(seed)
    phase = r.randint(0, 5, n)
    return ((np.arange(t)[None, :] * 3 + phase[:, None]) % vocab
            ).astype(np.int32)


def _train_lm(max_epochs=12, **zoo_kwargs):
    prng.seed_all(47)
    vocab = 17
    tokens = _lm_tokens(vocab=vocab)
    loader = FullBatchLoader(None, data=tokens, labels=tokens,
                             minibatch_size=64,
                             class_lengths=[0, 64, 192])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32, n_heads=4,
                                  n_layers=1, lr=0.005, **zoo_kwargs),
        loader=loader, loss="lm",
        decision_config={"max_epochs": max_epochs},
        name="tfm-lm-x")
    wf.initialize()
    wf.run()
    return wf


def test_gqa_params_smaller_and_trains():
    """Grouped-query attention: fewer k/v parameters, still learns."""
    wf_full = _train_lm(max_epochs=1)
    wf_gqa = _train_lm(max_epochs=12, n_kv_heads=2)
    mha_full = wf_full.trainer.params["l02_transformer_block"]["mha"]
    mha_gqa = wf_gqa.trainer.params["l02_transformer_block"]["mha"]
    assert mha_gqa["wk"].shape[1] == mha_full["wk"].shape[1] // 2
    assert mha_gqa["wv"].shape[1] == mha_full["wv"].shape[1] // 2
    assert mha_gqa["wq"].shape == mha_full["wq"].shape
    assert wf_gqa.decision.best_metric < 0.2, wf_gqa.decision.best_metric


def test_remat_matches_no_remat():
    """jax.checkpoint rematerialization must not change the math."""
    wf_a = _train_lm(max_epochs=4)
    wf_b = _train_lm(max_epochs=4, remat=True)
    import jax
    pa, pb = wf_a.trainer.host_params(), wf_b.trainer.host_params()
    # remat recomputes the forward inside the backward: XLA may fuse the
    # recompute differently, so ulp-level drift accumulates over steps
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3,
                                                atol=1e-5), pa, pb)


def test_remat_dots_policy_matches_no_remat():
    """remat="dots" (dots_saveable: keep matmul outputs, recompute only
    elementwise) must match plain training — same math, fewer saved
    activations, none of full remat's recompute FLOPs."""
    wf_a = _train_lm(max_epochs=4)
    wf_b = _train_lm(max_epochs=4, remat="dots")
    import jax
    pa, pb = wf_a.trainer.host_params(), wf_b.trainer.host_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3,
                                                atol=1e-5), pa, pb)


def test_remat_with_moe_aux_loss():
    """The MoE router aux loss must survive the remat boundary (it is
    returned through jax.checkpoint, not stashed as a side effect)."""
    wf = _train_lm(max_epochs=3, remat=True, n_experts=2)
    assert wf.decision.best_metric is not None


def test_rope_lm_trains():
    """Rotary position embedding: no position table, still learns."""
    wf = _train_lm(max_epochs=12, pos="rope")
    layer_types = [l.type for l in wf.trainer.layers]
    assert "positional_encoding" not in layer_types
    assert wf.decision.best_metric < 0.2, wf.decision.best_metric


def test_sliding_window_lm_trains():
    wf = _train_lm(max_epochs=12, window=6, impl="flash")
    assert wf.decision.best_metric < 0.2, wf.decision.best_metric


def test_tied_embeddings_lm():
    """Weight tying: no separate head params, gradients reach the table
    through both uses, and the model still learns."""
    wf_tied = _train_lm(max_epochs=12, tie_embeddings=True)
    wf_free = _train_lm(max_epochs=1)
    assert wf_tied.decision.best_metric < 0.2, wf_tied.decision.best_metric
    head_names = [l.name for l in wf_tied.trainer.layers
                  if l.type == "tied_lm_head"]
    assert head_names and head_names[0] not in wf_tied.trainer.params
    n_tied = sum(np.prod(a.shape) for lp in
                 wf_tied.trainer.host_params().values()
                 for a in _leaves(lp))
    n_free = sum(np.prod(a.shape) for lp in
                 wf_free.trainer.host_params().values()
                 for a in _leaves(lp))
    assert n_free - n_tied >= 17 * 32    # one vocab x d_model table saved


def _leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)
