"""End-to-end transformer workflows: encoder classifier on synthetic
sequences and the causal LM objective — exercises embedding, transformer
blocks (attention + MLP), layer norm, seq pooling, timestep dense, and the
per-timestep LM loss through the standard staged trainer."""

import numpy as np

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.standard_workflow import StandardWorkflow


def _seq_classification_data(n=512, t=12, f=8, seed=0):
    """Class = which third of the sequence carries the energy burst."""
    r = np.random.RandomState(seed)
    x = r.randn(n, t, f).astype(np.float32) * 0.1
    y = r.randint(0, 3, n).astype(np.int32)
    for i in range(n):
        lo = y[i] * (t // 3)
        x[i, lo:lo + t // 3] += 1.0
    return x, y


def test_transformer_classifier_trains():
    prng.seed_all(42)
    x, y = _seq_classification_data()
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=64,
                             class_lengths=[0, 128, 384])
    wf = StandardWorkflow(
        layers=zoo.transformer_classifier(
            n_classes=3, d_model=32, n_heads=4, n_layers=1, lr=0.003,
            dropout=0.0),
        loader=loader,
        decision_config={"max_epochs": 30},
        name="tfm-cls")
    wf.initialize()
    wf.run()
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.2, \
        "validation error %.3f not < 20%%" % wf.decision.best_metric


def test_transformer_lm_trains():
    prng.seed_all(43)
    # deterministic periodic token streams — trivially learnable
    r = np.random.RandomState(1)
    n, t, vocab = 256, 16, 17
    phase = r.randint(0, 5, n)
    tokens = ((np.arange(t)[None, :] * 3 + phase[:, None]) % vocab
              ).astype(np.int32)
    loader = FullBatchLoader(None, data=tokens, labels=tokens,
                             minibatch_size=64,
                             class_lengths=[0, 64, 192])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32, n_heads=4,
                                  n_layers=1, lr=0.005),
        loader=loader,
        loss="lm",
        decision_config={"max_epochs": 25},
        name="tfm-lm")
    wf.initialize()
    wf.run()
    # best_metric for DecisionGD = validation error rate (token-level here)
    assert wf.decision.best_metric is not None
    assert wf.decision.best_metric < 0.15, \
        "token error %.3f not < 15%%" % wf.decision.best_metric


def _lm_tokens(n=256, t=16, vocab=17, seed=1):
    r = np.random.RandomState(seed)
    phase = r.randint(0, 5, n)
    return ((np.arange(t)[None, :] * 3 + phase[:, None]) % vocab
            ).astype(np.int32)


def _train_lm(max_epochs=12, **zoo_kwargs):
    prng.seed_all(47)
    vocab = 17
    tokens = _lm_tokens(vocab=vocab)
    loader = FullBatchLoader(None, data=tokens, labels=tokens,
                             minibatch_size=64,
                             class_lengths=[0, 64, 192])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32, n_heads=4,
                                  n_layers=1, lr=0.005, **zoo_kwargs),
        loader=loader, loss="lm",
        decision_config={"max_epochs": max_epochs},
        name="tfm-lm-x")
    wf.initialize()
    wf.run()
    return wf


def test_gqa_params_smaller_and_trains():
    """Grouped-query attention: fewer k/v parameters, still learns."""
    wf_full = _train_lm(max_epochs=1)
    wf_gqa = _train_lm(max_epochs=12, n_kv_heads=2)
    mha_full = wf_full.trainer.params["l02_transformer_block"]["mha"]
    mha_gqa = wf_gqa.trainer.params["l02_transformer_block"]["mha"]
    assert mha_gqa["wk"].shape[1] == mha_full["wk"].shape[1] // 2
    assert mha_gqa["wv"].shape[1] == mha_full["wv"].shape[1] // 2
    assert mha_gqa["wq"].shape == mha_full["wq"].shape
    assert wf_gqa.decision.best_metric < 0.2, wf_gqa.decision.best_metric


def test_remat_matches_no_remat():
    """jax.checkpoint rematerialization must not change the math."""
    wf_a = _train_lm(max_epochs=4)
    wf_b = _train_lm(max_epochs=4, remat=True)
    import jax
    pa, pb = wf_a.trainer.host_params(), wf_b.trainer.host_params()
    # remat recomputes the forward inside the backward: XLA may fuse the
    # recompute differently, so ulp-level drift accumulates over steps
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3,
                                                atol=1e-5), pa, pb)


def test_remat_dots_policy_matches_no_remat():
    """remat="dots" (dots_saveable: keep matmul outputs, recompute only
    elementwise) must match plain training — same math, fewer saved
    activations, none of full remat's recompute FLOPs."""
    wf_a = _train_lm(max_epochs=4)
    wf_b = _train_lm(max_epochs=4, remat="dots")
    import jax
    pa, pb = wf_a.trainer.host_params(), wf_b.trainer.host_params()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3,
                                                atol=1e-5), pa, pb)


def test_remat_with_moe_aux_loss():
    """The MoE router aux loss must survive the remat boundary (it is
    returned through jax.checkpoint, not stashed as a side effect)."""
    wf = _train_lm(max_epochs=3, remat=True, n_experts=2)
    assert wf.decision.best_metric is not None


def test_rope_lm_trains():
    """Rotary position embedding: no position table, still learns."""
    wf = _train_lm(max_epochs=12, pos="rope")
    layer_types = [l.type for l in wf.trainer.layers]
    assert "positional_encoding" not in layer_types
    assert wf.decision.best_metric < 0.2, wf.decision.best_metric


def test_sliding_window_lm_trains():
    wf = _train_lm(max_epochs=12, window=6, impl="flash")
    assert wf.decision.best_metric < 0.2, wf.decision.best_metric


def test_tied_embeddings_lm():
    """Weight tying: no separate head params, gradients reach the table
    through both uses, and the model still learns."""
    wf_tied = _train_lm(max_epochs=12, tie_embeddings=True)
    wf_free = _train_lm(max_epochs=1)
    assert wf_tied.decision.best_metric < 0.2, wf_tied.decision.best_metric
    head_names = [l.name for l in wf_tied.trainer.layers
                  if l.type == "tied_lm_head"]
    assert head_names and head_names[0] not in wf_tied.trainer.params
    n_tied = sum(np.prod(a.shape) for lp in
                 wf_tied.trainer.host_params().values()
                 for a in _leaves(lp))
    n_free = sum(np.prod(a.shape) for lp in
                 wf_free.trainer.host_params().values()
                 for a in _leaves(lp))
    assert n_free - n_tied >= 17 * 32    # one vocab x d_model table saved


def _leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


class TestLoRA:
    def test_dense_adapter_freezes_base_and_starts_at_identity(self):
        """All2All lora_rank: output == base at init (B = 0); training
        moves ONLY the rank-r factors — W and b stay bit-frozen."""
        import jax
        import jax.numpy as jnp

        from veles_tpu import prng
        from veles_tpu.models import optimizer
        from veles_tpu.models.layers import make_layer
        from veles_tpu.ops import linear
        prng.seed_all(2)
        base = make_layer({"type": "all2all_tanh",
                           "output_sample_shape": 6})
        base.setup((5,))
        lora = make_layer({"type": "all2all_tanh",
                           "output_sample_shape": 6, "lora_rank": 2})
        lora.setup((5,))
        prng.seed_all(7)
        p = lora.init_params(prng.get("t"))
        assert p["lora_a"].shape == (5, 2) and p["lora_b"].shape == (2, 6)
        x = jnp.asarray(np.random.RandomState(0).rand(3, 5), jnp.float32)
        base_p = {k: v for k, v in p.items() if not k.startswith("lora")}
        np.testing.assert_allclose(np.asarray(lora.apply(p, x)),
                                   np.asarray(base.apply(base_p, x)),
                                   rtol=1e-6)

        def loss(params):
            return jnp.sum(jnp.square(linear.forward(params, x)))

        p = {k: jnp.asarray(v) for k, v in p.items()}
        g = jax.grad(loss)(p)
        assert float(jnp.abs(g["weights"]).max()) == 0.0   # frozen
        assert float(jnp.abs(g["bias"]).max()) == 0.0
        # at init B = 0, so only B receives gradient (dL/dA = ... · Bᵀ);
        # once B moves, A becomes trainable too
        assert float(jnp.abs(g["lora_b"]).max()) > 0.0
        assert float(jnp.abs(g["lora_a"]).max()) == 0.0
        params2, _ = optimizer.update(
            {"l": p}, {"l": g},
            optimizer.init_state({"l": p}),
            {"l": optimizer.resolve_hyper({"solver": "gd",
                                           "learning_rate": 0.1})})
        np.testing.assert_array_equal(np.asarray(params2["l"]["weights"]),
                                      np.asarray(p["weights"]))
        g2 = jax.grad(loss)(params2["l"])
        assert float(jnp.abs(g2["lora_a"]).max()) > 0.0    # now trainable

    def test_lora_fine_tune_trains_only_adapters(self):
        """The full parameter-efficient flow: pretrain a base LM →
        snapshot → rebuild with lora_rank + warm-start → fine-tune on a
        SHIFTED task.  Only the q/v adapters move; every base leaf
        stays bit-identical; the adapted model beats the frozen base on
        the new task and still decodes through LMGenerator."""
        import jax

        from veles_tpu.models.generate import LMGenerator
        from veles_tpu.services.snapshotter import TrainingSnapshotter

        def data(shift, seed):
            r = np.random.RandomState(seed)
            return ((np.arange(16)[None, :] * shift
                     + r.randint(0, 4, 192)[:, None]) % 13).astype(
                         np.int32)

        def build(toks, lora_rank, max_epochs, lr):
            loader = FullBatchLoader(None, data=toks, labels=toks,
                                     minibatch_size=48,
                                     class_lengths=[0, 48, 144])
            return StandardWorkflow(
                layers=zoo.transformer_lm(
                    vocab_size=13, d_model=32, n_heads=4, n_layers=1,
                    lr=lr, dropout=0.0, lora_rank=lora_rank,
                    solver="adam"),
                loader=loader, loss="lm",
                decision_config={"max_epochs": max_epochs},
                name="lora-lm")

        prng.seed_all(51)
        base_wf = build(data(2, 5), 0, 12, 5e-3)  # base task: +2 pattern
        base_wf.initialize()
        base_wf.run()
        snap = {"params": base_wf.trainer.host_params()}

        # new task: +3 pattern.  Adapters need a higher lr than full
        # fine-tuning (rank-8 q/v at lr 0.05 reaches 0% here; lr 5e-3
        # stalls at ~53% — measured sweep in the round-4 session log)
        prng.seed_all(52)
        ft = build(data(3, 6), 8, 20, 0.05)
        ft.initialize()
        TrainingSnapshotter.warm_start(ft, snap)
        before = jax.tree_util.tree_map(np.asarray,
                                        ft.trainer.host_params())
        ft.run()
        after = jax.tree_util.tree_map(np.asarray,
                                       ft.trainer.host_params())

        moved, frozen_ok = [], True
        for lname, sub in before.items():
            flat_b = list(jax.tree_util.tree_leaves_with_path(sub))
            flat_a = {jax.tree_util.keystr(pp): ll for pp, ll in
                      jax.tree_util.tree_leaves_with_path(after[lname])}
            for path, leaf in flat_b:
                key = jax.tree_util.keystr(path)
                same = np.array_equal(leaf, flat_a[key])
                if "lora" in key:
                    if not same:
                        moved.append((lname, key))
                else:
                    frozen_ok &= same
        assert moved, "no adapter moved"
        assert frozen_ok, "a frozen base leaf changed"
        # the adapted model learned the shifted pattern
        assert ft.decision.best_metric < 0.10, ft.decision.best_metric
        # and serves through the standard decode paths
        gen = LMGenerator(ft.trainer, max_len=16)
        out = gen.generate(data(3, 6)[:1, :6], 6)
        assert out.shape == (1, 12)

    def test_weight_decay_does_not_pierce_the_freeze(self):
        """adamw's decoupled decay acts OUTSIDE the gradient, so
        stop_gradient alone wouldn't stop it — adapted layers must zero
        their weights_decay or 'frozen' base matrices shrink every
        step."""
        import jax

        prng.seed_all(53)
        toks = _lm_tokens(vocab=13, t=16)[:192] % 13
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=48,
                                 class_lengths=[0, 48, 144])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=13, d_model=32,
                                      n_heads=4, n_layers=1, lr=0.05,
                                      dropout=0.0, lora_rank=4,
                                      solver="adamw"),
            loader=loader, loss="lm",
            gd_defaults={"weights_decay": 0.05},
            decision_config={"max_epochs": 3}, name="lora-wd")
        wf.initialize()
        before = jax.tree_util.tree_map(np.asarray,
                                        wf.trainer.host_params())
        wf.run()
        after = wf.trainer.host_params()
        blk = [n for n in before if "transformer_block" in n][0]
        np.testing.assert_array_equal(
            np.asarray(before[blk]["mha"]["wq"]),
            np.asarray(after[blk]["mha"]["wq"]))
        np.testing.assert_array_equal(
            np.asarray(before[blk]["w1"]), np.asarray(after[blk]["w1"]))
