"""Tests for topology layers (channel split/merge, resizable all2all,
stochastic pool-depool), InputJoiner/Avatar/Shell units, and the
foundation helpers (NumDiff, DeviceBenchmark, Watcher)."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from veles_tpu import prng  # noqa: E402
from veles_tpu.avatar import Avatar  # noqa: E402
from veles_tpu.benchmark import DeviceBenchmark, Watcher  # noqa: E402
from veles_tpu.input_joiner import InputJoiner  # noqa: E402
from veles_tpu.interaction import Shell  # noqa: E402
from veles_tpu.models.layers import make_layer  # noqa: E402
from veles_tpu.numpy_ext import NumDiff, interleave, roundup  # noqa: E402
from veles_tpu.units import TrivialUnit  # noqa: E402


class TestTopologyLayers:
    def test_channel_split_merge_roundtrip(self):
        split = make_layer({"type": "channel_splitter"})
        merge = make_layer({"type": "channel_merger"})
        shape = split.setup((4, 5, 3))
        assert shape == (3, 4, 5, 1)
        assert merge.setup(shape) == (4, 5, 3)
        x = jnp.asarray(np.random.RandomState(0).rand(2, 4, 5, 3),
                        jnp.float32)
        y = merge.apply(None, split.apply(None, x))
        np.testing.assert_allclose(np.asarray(y), np.asarray(x))

    def test_resizable_all2all_resize_preserves_overlap(self):
        layer = make_layer({"type": "resizable_all2all",
                            "output_sample_shape": 6})
        layer.setup((4,))
        prng.seed_all(5)
        params = layer.init_params(prng.get("t"))
        grown = layer.resize(params, 10, prng.get("t2"))
        assert grown["weights"].shape == (4, 10)
        assert layer.output_shape == (10,)
        np.testing.assert_allclose(np.asarray(grown["weights"][:, :6]),
                                   np.asarray(params["weights"]))
        np.testing.assert_allclose(np.asarray(grown["bias"][:6]),
                                   np.asarray(params["bias"]))
        shrunk = layer.resize(grown, 3, prng.get("t3"))
        assert shrunk["weights"].shape == (4, 3)
        np.testing.assert_allclose(np.asarray(shrunk["weights"]),
                                   np.asarray(params["weights"][:, :3]))

    def test_stochastic_pool_depool_shape_and_sparsity(self):
        layer = make_layer({"type": "stochastic_pooling_depooling",
                            "kx": 2, "ky": 2})
        assert layer.setup((4, 4, 2)) == (4, 4, 2)
        x = jnp.asarray(np.random.RandomState(1).rand(3, 4, 4, 2) + 0.1,
                        jnp.float32)
        y = np.asarray(layer.apply(None, x, train=True,
                                   key=jax.random.PRNGKey(0)))
        assert y.shape == (3, 4, 4, 2)
        # exactly one survivor per 2x2 window per channel, value from input
        win = y.reshape(3, 2, 2, 2, 2, 2)
        nonzero = (np.abs(win) > 0).sum(axis=(2, 4))
        assert (nonzero == 1).all()
        mask = np.abs(y) > 0
        np.testing.assert_allclose(y[mask], np.asarray(x)[mask])
        # inference is identity
        np.testing.assert_allclose(
            np.asarray(layer.apply(None, x, train=False)), np.asarray(x))

    def test_stochastic_pool_depool_ragged_edges_zeroed(self):
        layer = make_layer({"type": "stochastic_pooling_depooling",
                            "kx": 2, "ky": 2})
        assert layer.setup((5, 5, 1)) == (5, 5, 1)
        x = jnp.ones((1, 5, 5, 1), jnp.float32)
        y = np.asarray(layer.apply(None, x, train=True,
                                   key=jax.random.PRNGKey(1)))
        assert (y[:, 4, :, :] == 0).all() and (y[:, :, 4, :] == 0).all()


class TestJoinerAvatarShell:
    def test_input_joiner_concatenates_features(self):
        a = TrivialUnit(None, name="a")
        b = TrivialUnit(None, name="b")
        a.output = np.arange(6, dtype=np.float32).reshape(2, 3)
        b.output = np.arange(8, dtype=np.float32).reshape(2, 2, 2)
        joiner = InputJoiner(None)
        joiner.link_input(a).link_input(b)
        joiner.initialize()
        joiner.run()
        assert joiner.output.shape == (2, 7)
        assert joiner.output_sample_size == 7
        np.testing.assert_array_equal(joiner.output[0],
                                      [0, 1, 2, 0, 1, 2, 3])

    def test_input_joiner_rejects_mismatched_batch(self):
        a = TrivialUnit(None, name="a")
        b = TrivialUnit(None, name="b")
        a.output = np.zeros((2, 3), np.float32)
        b.output = np.zeros((3, 3), np.float32)
        joiner = InputJoiner(None).link_input(a).link_input(b)
        joiner.initialize()
        with pytest.raises(ValueError):
            joiner.run()

    def test_avatar_clones_and_tracks(self):
        src = TrivialUnit(None, name="src")
        src.metric = 1.0
        av = Avatar(None, source=src, attrs=["metric"])
        av.initialize()
        assert av.metric == 1.0
        src.metric = 2.0
        av.run()
        assert av.metric == 2.0

    def test_avatar_deep_copies(self):
        src = TrivialUnit(None, name="src")
        src.buf = np.zeros(3)
        av = Avatar(None, source=src, attrs=["buf"], deep=True)
        av.initialize()
        src.buf[0] = 7
        assert av.buf[0] == 0

    def test_shell_injectable_console(self):
        seen = {}
        sh = Shell(None, console=lambda env: seen.update(env))
        sh.run()
        assert seen["shell"] is sh
        assert "wf" in seen


class TestFoundationHelpers:
    def test_roundup_interleave(self):
        assert roundup(5, 8) == 8
        assert roundup(16, 8) == 16
        out = interleave(np.array([[1, 3], [2, 4]]))
        np.testing.assert_array_equal(out, [1, 2, 3, 4])

    def test_numdiff(self):
        d = NumDiff(threshold=1e-3)
        d.check(np.zeros(4), np.zeros(4))
        assert d.ok
        d.check(np.zeros(4), np.array([0, 0, 0.01, 0]))
        assert not d.ok and d.count == 1
        assert d.max_index == (2,)
        with pytest.raises(AssertionError):
            d.assert_ok()

    def test_device_benchmark(self):
        b = DeviceBenchmark(None, size=64, repeats=1)
        b.run()
        assert b.seconds > 0 and b.computing_power > 0 and b.gflops > 0

    def test_watcher(self):
        keep = jnp.ones((16, 16))
        w = Watcher()
        per_device = w.snapshot()
        assert all(v >= 0 for v in per_device.values())
        assert w.peak >= keep.nbytes
        assert isinstance(Watcher.runtime_stats(), dict)


class TestGraphSurgeryAndHttpImport:
    def test_change_unit_relinks(self):
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="surgery")
        a = TrivialUnit(wf, name="a")
        b = TrivialUnit(wf, name="b")
        c = TrivialUnit(wf, name="c")
        b.link_from(a)
        c.link_from(b)
        d = TrivialUnit(wf, name="d")
        wf.change_unit(b, d)
        assert a in d.links_from and d in a.links_to
        assert d in c.links_from and b not in c.links_from
        assert not b.links_from and not b.links_to

    def test_snapshot_import_over_http(self, tmp_path, monkeypatch):
        monkeypatch.delenv("VELES_ALLOW_REMOTE_SNAPSHOT", raising=False)
        import gzip
        import pickle
        import threading
        from functools import partial
        from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer
        from veles_tpu.services.snapshotter import SnapshotterBase

        with gzip.open(tmp_path / "snap.pickle.gz", "wb") as f:
            pickle.dump({"epoch": 9}, f)
        handler = partial(SimpleHTTPRequestHandler,
                          directory=str(tmp_path))
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            url = "http://127.0.0.1:%d/snap.pickle.gz" % \
                httpd.server_address[1]
            with pytest.raises(PermissionError):
                SnapshotterBase.import_(url)   # remote needs opt-in
            state = SnapshotterBase.import_(url, allow_remote=True)
            assert state["epoch"] == 9
            import hashlib
            good = hashlib.sha256(
                (tmp_path / "snap.pickle.gz").read_bytes()).hexdigest()
            state = SnapshotterBase.import_(url, allow_remote=True,
                                            expected_sha256=good)
            assert state["epoch"] == 9
            with pytest.raises(ValueError):
                SnapshotterBase.import_(url, allow_remote=True,
                                        expected_sha256="0" * 64)
        finally:
            httpd.shutdown()
            httpd.server_close()


class TestManhole:
    def test_attach_and_evaluate(self, tmp_path):
        """r2: the reference's manhole — a live REPL over a unix socket
        (code execution gated by 0600 socket perms)."""
        import socket
        import stat

        from veles_tpu.interaction import Manhole
        path = str(tmp_path / "mh.sock")
        mh = Manhole(path, scope={"x": 41}).start()
        try:
            assert stat.S_IMODE(os.stat(path).st_mode) == 0o600
            c = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            c.connect(path)
            c.settimeout(5)
            f = c.makefile("rw", encoding="utf-8", newline="\n")

            def read_to_prompt():
                out = ""
                while not out.endswith(">>> "):
                    chunk = f.read(1)
                    if not chunk:
                        break
                    out += chunk
                return out

            read_to_prompt()                 # banner
            f.write("x + 1\n")
            f.flush()
            assert "42" in read_to_prompt()
            f.write("y = 10\n")              # state persists per session
            f.flush()
            read_to_prompt()
            f.write("y * 2\n")
            f.flush()
            assert "20" in read_to_prompt()
            c.close()
        finally:
            mh.stop()
        assert not os.path.exists(path)


class TestThreadRouter:
    def test_routes_only_the_session_thread(self):
        """Manhole output capture must not hijack other threads' stdout
        (the training loop keeps printing while a session evaluates)."""
        import io
        import threading

        from veles_tpu.interaction import _ThreadRouter
        orig = io.StringIO()
        router = _ThreadRouter(orig)
        session = io.StringIO()
        router.write("train-before ")

        def worker():
            router.route(session)
            router.write("session-output")
            router.unroute()
            router.write(" worker-after")

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        router.write("train-after")
        assert session.getvalue() == "session-output"
        assert orig.getvalue() == "train-before  worker-aftertrain-after"
