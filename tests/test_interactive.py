"""``--interactive`` REPL end-to-end, driven through a real pty:
construct → training runs in the background scheduler thread → inspect
live weights from the prompt → stop → clean exit (ref
Main(interactive=True), veles/__main__.py:380-394, and the background
reactor thread, launcher.py:556-562)."""

import os
import sys

import pytest

pexpect = pytest.importorskip("pexpect")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_interactive_repl_inspects_live_workflow():
    env = dict(os.environ, JAX_PLATFORMS="cpu", VELES_PLAIN_REPL="1",
               TERM="dumb")
    child = pexpect.spawn(
        sys.executable,
        ["-m", "veles_tpu", "samples/digits_mlp.py", "--backend", "cpu",
         "--interactive", "--random-seed", "5",
         "--config-list", "root.digits.max_epochs=100000"],
        cwd=REPO, env=env, encoding="utf-8", timeout=240)
    try:
        # banner + first prompt: the workflow is built and the scheduler
        # thread is already training behind the prompt
        child.expect_exact(">>> ")
        # the live param tree is reachable and has the digits-MLP shape
        child.sendline("ln = sorted(weights())[0]")
        child.expect_exact(">>> ")
        child.sendline("print('SHAPE', weights(ln)['weights'].shape)")
        child.expect(r"SHAPE \(64, 60\)")
        child.expect_exact(">>> ")
        # liveness probe: with max_epochs=100000 the scheduler must
        # still be running while we poke at it
        child.sendline("print('ALIVE', status())")
        child.expect(r"scheduler=running")
        child.expect(r"ALIVE True")
        child.expect_exact(">>> ")
        # mid-training inspection actually observed training progress:
        # epoch counter moved past 0
        child.sendline("print('EPOCH', wf.loader.epoch_number > 0)")
        child.expect(r"EPOCH (True|False)")
        child.expect_exact(">>> ")
        child.sendline("stop()")
        child.expect("scheduler stopped")
        child.expect_exact(">>> ")
        child.sendline("print('DEAD', status())")
        child.expect(r"scheduler=done")
        child.expect_exact(">>> ")
        child.sendline("exit()")
        child.expect(pexpect.EOF)
    finally:
        child.close(force=True)
    assert child.exitstatus == 0, child.before
