"""Plugin discovery, numpy JSON, timeit, DB snapshotter, forge CLI,
computing_power."""

import json
import os
import sys

import numpy as np
import pytest

from veles_tpu import json_encoders, plugins, timeit2
from veles_tpu.services.snapshotter import DBSnapshotter, SnapshotterBase


class TestJsonEncoders:
    def test_numpy_types(self):
        s = json_encoders.dumps({"i": np.int64(3), "f": np.float32(0.5),
                                 "b": np.bool_(True),
                                 "a": np.arange(3)})
        assert json.loads(s) == {"i": 3, "f": 0.5, "b": True, "a": [0, 1, 2]}

    def test_jax_array(self):
        jax = pytest.importorskip("jax")
        import jax.numpy as jnp
        assert json.loads(json_encoders.dumps(jnp.ones(2))) == [1.0, 1.0]


class TestTimeit:
    def test_returns_result_and_seconds(self):
        result, sec = timeit2.timeit(lambda a, b: a + b, 2, 3)
        assert result == 5 and sec >= 0


class TestPlugins:
    def test_marker_discovery(self, tmp_path, monkeypatch):
        pkg = tmp_path / "my_veles_plugin"
        pkg.mkdir()
        (pkg / "__init__.py").write_text("LOADED = True\n")
        (pkg / ".veles_tpu").write_text("")
        monkeypatch.syspath_prepend(str(tmp_path))
        mods = plugins.discover(extra_paths=(str(tmp_path),))
        assert "my_veles_plugin" in mods
        assert mods["my_veles_plugin"].LOADED

    def test_discover_idempotent(self):
        assert plugins.discover() is plugins.discover()


class TestDBSnapshotter:
    def test_export_import_roundtrip(self, tmp_path):
        class FakeTrainer:
            velocity = {}
            _step_counter = 7
            class_stats = [{}, {}, {}]   # device accumulators (empty)

            def flush(self):
                pass                     # no fused steps pending

            def host_params(self):
                return {"l0": {"weights": np.ones((2, 2))}}

            def host_velocity(self):
                return {}

        class FakeLoader:
            state = {"pos": 3}
            epoch_number = 2

        snap = DBSnapshotter.__new__(DBSnapshotter)
        snap.dsn = str(tmp_path / "snaps.sqlite")
        snap.prefix = "t"
        snap.async_write = False
        snap._writer = None
        snap.keep_last = 0
        snap.commit_retries = 1
        snap.retry_backoff = 0.0
        snap.manifest = True
        snap.trainer = FakeTrainer()
        snap.loader = FakeLoader()
        snap.decision = None
        snap._logger_ = None
        import logging
        snap._logger_ = logging.getLogger("test")
        dest = snap.export()
        assert "snaps.sqlite" in dest
        state = DBSnapshotter.import_db(snap.dsn)
        assert state["epoch"] == 2
        assert state["step_counter"] == 7
        np.testing.assert_array_equal(state["params"]["l0"]["weights"],
                                      np.ones((2, 2)))
        with pytest.raises(KeyError):
            DBSnapshotter.import_db(snap.dsn, prefix="other")


class TestForgeCLI:
    def test_upload_list_fetch_via_cli(self, tmp_path, capsys):
        import zipfile
        from veles_tpu.forge import ForgeServer
        from veles_tpu.forge.client import main as forge_main
        pkg = str(tmp_path / "m.zip")
        with zipfile.ZipFile(pkg, "w") as zf:
            zf.writestr("contents.json", "{}")
        srv = ForgeServer(str(tmp_path / "store")).start()
        try:
            assert forge_main(["upload", "--url", srv.url, "m", pkg,
                               "1.0"]) == 0
            assert forge_main(["list", "--url", srv.url]) == 0
            out = capsys.readouterr().out
            assert '"m"' in out
            dest = str(tmp_path / "got.zip")
            assert forge_main(["fetch", "--url", srv.url, "m", dest]) == 0
            assert os.path.exists(dest)
        finally:
            srv.stop()


class TestComputingPower:
    def test_cached_power(self):
        pytest.importorskip("jax")
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="power-test")
        p1 = wf.computing_power()
        assert p1 > 0
        assert wf.computing_power() == p1   # cache hit inside 120 s
