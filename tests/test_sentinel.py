"""Numeric-fault survival tier (services.sentinel, PR 13): the in-jit
health probes fused into the staged train step, the skip-update /
rollback-and-replay / escalate response ladder, the commit health
stamps + healthy-preferring agreement, the supervisor/pod numerics
valves, and the reject_nonfinite surfacing — the in-process flavors of
the tools/numerics_chaos.py gate (the CI ``numerics-chaos`` job runs
the full subprocess version)."""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from sklearn.datasets import load_digits

from veles_tpu import prng, telemetry
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.services import sentinel
from veles_tpu.services.sentinel import (NumericFaultError, apply_probes,
                                         init_health, skip_steps_array)
from veles_tpu.services.snapshotter import (SnapshotNonFiniteError,
                                            SnapshotterBase,
                                            agree_commits, commit_meta,
                                            scan_commits, state_manifest)
from veles_tpu.services.supervisor import Supervisor, classify_exit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def cfg_guard():
    """Snapshot + restore the sentinel/chaos config namespaces — every
    test here may retune the ladder."""
    saved = {ns: getattr(root.common, ns).as_dict()
             for ns in ("sentinel", "chaos")}
    yield root.common
    for ns, vals in saved.items():
        node = getattr(root.common, ns)
        for k in [k for k in node.__dict__ if k != "_path_"]:
            delattr(node, k)
        node.update(vals)


def _probe_cfg(**over):
    cfg = {"enabled": True, "spike_zscore": 6.0, "spike_warmup": 8,
           "update_norm_limit": 1e6, "ewma_decay": 0.9,
           "max_skip_steps": 8, "force_skip_steps": ()}
    cfg.update(over)
    return cfg


def _trees(g_val=0.01, upd=0.001):
    params = {"l": {"weights": jnp.ones((4, 3), jnp.float32)}}
    grads = {"l": {"weights": jnp.full((4, 3), g_val, jnp.float32)}}
    new_params = {"l": {"weights": jnp.full((4, 3), 1.0 - upd,
                                            jnp.float32)}}
    return params, grads, new_params


def _run_probe(health, loss, step=5, skips=(), cfg=None, **tree_kw):
    params, grads, new_params = _trees(**tree_kw)
    return apply_probes(
        health, jnp.float32(loss), grads, new_params, params,
        jnp.int32(step), jnp.asarray(skip_steps_array(skips, 8)),
        cfg or _probe_cfg())


def _counts(health):
    return {k: float(health[k])
            for k in sentinel._COUNTER_KEYS}


# =====================================================================
# the anomaly-taxonomy matrix: each probe kind fires exactly once on a
# seeded hazard, and nothing else fires with it
# =====================================================================
class TestProbeTaxonomy:
    def _warm(self, n=10, loss=1.0):
        h = init_health()
        for i in range(n):
            h, ok = _run_probe(h, loss, step=i + 1)
            assert bool(ok)
        return h

    def test_clean_step_updates_ewma_and_applies(self):
        h = self._warm()
        assert float(h["obs"]) == 10
        assert float(h["anomalies"]) == 0
        # geometric approach toward the constant loss: 1 - d^n
        assert abs(float(h["ewma_mean"]) - (1.0 - 0.9 ** 10)) < 1e-5

    @pytest.mark.parametrize("kind,kw", [
        ("nonfinite_loss", {"loss": np.nan}),
        ("nonfinite_grad", {"loss": 1.0, "g_val": np.nan}),
        ("update_explosion", {"loss": 1.0, "upd": 1e5}),
        ("loss_spike", {"loss": 1e6}),
    ])
    def test_kind_fires_exactly_once(self, kind, kw):
        cfg = _probe_cfg(update_norm_limit=10.0)
        h = self._warm()
        mean_before = float(h["ewma_mean"])
        loss = kw.pop("loss")
        h, ok = _run_probe(h, loss, step=99, cfg=cfg, **kw)
        assert not bool(ok)
        counts = _counts(h)
        assert counts[kind] == 1, counts
        assert counts["anomalies"] == 1
        assert counts["skipped"] == 1
        assert counts["policy_skips"] == 0
        for other in sentinel.ANOMALY_KINDS:
            if other != kind:
                assert counts[other] == 0, (other, counts)
        assert int(h["first_bad_step"]) == 99
        assert int(h["last_bad_step"]) == 99
        # the poisoned observation must NOT advance the EWMA baseline
        assert float(h["ewma_mean"]) == mean_before

    def test_policy_skip_is_never_an_anomaly(self):
        """A step on the skip list gates its update but counts zero
        anomalies even when its numerics ARE poisoned — otherwise a
        step-keyed fault would re-strike on every replay and the
        ladder could never converge."""
        h = self._warm()
        h, ok = _run_probe(h, 1.0, step=42, skips=(42,), g_val=np.nan)
        assert not bool(ok)
        counts = _counts(h)
        assert counts["policy_skips"] == 1
        assert counts["anomalies"] == 0
        assert counts["nonfinite_grad"] == 0
        assert int(h["first_bad_step"]) == sentinel.NO_BAD_STEP

    def test_spike_needs_warmup(self):
        h = init_health()
        h, ok = _run_probe(h, 1e9, step=1)   # cold stats: no spike
        assert bool(ok)
        assert _counts(h)["loss_spike"] == 0

    def test_dominant_kind_priority(self):
        assert sentinel.dominant_kind(
            {"loss_spike": 1, "nonfinite_grad": 2}) == "nonfinite_grad"
        assert sentinel.dominant_kind({"loss_spike": 3}) == "loss_spike"
        assert sentinel.dominant_kind({}) is None


# =====================================================================
# workload fixtures (digits MLP = the MNIST proxy, tiny conv = the
# CIFAR proxy, tiny transformer LM)
# =====================================================================
def _digits():
    d = load_digits()
    return ((d.data / 16.0).astype(np.float32),
            d.target.astype(np.int32))


def _mlp_wf(snap_dir=None, epochs=4, seed=7, name="sent-mlp",
            interval=1):
    prng.seed_all(seed)
    x, y = _digits()
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=64,
                             class_lengths=[0, 297, 1500])
    snap = None if snap_dir is None else {"directory": str(snap_dir),
                                          "interval": interval}
    return StandardWorkflow(
        layers=[{"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1, "gradient_moment": 0.9}],
        loader=loader, decision_config={"max_epochs": epochs},
        snapshotter_config=snap, name=name)


# =====================================================================
# rung 2: rollback + replay bit-identical to the golden skip-batch run
# =====================================================================
class TestRollbackReplayExactness:
    NAN_STEP = 30   # epoch 2 of the digits MLP (24 train steps/epoch)

    def _run(self, tmp_path, leg, force_skip=None, nan_step=None):
        root.common.sentinel.force_skip_steps = tuple(force_skip or ())
        root.common.chaos.nan_grads_step = nan_step
        wf = _mlp_wf(tmp_path / leg, epochs=4, name="sent-exact")
        wf.initialize()
        wf.run()
        final = os.path.realpath(
            str(tmp_path / leg / "sent-exact_current"))
        return wf, final

    def test_transient_nan_recovers_bit_exact(self, tmp_path,
                                              cfg_guard):
        golden_wf, golden = self._run(tmp_path, "golden",
                                      force_skip=(self.NAN_STEP,))
        assert golden_wf.sentinel.rollbacks == 0
        assert float(
            golden_wf.trainer._health_host["policy_skips"]) == 1

        chaos_wf, chaos = self._run(tmp_path, "chaos",
                                    nan_step=self.NAN_STEP)
        # exactly ONE rollback, to a commit stamped healthy, with the
        # poisoned step armed on the skip list
        assert chaos_wf.sentinel.rollbacks == 1
        rec = chaos_wf.sentinel.history[0]
        assert rec["anomaly"] == "nonfinite_grad"
        assert rec["skip_step"] == self.NAN_STEP
        assert rec["quarantined"]   # the unhealthy commit left the ring
        assert any(n.endswith(".corrupt")
                   for n in os.listdir(tmp_path / "chaos"))
        # THE guarantee: params + optimizer slots + PRNG counters +
        # loader order + decision bookkeeping all bit-identical to the
        # golden run that skipped that batch (threshold 0)
        from veles_tpu.scripts.compare_snapshots import diff_report
        rep = diff_report(golden, chaos, threshold=0.0)
        assert rep["identical"], rep["diffs"][:5]
        # the replayed run's final commit is healthy again
        scan = scan_commits(str(tmp_path / "chaos"), "sent-exact")
        final_name = os.path.basename(chaos)
        assert scan[final_name]["health"] == "healthy"

    def test_persistent_nan_escalates_with_diagnosis(self, tmp_path,
                                                     cfg_guard):
        root.common.chaos.nan_grads_from = self.NAN_STEP
        root.common.sentinel.rollbacks_to_escalate = 1
        wf = _mlp_wf(tmp_path, epochs=4, name="sent-esc")
        wf.initialize()
        with pytest.raises(NumericFaultError) as exc:
            wf.run()
        assert exc.value.kind == "nonfinite_grad"
        assert "first bad step" in str(exc.value)
        assert wf.sentinel.rollbacks == 1
        # params stayed finite throughout (rung 1 protected them)
        for leaf in jax.tree_util.tree_leaves(
                wf.trainer.host_params()):
            assert np.isfinite(leaf).all()

    def test_final_epoch_rollback_still_replays(self, tmp_path,
                                                cfg_guard):
        """An anomaly in the LAST epoch must not end the run on the
        poisoned timeline's latched stop condition — the rollback
        clears it and the replay still converges bit-exact."""
        step = 80   # epoch 4 of 4 (24 train steps/epoch)
        _, golden = self._run(tmp_path, "golden", force_skip=(step,))
        chaos_wf, chaos = self._run(tmp_path, "chaos", nan_step=step)
        assert chaos_wf.sentinel.rollbacks == 1
        from veles_tpu.scripts.compare_snapshots import diff_report
        rep = diff_report(golden, chaos, threshold=0.0)
        assert rep["identical"], rep["diffs"][:5]

    def test_noncommitting_epoch_anomaly_next_commit_healthy(
            self, tmp_path, cfg_guard):
        """With snapshot interval > 1 the anomalous epoch may never
        commit; the rollback must drain the commit-verdict delta so
        the first CLEAN post-replay commit is not stamped unhealthy
        (which would make later rollbacks skip perfectly good
        state)."""
        root.common.chaos.nan_grads_step = 54   # epoch 3: no commit
        wf = _mlp_wf(tmp_path, epochs=4, name="sent-int2", interval=2)
        wf.initialize()
        wf.run()
        assert wf.sentinel.rollbacks == 1
        scan = scan_commits(str(tmp_path), "sent-int2")
        healths = {n: e["health"] for n, e in scan.items()}
        assert healths and all(h == "healthy"
                               for h in healths.values()), healths

    def test_transient_without_snapshotter_is_contained(self,
                                                        cfg_guard):
        """Rung 1 already protected the state, so a run that CANNOT
        roll back (no snapshotter) keeps training on a transient
        anomaly instead of dying — only persistence escalates."""
        root.common.chaos.nan_grads_step = self.NAN_STEP
        wf = _mlp_wf(epochs=3, name="sent-contain")   # no snapshotter
        wf.initialize()
        wf.run()                                      # completes
        assert wf.sentinel.rollbacks == 0
        assert wf.sentinel.history and \
            wf.sentinel.history[0].get("contained") is True
        for leaf in jax.tree_util.tree_leaves(
                wf.trainer.host_params()):
            assert np.isfinite(leaf).all()

    def test_persistent_without_snapshotter_still_escalates(
            self, cfg_guard):
        root.common.chaos.nan_grads_from = self.NAN_STEP
        root.common.sentinel.rollbacks_to_escalate = 1
        wf = _mlp_wf(epochs=4, name="sent-contain-esc")
        wf.initialize()
        with pytest.raises(NumericFaultError):
            wf.run()
        assert wf.sentinel.rollbacks == 0
        assert all(r.get("contained") for r in wf.sentinel.history)

    def test_skip_list_overflow_refuses_inexact_replay(self,
                                                       cfg_guard):
        wf = _mlp_wf(epochs=1, name="sent-ovf")
        wf.initialize()
        with pytest.raises(ValueError, match="skip list overflow"):
            wf.trainer.add_skip_steps(range(100, 200))


# =====================================================================
# the model sweep stays silent: no false positives on healthy training
# =====================================================================
class TestModelSweepSilent:
    def _assert_silent(self, wf):
        wf.initialize()
        wf.run()
        h = {k: float(v) for k, v in
             jax.device_get(wf.trainer.health).items()}
        assert h["anomalies"] == 0, h
        assert h["skipped"] == 0, h
        assert wf.sentinel is not None and wf.sentinel.rollbacks == 0

    def test_digits_mlp_silent(self, cfg_guard):
        self._assert_silent(_mlp_wf(epochs=3, name="silent-mlp"))

    def test_conv_stack_silent(self, cfg_guard):
        prng.seed_all(9)
        x, y = _digits()
        loader = FullBatchLoader(
            None, data=x.reshape(-1, 8, 8, 1), labels=y,
            minibatch_size=64, class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[{"type": "conv_relu", "n_kernels": 8, "kx": 3,
                     "ky": 3, "learning_rate": 0.03},
                    {"type": "max_pooling", "kx": 2, "ky": 2},
                    {"type": "softmax", "output_sample_shape": 10,
                     "learning_rate": 0.03}],
            loader=loader, decision_config={"max_epochs": 2},
            name="silent-conv")
        self._assert_silent(wf)

    @pytest.mark.slow
    def test_transformer_lm_silent(self, cfg_guard):
        prng.seed_all(43)
        from veles_tpu.models import zoo
        r = np.random.RandomState(1)
        n, t, vocab = 256, 16, 17
        phase = r.randint(0, 5, n)
        tokens = ((np.arange(t)[None, :] * 3 + phase[:, None]) % vocab
                  ).astype(np.int32)
        loader = FullBatchLoader(None, data=tokens, labels=tokens,
                                 minibatch_size=64,
                                 class_lengths=[0, 64, 192])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=32,
                                      n_heads=4, n_layers=1, lr=0.005),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 2}, name="silent-lm")
        self._assert_silent(wf)


# =====================================================================
# commit health stamps + healthy-preferring agreement
# =====================================================================
class TestHealthStamp:
    def test_commit_meta_and_manifest_carry_health(self):
        state = {"epoch": 3, "health": "unhealthy:nonfinite_grad",
                 "params": {"l": {"w": np.zeros(2)}}}
        assert commit_meta(state)["health"] == \
            "unhealthy:nonfinite_grad"
        assert state_manifest(state)["health"] == \
            "unhealthy:nonfinite_grad"
        assert "health" not in commit_meta({"epoch": 1})

    def test_scan_commits_surfaces_health_without_unpickling(
            self, tmp_path):
        from test_supervisor import _StateSnap, _state
        st = dict(_state(), health="unhealthy:loss_spike")
        snap = _StateSnap(st, directory=str(tmp_path), prefix="h",
                          compression="gz")
        snap.export()
        scan = scan_commits(str(tmp_path), "h")
        assert len(scan) == 1
        entry = next(iter(scan.values()))
        assert entry["health"] == "unhealthy:loss_spike"
        assert entry["valid"] is True

    def _reports(self, health_new):
        def entry(name, health, mtime):
            return {"path": name, "epoch": int(name[-1]),
                    "mtime": mtime, "valid": True, "health": health}
        reports = {}
        for host in (0, 1):
            reports[host] = {
                "wf_1": entry("wf_1", "healthy", 100.0),
                "wf_2": entry("wf_2", health_new, 200.0),
            }
        return reports

    def test_agreement_prefers_older_healthy_over_newer_unhealthy(
            self):
        agreed, detail = agree_commits(
            self._reports("unhealthy:nonfinite_grad"))
        assert agreed == "wf_1"
        assert detail["wf_2"]["healthy"] is False

    def test_agreement_takes_newest_when_all_healthy(self):
        agreed, _ = agree_commits(self._reports("healthy"))
        assert agreed == "wf_2"

    def test_agreement_falls_back_to_unhealthy_when_nothing_else(self):
        reports = self._reports("unhealthy:loss_spike")
        for rep in reports.values():
            del rep["wf_1"]
        agreed, _ = agree_commits(reports)
        assert agreed == "wf_2"   # better a suspect commit than none

    def test_newest_healthy_skips_unhealthy_and_invalid(self):
        from veles_tpu.services.sentinel import HealthSentinel
        scan = {
            "wf_1": {"epoch": 1, "mtime": 1.0, "valid": True,
                     "health": "healthy"},
            "wf_2": {"epoch": 2, "mtime": 2.0, "valid": True,
                     "health": None},          # legacy: trusted
            "wf_3": {"epoch": 3, "mtime": 3.0, "valid": True,
                     "health": "unhealthy:nonfinite_grad"},
            "wf_4": {"epoch": 4, "mtime": 4.0, "valid": False,
                     "health": "healthy"},
        }
        assert HealthSentinel._newest_healthy(scan) == "wf_2"


# =====================================================================
# classification + the supervisor / pod valves
# =====================================================================
_CHILD_NUMERICS_CRASH = """\
import json, os, sys, time
blackbox, progress = sys.argv[1], sys.argv[2]
d = os.path.join(blackbox, "crashdump-%d" % int(time.time() * 1e6))
os.makedirs(d)
with open(os.path.join(d, "events.jsonl"), "w") as f:
    f.write(json.dumps({"kind": "sentinel.giveup",
                        "anomaly": "nonfinite_grad",
                        "signature": "nonfinite_grad"}) + "\\n")
with open(os.path.join(d, "meta.json"), "w") as f:
    json.dump({"reason": "excepthook",
               "error": {"type": "NumericFaultError",
                         "message": "numeric fault"}}, f)
# every life ADVANCES a checkpoint-progress marker: the numerics valve
# must give up anyway (replay commits do not excuse divergence)
open(os.path.join(progress, "c-%d" % time.time_ns()), "w").write("x")
sys.exit(1)
"""


class TestNumericsClassification:
    def _dump(self, tmp_path, events, meta=None):
        d = tmp_path / ("crashdump-%d" % time.time_ns())
        os.makedirs(d)
        with open(d / "events.jsonl", "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        if meta is not None:
            with open(d / "meta.json", "w") as f:
                json.dump(meta, f)

    def test_classify_exit_reads_sentinel_giveup(self, tmp_path):
        self._dump(tmp_path,
                   [{"kind": "step"},
                    {"kind": "sentinel.giveup",
                     "anomaly": "loss_spike",
                     "signature": "loss_spike"}],
                   meta={"error": {"type": "NumericFaultError",
                                   "message": "boom"}})
        kind, sig = classify_exit(1, str(tmp_path), since=0.0)
        assert kind == "numerics:loss_spike"
        assert sig == "numerics:loss_spike"

    def test_fault_injection_still_wins(self, tmp_path):
        self._dump(tmp_path, [{"kind": "fault.injected"},
                              {"kind": "sentinel.giveup",
                               "anomaly": "loss_spike"}])
        kind, _ = classify_exit(1, str(tmp_path), since=0.0)
        assert kind == "fault-injection"

    def test_supervisor_numerics_valve_ignores_progress(self,
                                                        tmp_path):
        """deterministic_limit identical numerics give-ups end the run
        even though every life advanced a checkpoint — replay commits
        must not excuse identical divergence."""
        from test_supervisor import _script
        bb = tmp_path / "bb"
        progress = tmp_path / "snaps"
        os.makedirs(bb)
        os.makedirs(progress)
        child = _script(tmp_path, _CHILD_NUMERICS_CRASH)
        sup = Supervisor(
            [sys.executable, child, str(bb), str(progress)],
            max_restarts=50, window_seconds=600,
            backoff_base_ms=1, backoff_max_ms=2,
            deterministic_limit=2, blackbox_dir=str(bb),
            progress_paths=[str(progress)], install_signals=False)
        assert sup.run() == 1
        assert sup.spawn_count == 2
        assert sup.giveup_reason == "numerics"
        assert "deterministically" in sup.giveup_diagnosis
        assert sup.restarts["numerics"] == 2
        assert all(h["kind"] == "numerics:nonfinite_grad"
                   for h in sup.history)

    def test_pod_valves_sticky_signature(self):
        from veles_tpu.services.podmaster import PodValves
        valves = PodValves(max_restarts=50, window_seconds=600,
                           deterministic_limit=2)
        sig = ("0=numerics:nonfinite_grad",)
        # progressed rounds normally RESET the deterministic counter...
        assert valves.admit(1.0, sig, progressed=True) == "respawn"
        assert valves.admit(2.0, sig, progressed=True) == "respawn"
        assert valves.admit(3.0, sig, progressed=True) == "respawn"
        # ...but numerics rounds judge the signature regardless
        valves = PodValves(max_restarts=50, window_seconds=600,
                           deterministic_limit=2)
        assert valves.admit(1.0, sig, progressed=True,
                            sticky_signature=True) == "respawn"
        assert valves.admit(2.0, sig, progressed=True,
                            sticky_signature=True) == \
            "deterministic-bug"


# =====================================================================
# satellite 1: the reject_nonfinite valve is SURFACED, not just thrown
# =====================================================================
class TestNonfiniteSurfacing:
    def test_refused_commit_counts_and_degrades_health(self, tmp_path):
        from veles_tpu.telemetry import health as health_mod
        from test_supervisor import _StateSnap, _state
        saved = (health_mod._state["nonfinite_commits"],
                 health_mod._state["nonfinite_last"])
        try:
            health_mod._state["nonfinite_commits"] = 0
            health_mod._state["nonfinite_last"] = None
            st = _state()
            st["params"]["l0"]["weights"] = np.array([1.0, np.nan])
            snap = _StateSnap(st, directory=str(tmp_path), prefix="nf")
            counter = telemetry.registry.counter(
                "veles_snapshot_nonfinite_total",
                "checkpoint commits refused by the "
                "reject_nonfinite poison valve")
            before = counter.value()
            with pytest.raises(SnapshotNonFiniteError):
                snap.export()
            assert counter.value() == before + 1
            status = health_mod.status()
            assert status["degraded"] is True
            assert status["snapshot_nonfinite"]["count"] == 1
            assert status["snapshot_nonfinite"]["last"]["prefix"] == \
                "nf"
            # the /api/health payload carries it end to end
            from veles_tpu.services.web_status import WebStatusServer
            web = WebStatusServer.__new__(WebStatusServer)
            import threading
            web._lock = threading.Lock()
            web._serving = None
            assert web.health_status()["degraded"] is True
        finally:
            (health_mod._state["nonfinite_commits"],
             health_mod._state["nonfinite_last"]) = saved

    def test_healthy_process_not_degraded(self):
        from veles_tpu.telemetry import health as health_mod
        saved = health_mod._state["nonfinite_commits"]
        try:
            health_mod._state["nonfinite_commits"] = 0
            assert health_mod.status()["degraded"] in (False,)
        finally:
            health_mod._state["nonfinite_commits"] = saved


# =====================================================================
# satellite 2: rollback/replay reads as PROGRESS, never as a hang
# =====================================================================
class TestRollbackIsProgress:
    def test_rollback_notes_progress_for_watchdog_and_pod_latch(
            self, tmp_path, cfg_guard):
        from veles_tpu.services.podmaster import classify_stall
        from veles_tpu.telemetry import health as health_mod
        # commit a healthy ring first
        wf = _mlp_wf(tmp_path, epochs=2, name="sent-prog")
        wf.initialize()
        wf.run()
        # stale the liveness clock, then roll back directly
        health_mod._state["last_progress"] = \
            time.monotonic() - 10_000.0
        pending = {"anomaly": "nonfinite_grad", "class": 2,
                   "deltas": {"nonfinite_grad": 1, "anomalies": 1},
                   "first_bad_step": 30, "last_bad_step": 30}
        wf.sentinel._rollback(pending)
        age = health_mod.last_progress_age()
        assert age is not None and age < 5.0, \
            "rollback did not note progress — a hang watchdog would " \
            "have tripped"
        # the pod master's collective-hang latch sees the same signal:
        # fresh progress_ts on every host -> no hang verdict
        now = time.time()
        hosts = {h: {"heartbeat_ts": now, "progress_ts": now,
                     "worker_alive": True} for h in (0, 1)}
        assert classify_stall(now, hosts, hang_seconds=300,
                              stale_after=10.0) is None
        assert wf.sentinel.rollbacks == 1
        assert wf.trainer._skip_steps[0] == 30


# =====================================================================
# the ladder's strike/escalation accounting (host side, no training)
# =====================================================================
class TestLadderAccounting:
    def _sentinel(self, strikes=2, escalate=3):
        from veles_tpu.services.sentinel import HealthSentinel
        s = HealthSentinel.__new__(HealthSentinel)
        s.strikes_to_rollback = strikes
        s.rollbacks_to_escalate = escalate
        s.rollback_enabled = True
        s.strikes = 0
        s.rollbacks = 0
        s.same_signature_rollbacks = 0
        s.last_signature = None
        s._seen = {k: 0.0 for k in sentinel._COUNTER_KEYS}
        s._pending = None
        s.history = []
        s.snapshotter = object()   # rollback branch reachable
        return s

    def test_observe_sweep_deltas_and_latch(self):
        s = self._sentinel()

        class _T:
            def reset_health_marks(self):
                pass

        s.trainer = _T()
        h = {k: 0.0 for k in sentinel._COUNTER_KEYS}
        h.update(first_bad_step=float(sentinel.NO_BAD_STEP),
                 last_bad_step=-1.0)
        assert s.observe_sweep(2, {}, h) is None
        h2 = dict(h, anomalies=2.0, nonfinite_grad=2.0,
                  first_bad_step=31.0, last_bad_step=33.0)
        pending = s.observe_sweep(2, {}, h2)
        assert pending["anomaly"] == "nonfinite_grad"
        assert pending["first_bad_step"] == 31
        # same cumulative counts again: no NEW anomalies, no latch
        s._pending = None
        assert s.observe_sweep(2, {}, h2) is None

    def test_strikes_to_rollback_threshold(self, monkeypatch):
        s = self._sentinel(strikes=2)
        rolled = []
        monkeypatch.setattr(
            type(s), "_rollback", lambda self, p: rolled.append(p))
        s._pending = {"anomaly": "loss_spike", "first_bad_step": 5,
                      "deltas": {}}
        s.run()
        assert not rolled and s.strikes == 1
        s._pending = {"anomaly": "loss_spike", "first_bad_step": 6,
                      "deltas": {}}
        s.run()
        assert len(rolled) == 1 and s.strikes == 0
