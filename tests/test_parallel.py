"""SPMD tests on the virtual 8-device CPU mesh (conftest forces
jax_num_cpu_devices=8) — the reference's distributed tests ran a real
master+slave in one process (SURVEY.md §4 "Distributed tests without a
cluster"); the TPU equivalent is real multi-device sharding semantics
without TPU hardware."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.parallel import MeshConfig, make_mesh, sharding


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


class TestMakeMesh:
    def test_default_all_data(self):
        mesh = make_mesh()
        assert mesh.shape == {"data": 8}

    def test_two_axes(self):
        mesh = make_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_wildcard_axis(self):
        mesh = make_mesh({"data": -1, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"data": 16})


class TestShardingRules:
    def setup_method(self):
        self.mc = MeshConfig(make_mesh({"data": 4, "model": 2}))

    def test_dense_weights_shard_out_dim(self):
        assert sharding.param_spec((64, 32), self.mc) == P(None, "model")

    def test_conv_kernels_shard_out_channels(self):
        assert sharding.param_spec((3, 3, 8, 16), self.mc) == \
            P(None, None, None, "model")

    def test_indivisible_stays_replicated(self):
        assert sharding.param_spec((64, 7), self.mc) == P()

    def test_bias_shards(self):
        assert sharding.param_spec((32,), self.mc) == P("model",)


def run_digits(mesh_config, seed=1234, max_epochs=6, **kw):
    prng.seed_all(seed)
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=96,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 64,
             "learning_rate": 0.1, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.1, "gradient_moment": 0.9},
        ],
        loader=loader, decision_config={"max_epochs": max_epochs},
        mesh_config=mesh_config, name="digits-spmd", **kw)
    wf.initialize()
    wf.run()
    return wf


class TestSPMDTraining:
    def test_dp_training_runs_and_learns(self):
        mc = MeshConfig(make_mesh({"data": 8}))
        wf = run_digits(mc)
        assert wf.decision.best_metric < 0.15

    def test_dp_tp_training_runs_and_learns(self):
        mc = MeshConfig(make_mesh({"data": 4, "model": 2}))
        wf = run_digits(mc)
        assert wf.decision.best_metric < 0.15
        # dense weights really are sharded over the model axis
        w = wf.trainer.params[wf.trainer.layers[0].name]["weights"]
        assert w.sharding.spec == P(None, "model")

    def test_orbax_snapshot_of_sharded_params_resumes(self, tmp_path):
        """The orbax backend checkpoints the LIVE dp+tp-sharded arrays
        (no host gather) and a restore into a fresh mesh workflow
        continues to the exact same metrics as an uninterrupted run."""
        cfg = {"name": "orbax", "directory": str(tmp_path),
               "interval": 1, "prefix": "oxp"}
        mc = lambda: MeshConfig(make_mesh({"data": 4, "model": 2}))  # noqa: E731
        prng.seed_all(31)
        wf = run_digits(mc(), seed=31, max_epochs=2,
                        snapshotter_config=cfg)
        w = wf.trainer.params[wf.trainer.layers[0].name]["weights"]
        assert w.sharding.spec == P(None, "model")   # really sharded
        from veles_tpu.services.snapshotter import SnapshotterBase
        snap = SnapshotterBase.import_(
            str(tmp_path / "oxp_current"))
        assert snap["epoch"] == 2

        prng.seed_all(31)
        d = load_digits()
        loader = FullBatchLoader(
            None, data=(d.data / 16.0).astype(np.float32),
            labels=d.target.astype(np.int32), minibatch_size=96,
            class_lengths=[0, 297, 1500])
        wf2 = StandardWorkflow(
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 64,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
            ],
            loader=loader, decision_config={"max_epochs": 4},
            mesh_config=mc(), name="digits-spmd")
        wf2.initialize()
        wf2.restore(snap)
        wf2.run()
        wf3 = run_digits(mc(), seed=31, max_epochs=4)
        assert wf2.decision.best_metric == wf3.decision.best_metric

    def test_spmd_matches_single_device_metrics(self):
        """DP must be numerically equivalent to single-device training
        (same global batch, same seed) — the psum is exact in f32."""
        wf_single = run_digits(None, seed=55, max_epochs=3)
        wf_dp = run_digits(MeshConfig(make_mesh({"data": 8})), seed=55,
                           max_epochs=3)
        s = wf_single.decision.epoch_metrics[1]
        p = wf_dp.decision.epoch_metrics[1]
        assert s["n_errors"] == p["n_errors"]
        np.testing.assert_allclose(s["loss"], p["loss"], rtol=1e-3)

    def test_dataset_rows_sharded_not_replicated(self):
        """r2: the HBM dataset shards its rows over the data axis — each
        device holds 1/8 of the samples, not a full replica (VERDICT #2a;
        what makes ImageNet-scale fullbatch feasible)."""
        mc = MeshConfig(make_mesh({"data": 8}))
        wf = run_digits(mc, max_epochs=1)
        data = wf.trainer._data_dev
        shards = list(data.addressable_shards)
        assert len(shards) == 8
        # 1797 rows pad to 1800; 225 per device
        assert data.shape[0] == 1800
        assert all(s.data.shape[0] == 225 for s in shards)

    def test_sharded_matches_replicated_metrics(self):
        """The psum_scatter gather against the row-sharded dataset is
        numerically identical to gathering from a replica."""
        wf_sh = run_digits(MeshConfig(make_mesh({"data": 8})), seed=77,
                           max_epochs=3, dataset_placement="shard")
        wf_re = run_digits(MeshConfig(make_mesh({"data": 8})), seed=77,
                           max_epochs=3, dataset_placement="replicate")
        s = wf_sh.decision.epoch_metrics[1]
        r = wf_re.decision.epoch_metrics[1]
        assert s["n_errors"] == r["n_errors"]
        np.testing.assert_allclose(s["loss"], r["loss"], rtol=1e-5)

    def test_generator_loader_under_mesh(self):
        """Host-streaming SPMD (VERDICT #2b): minibatches produced by a
        host generator, batch sharded over the data axis — no dataset
        materialized on any device — must train and match the
        single-device run on the same stream."""
        from veles_tpu.loader.streaming import GeneratorLoader
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)[:1600]
        y = d.target.astype(np.int32)[:1600]

        def gen(step, size):
            ofs = (step * size) % 1600
            return x[ofs:ofs + size], y[ofs:ofs + size]

        def run(mesh_config, seed):
            prng.seed_all(seed)
            loader = GeneratorLoader(None, generator=gen, sample_shape=(64,),
                                     steps_per_epoch=16, minibatch_size=80)
            wf = StandardWorkflow(
                layers=[{"type": "all2all_tanh", "output_sample_shape": 32,
                         "learning_rate": 0.1},
                        {"type": "softmax", "output_sample_shape": 10,
                         "learning_rate": 0.1}],
                loader=loader, decision_config={"max_epochs": 4},
                mesh_config=mesh_config, name="gen-spmd")
            wf.initialize()
            wf.run()
            return wf

        wf_mesh = run(MeshConfig(make_mesh({"data": 8})), seed=31)
        wf_single = run(None, seed=31)
        m = wf_mesh.decision.epoch_metrics[2]
        s = wf_single.decision.epoch_metrics[2]
        assert m["n_errors"] == s["n_errors"]
        np.testing.assert_allclose(m["loss"], s["loss"], rtol=1e-4)
        assert m["count"] == 16 * 80   # one epoch's worth of samples

    def test_indivisible_minibatch_raises(self):
        mc = MeshConfig(make_mesh({"data": 8}))
        prng.seed_all(1)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=99,
                                 class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 10}],
            loader=loader, mesh_config=mc, name="bad-mb")
        with pytest.raises(ValueError, match="divisible"):
            wf.initialize()


class TestFSDP:
    def test_param_spec_shards_first_dim_over_data(self):
        mc = MeshConfig(make_mesh({"data": 4, "model": 2}), fsdp=True)
        assert sharding.param_spec((64, 32), mc) == P("data", "model")
        # model takes the only dim of a 1-D param; fsdp must not fight it
        assert sharding.param_spec((32,), mc) == P("model")
        # indivisible first dim stays replicated
        assert sharding.param_spec((7, 32), mc) == P(None, "model")

    def test_fsdp_params_sharded_and_metrics_match_dp(self):
        """ZeRO-3-style sharding: each worker stores 1/D of the weights;
        training must be numerically equivalent to replicated DP."""
        mc = MeshConfig(make_mesh({"data": 8}), fsdp=True)
        wf = run_digits(mc, seed=55, max_epochs=3)
        w = wf.trainer.params[wf.trainer.layers[0].name]["weights"]
        assert w.sharding.spec == P("data")
        shards = list(w.addressable_shards)
        assert len(shards) == 8
        assert all(s.data.shape[0] == w.shape[0] // 8 for s in shards)
        # optimizer state shards the same way (the ZeRO memory win)
        v = wf.trainer.velocity["slot1"][
            wf.trainer.layers[0].name]["weights"]
        assert v.sharding.spec == P("data")

        wf_dp = run_digits(MeshConfig(make_mesh({"data": 8})), seed=55,
                           max_epochs=3)
        s = wf.decision.epoch_metrics[1]
        p = wf_dp.decision.epoch_metrics[1]
        assert s["n_errors"] == p["n_errors"]
        np.testing.assert_allclose(s["loss"], p["loss"], rtol=1e-3)

    def test_fsdp_shards_grad_accum_and_ema_state(self):
        """The accumulation/EMA slots are optimizer state like any
        other: under ZeRO-3 they shard over the data axis (the memory
        win extends to them) and training still converges."""
        mc = MeshConfig(make_mesh({"data": 8}), fsdp=True)
        wf = run_digits(mc, seed=55, max_epochs=3,
                        gd_defaults={"grad_accum_steps": 2,
                                     "ema_decay": 0.9})
        tr = wf.trainer
        lname = tr.layers[0].name
        for slot in ("gacc", "ema"):
            leaf = tr.velocity[slot][lname]["weights"]
            assert leaf.sharding.spec == P("data"), (slot, leaf.sharding)
        assert wf.decision.best_metric < 0.3
        # EMA moved off its seed and is finite
        e = np.asarray(tr.ema_params[lname]["weights"])
        assert np.all(np.isfinite(e))

    def test_fsdp_composes_with_tp(self):
        mc = MeshConfig(make_mesh({"data": 4, "model": 2}), fsdp=True)
        wf = run_digits(mc, max_epochs=3)
        assert wf.decision.best_metric < 0.2
        w = wf.trainer.params[wf.trainer.layers[0].name]["weights"]
        assert w.sharding.spec == P("data", "model")
