"""SPMD tests on the virtual 8-device CPU mesh (conftest forces
jax_num_cpu_devices=8) — the reference's distributed tests ran a real
master+slave in one process (SURVEY.md §4 "Distributed tests without a
cluster"); the TPU equivalent is real multi-device sharding semantics
without TPU hardware."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.parallel import MeshConfig, make_mesh, sharding


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


class TestMakeMesh:
    def test_default_all_data(self):
        mesh = make_mesh()
        assert mesh.shape == {"data": 8}

    def test_two_axes(self):
        mesh = make_mesh({"data": 4, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_wildcard_axis(self):
        mesh = make_mesh({"data": -1, "model": 2})
        assert mesh.shape == {"data": 4, "model": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            make_mesh({"data": 16})


class TestShardingRules:
    def setup_method(self):
        self.mc = MeshConfig(make_mesh({"data": 4, "model": 2}))

    def test_dense_weights_shard_out_dim(self):
        assert sharding.param_spec((64, 32), self.mc) == P(None, "model")

    def test_conv_kernels_shard_out_channels(self):
        assert sharding.param_spec((3, 3, 8, 16), self.mc) == \
            P(None, None, None, "model")

    def test_indivisible_stays_replicated(self):
        assert sharding.param_spec((64, 7), self.mc) == P()

    def test_bias_shards(self):
        assert sharding.param_spec((32,), self.mc) == P("model",)


def run_digits(mesh_config, seed=1234, max_epochs=6):
    prng.seed_all(seed)
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=96,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 64,
             "learning_rate": 0.1, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.1, "gradient_moment": 0.9},
        ],
        loader=loader, decision_config={"max_epochs": max_epochs},
        mesh_config=mesh_config, name="digits-spmd")
    wf.initialize()
    wf.run()
    return wf


class TestSPMDTraining:
    def test_dp_training_runs_and_learns(self):
        mc = MeshConfig(make_mesh({"data": 8}))
        wf = run_digits(mc)
        assert wf.decision.best_metric < 0.15

    def test_dp_tp_training_runs_and_learns(self):
        mc = MeshConfig(make_mesh({"data": 4, "model": 2}))
        wf = run_digits(mc)
        assert wf.decision.best_metric < 0.15
        # dense weights really are sharded over the model axis
        w = wf.trainer.params[wf.trainer.layers[0].name]["weights"]
        assert w.sharding.spec == P(None, "model")

    def test_spmd_matches_single_device_metrics(self):
        """DP must be numerically equivalent to single-device training
        (same global batch, same seed) — the psum is exact in f32."""
        wf_single = run_digits(None, seed=55, max_epochs=3)
        wf_dp = run_digits(MeshConfig(make_mesh({"data": 8})), seed=55,
                           max_epochs=3)
        s = wf_single.decision.epoch_metrics[1]
        p = wf_dp.decision.epoch_metrics[1]
        assert s["n_errors"] == p["n_errors"]
        np.testing.assert_allclose(s["loss"], p["loss"], rtol=1e-3)

    def test_indivisible_minibatch_raises(self):
        mc = MeshConfig(make_mesh({"data": 8}))
        prng.seed_all(1)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=99,
                                 class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 10}],
            loader=loader, mesh_config=mc, name="bad-mb")
        with pytest.raises(ValueError, match="divisible"):
            wf.initialize()
