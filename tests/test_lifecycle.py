"""Serving survival layer (services.lifecycle + ContinuousEngine):
engine-side cancellation frees slots AND paged-KV blocks mid-decode,
deadlines are enforced (never admitted / cancelled mid-decode),
streaming queues are bounded, the SLO shedder opens and closes around
the threshold, disconnects leak nothing, and an engine tick fault is
survived.  One tiny untrained transformer is shared module-wide — the
suite tests lifecycle plumbing, not the model."""

import json
import socket
import struct
import threading
import time

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.services.lifecycle import (BoundedStream, DeadlineExceeded,
                                          RequestCancelled, ShedError,
                                          SloShedder)

T, VOCAB = 16, 11
PROMPT = [1, 2, 3, 4, 5]


@pytest.fixture(scope="module")
def gen():
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models import zoo
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(31)
    toks = np.random.RandomState(5).randint(
        0, VOCAB, (8, T)).astype(np.int32)
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                  n_heads=2, n_layers=1, dropout=0.0),
        loader=FullBatchLoader(None, data=toks, labels=toks,
                               minibatch_size=4,
                               class_lengths=[0, 4, 4]),
        loss="lm", decision_config={"max_epochs": 1},
        name="lifecycle-serve")
    wf.initialize()
    return LMGenerator(wf.trainer, max_len=T)


@pytest.fixture
def serve_cfg():
    """Snapshot/restore the process-global serve config so per-test
    knob changes never leak into other tests."""
    keys = ("slo_queue_wait_ms", "default_deadline_ms",
            "stream_queue_chunks", "stream_overflow",
            "stream_stall_timeout_ms", "shed_close_fraction")
    prev = {k: root.common.serve.get(k) for k in keys}
    try:
        yield root.common.serve
    finally:
        for k, v in prev.items():
            setattr(root.common.serve, k, v)


def _engine(gen, **kw):
    from veles_tpu.services.restful import ContinuousEngine
    return ContinuousEngine(gen, **kw)


def _wait_idle(eng, timeout=60.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        m = eng.metrics()
        if m["queued"] == 0 and m["in_flight"] == 0:
            return True
        time.sleep(0.02)
    return False


def _assert_no_leaks(eng):
    leaks = eng.leak_check()
    for key in ("ingress", "records", "open_requests",
                "pending_cancels", "slots_busy"):
        assert leaks[key] == 0, leaks
    assert leaks.get("kv_blocks_leaked", 0) == 0, leaks
    assert leaks["engine_thread_alive"]


class TestBoundedStream:
    def test_drop_oldest_bounds_and_counts(self):
        bs = BoundedStream(capacity=3, overflow="drop_oldest")
        for i in range(7):
            assert bs.push(("tokens", [i]))
        assert bs.qsize() == 3
        assert bs.dropped == 4
        # survivors are the NEWEST chunks
        assert [bs.get()[1] for _ in range(3)] == [[4], [5], [6]]

    def test_block_mode_refuses_without_sleeping(self):
        bs = BoundedStream(capacity=2, overflow="block")
        assert bs.push(("tokens", [0]))
        assert bs.push(("tokens", [1]))
        t0 = time.monotonic()
        assert not bs.push(("tokens", [2]))      # full: refused, and
        assert time.monotonic() - t0 < 0.5      # NEVER sleeps (the
        # producer is the engine thread every request's decode shares)
        assert bs.dropped == 0                   # nothing discarded
        bs.get()
        assert bs.push(("tokens", [2]))          # space freed

    def test_terminal_never_dropped_never_blocked(self):
        bs = BoundedStream(capacity=1, overflow="block")
        bs.push(("tokens", [0]))
        t0 = time.monotonic()
        bs.put_terminal(("done", [0, 1]))        # instant despite full
        assert time.monotonic() - t0 < 1.0
        assert bs.get()[1] == [0]
        assert bs.get()[0] == "done"
        # closed: producers no-op instead of growing the queue
        assert bs.push(("tokens", [9]))
        assert bs.qsize() == 0

    def test_invalid_overflow_rejected(self):
        with pytest.raises(ValueError):
            BoundedStream(overflow="explode")


class TestSloShedder:
    def test_opens_and_closes_with_hysteresis(self):
        sh = SloShedder(100.0, close_fraction=0.5)
        assert sh.enabled and not sh.should_shed()
        assert sh.update(head_wait_ms=50.0) is None
        assert sh.update(head_wait_ms=150.0) == "open"
        assert sh.should_shed()
        # between close and open thresholds: stays open (hysteresis)
        assert sh.update(head_wait_ms=80.0) is None
        assert sh.should_shed()
        assert sh.update(head_wait_ms=10.0) == "close"
        assert not sh.should_shed()
        assert sh.open_total == 1

    def test_admitted_wait_also_opens(self):
        sh = SloShedder(100.0)
        sh.note_admit(250.0)
        assert sh.update(head_wait_ms=0.0) == "open"

    def test_disabled_never_sheds(self):
        sh = SloShedder(0)
        assert not sh.enabled
        sh.note_admit(1e9)
        assert sh.update(head_wait_ms=1e9) is None
        assert not sh.should_shed()

    def test_shed_counts_and_retry_after(self):
        sh = SloShedder(2000.0)
        ra = sh.shed()
        assert ra == pytest.approx(2.0)
        assert sh.shed_total == 1
        assert sh.status()["state"] == "closed"

    def test_retry_after_scales_with_overshoot(self):
        """A deeply overloaded replica pushes clients (and the fleet
        router) away for longer: the Retry-After hint scales with the
        measured queue-wait overshoot, capped."""
        sh = SloShedder(1000.0, overshoot_cap=8.0)
        assert sh.retry_after_s() == pytest.approx(1.0)  # no measure yet
        sh.update(head_wait_ms=500.0)          # under the SLO: floor
        assert sh.retry_after_s() == pytest.approx(1.0)
        sh.update(head_wait_ms=3500.0)         # 3.5x the SLO
        assert sh.retry_after_s() == pytest.approx(3.5)
        sh.update(head_wait_ms=100000.0)       # pathological: capped
        assert sh.retry_after_s() == pytest.approx(8.0)
        sh.update(head_wait_ms=200.0)          # drained: back to floor
        assert sh.retry_after_s() == pytest.approx(1.0)


class TestCancel:
    def test_cancel_mid_decode_frees_slot_and_kv_blocks(self, gen,
                                                        serve_cfg):
        eng = _engine(gen, slots=2, paged_block=4, pool_tokens=64)
        try:
            pool_blocks = eng.cb.pool_blocks
            eng.wait(eng.submit_async(PROMPT, 4))       # warmup/compile
            handle, it = eng.stream_open(PROMPT, 10)
            first = next(it)                            # admitted + decoding
            assert first
            assert eng.cancel(handle["id"], reason="test cancel")
            with pytest.raises(RequestCancelled):
                for _ in it:
                    pass
            assert _wait_idle(eng)
            assert eng.cb.free_blocks() == pool_blocks  # blocks freed
            _assert_no_leaks(eng)
            m = eng.metrics()
            assert m["cancelled_total"] == 1
            # the pool still serves fresh work after the cancel
            out = eng.wait(eng.submit_async(PROMPT, 3))
            assert len(out) == len(PROMPT) + 3
        finally:
            eng.stop()

    def test_cancel_queued_request_before_admission(self, gen,
                                                    serve_cfg):
        eng = _engine(gen, slots=1)
        try:
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            blocker = eng.submit_async(PROMPT, 10)      # owns the slot
            queued = eng.submit_async(PROMPT, 10)       # waits behind it
            assert eng.cancel(queued["id"])
            with pytest.raises(RequestCancelled):
                eng.wait(queued)
            assert queued["admit_ts"] is None           # never admitted
            assert len(eng.wait(blocker)) == len(PROMPT) + 10
            assert _wait_idle(eng)
            _assert_no_leaks(eng)
        finally:
            eng.stop()

    def test_cancel_unknown_id_is_false(self, gen, serve_cfg):
        eng = _engine(gen, slots=1)
        try:
            assert eng.cancel(12345) is False
        finally:
            eng.stop()


class TestDeadline:
    def test_expired_request_never_admitted(self, gen, serve_cfg):
        eng = _engine(gen, slots=1)
        try:
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            blocker = eng.submit_async(PROMPT, 10)
            doomed = eng.submit_async(PROMPT, 10, deadline_ms=1)
            with pytest.raises(DeadlineExceeded):
                eng.wait(doomed)
            assert doomed["admit_ts"] is None
            assert len(eng.wait(blocker)) == len(PROMPT) + 10
            assert _wait_idle(eng)
            _assert_no_leaks(eng)
            assert eng.metrics()["deadline_expired_total"] == 1
        finally:
            eng.stop()

    def test_deadline_event_in_flight_ring(self, gen, serve_cfg):
        from veles_tpu.telemetry import flight
        eng = _engine(gen, slots=1)
        try:
            eng.wait(eng.submit_async(PROMPT, 2))
            blocker = eng.submit_async(PROMPT, 10)
            doomed = eng.submit_async(PROMPT, 4, deadline_ms=1)
            with pytest.raises(DeadlineExceeded):
                eng.wait(doomed)
            eng.wait(blocker)
            kinds = [e["kind"] for e in flight.recorder.snapshot()]
            assert "serve.deadline" in kinds
        finally:
            eng.stop()


class TestBoundedStreamOnEngine:
    def test_slow_consumer_bounded_and_result_authoritative(
            self, gen, serve_cfg):
        serve_cfg.stream_queue_chunks = 2
        serve_cfg.stream_overflow = "drop_oldest"
        eng = _engine(gen, slots=1)
        try:
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            want = eng.wait(eng.submit_async(PROMPT, 10)).tolist()
            handle, it = eng.stream_open(PROMPT, 10)
            chunks = [next(it)]                         # start, then stall
            assert _wait_idle(eng)                      # decode finished
            assert handle["stream_q"].qsize() <= 3      # bounded (+done)
            assert handle["stream_q"].dropped > 0
            for c in it:                                # drain remainder
                chunks.append(c)
            # drops cost incremental granularity, NEVER tokens: the
            # drain yields only contiguous progress and reconstructs
            # everything after the first gap from the terminal payload
            assert PROMPT + [t for c in chunks for t in c] == want
            assert list(handle["out"]) == want
            _assert_no_leaks(eng)
            assert eng.metrics()["stream_dropped_chunks"] > 0
        finally:
            eng.stop()

    def test_block_mode_stall_cancels_slowloris(self, gen, serve_cfg):
        serve_cfg.stream_queue_chunks = 2
        serve_cfg.stream_overflow = "block"
        serve_cfg.stream_stall_timeout_ms = 100
        eng = _engine(gen, slots=1)
        try:
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            # throttle decode below the stall budget: push never
            # blocks, so an unthrottled 10-token decode would finish
            # before the 100 ms no-progress window can expire
            orig = eng.cb.tick

            def slow_tick():
                time.sleep(0.03)
                return orig()

            eng.cb.tick = slow_tick
            handle, it = eng.stream_open(PROMPT, 10)
            next(it)                 # read ONE chunk, then stop reading
            deadline = time.monotonic() + 30
            while handle["error"] is None \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert isinstance(handle["error"], RequestCancelled)
            assert _wait_idle(eng)
            _assert_no_leaks(eng)
        finally:
            eng.stop()


class TestShedderOnEngine:
    def test_sheds_under_overload_and_recovers(self, gen, serve_cfg):
        serve_cfg.slo_queue_wait_ms = 20
        eng = _engine(gen, slots=1)
        try:
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            # a burst of instant submissions all precedes the breach —
            # the valve reacts to the MEASURED wait, so overload the
            # pool, wait for the head-of-line wait to cross the SLO,
            # and only then probe admission
            handles = [eng.submit_async(PROMPT, 11) for _ in range(25)]
            deadline = time.monotonic() + 30
            while not eng._shed.should_shed() \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            assert eng._shed.should_shed(), \
                "overload never opened the shedder"
            assert eng.metrics()["shed_state"] == "open"
            shed = 0
            for _ in range(3):
                try:
                    handles.append(eng.submit_async(PROMPT, 5))
                except ShedError as e:
                    shed += 1
                    assert e.retry_after_s >= 1.0
            assert shed > 0, "open valve admitted every probe"
            for h in handles:                           # admitted work OK
                assert len(eng.wait(h)) > len(PROMPT)
            deadline = time.monotonic() + 30
            while eng.metrics()["shed_state"] != "closed" \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert eng.metrics()["shed_state"] == "closed"
            # valve closed: fresh work admits again
            assert len(eng.wait(eng.submit_async(PROMPT, 2))) == \
                len(PROMPT) + 2
            assert eng.metrics()["shed_total"] == shed
            assert _wait_idle(eng)
            _assert_no_leaks(eng)
        finally:
            eng.stop()


class TestEngineFaultRecovery:
    def test_tick_fault_evicts_resets_and_keeps_serving(self, gen,
                                                        serve_cfg):
        eng = _engine(gen, slots=2, paged_block=4, pool_tokens=64)
        try:
            pool_blocks = eng.cb.pool_blocks
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            orig = eng.cb.tick
            state = {"armed": True}

            def chaos_tick():
                if state["armed"]:
                    state["armed"] = False
                    raise RuntimeError("injected tick fault")
                return orig()

            eng.cb.tick = chaos_tick
            victim = eng.submit_async(PROMPT, 6)
            with pytest.raises(RuntimeError, match="engine fault"):
                eng.wait(victim)
            # the pool reset freed everything and fresh work succeeds
            out = eng.wait(eng.submit_async(PROMPT, 3))
            assert len(out) == len(PROMPT) + 3
            assert eng.cb.free_blocks() == pool_blocks
            assert _wait_idle(eng)
            _assert_no_leaks(eng)
            assert eng.metrics()["engine_faults"] == 1
        finally:
            eng.stop()


class TestDisconnectOverRest:
    def test_mid_stream_rst_frees_slot_blocks_and_serves_on(
            self, gen, serve_cfg):
        from veles_tpu.services.restful import RESTfulAPI
        api = RESTfulAPI(lambda xx: xx, (T,), port=0, generator=gen,
                         continuous_slots=2, paged_block=4,
                         pool_tokens=64)
        api.start()
        try:
            eng = api.engine
            pool_blocks = eng.cb.pool_blocks
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            # throttle decode so the RST lands MID-decode: on an
            # unthrottled CPU the whole 10-token stream fits in the
            # loopback buffer before the client even reads chunk 1,
            # and the server would never see the broken pipe
            orig = eng.cb.tick

            def slow_tick():
                time.sleep(0.03)
                return orig()

            eng.cb.tick = slow_tick
            body = json.dumps({"input": PROMPT,
                               "generate": {"max_new": 10,
                                            "stream": True}}).encode()
            sock = socket.create_connection(
                ("127.0.0.1", api.port), timeout=30)
            sock.sendall(
                b"POST /service HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
            buf = b""
            while b"\r\n\r\n" not in buf or b"tokens" not in buf:
                chunk = sock.recv(256)
                assert chunk, "connection closed before first tokens"
                buf += chunk
            # vanish rudely: RST so the server's next write fails
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
            sock.close()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if eng.metrics()["cancelled_total"] >= 1 \
                        and _wait_idle(eng, timeout=1):
                    break
                time.sleep(0.05)
            assert eng.metrics()["cancelled_total"] >= 1, \
                "disconnect never cancelled the request"
            assert eng.cb.free_blocks() == pool_blocks
            _assert_no_leaks(eng)
            # and the endpoint still serves
            import urllib.request
            req = urllib.request.Request(
                "http://127.0.0.1:%d/service" % api.port,
                data=json.dumps({"input": PROMPT,
                                 "generate": {"max_new": 2}}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                assert json.loads(resp.read())["result"]
        finally:
            api.stop()


class TestShedOverRest:
    def test_503_with_retry_after(self, gen, serve_cfg):
        import urllib.error
        import urllib.request

        from veles_tpu.services.restful import RESTfulAPI
        serve_cfg.slo_queue_wait_ms = 10
        api = RESTfulAPI(lambda xx: xx, (T,), port=0, generator=gen,
                         continuous_slots=1)
        api.start()
        try:
            eng = api.engine
            eng.wait(eng.submit_async(PROMPT, 2))       # warmup
            # widen the overload window past the HTTP round-trip: an
            # unthrottled warm pool can drain a small backlog (and
            # close the valve) before the probe request even connects
            orig = eng.cb.tick

            def slow_tick():
                time.sleep(0.005)
                return orig()

            eng.cb.tick = slow_tick
            handles = [eng.submit_async(PROMPT, 11) for _ in range(16)]
            deadline = time.monotonic() + 30
            while not eng._shed.should_shed() \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eng._shed.should_shed(), "overload never shed"
            req = urllib.request.Request(
                "http://127.0.0.1:%d/service" % api.port,
                data=json.dumps({"input": PROMPT,
                                 "generate": {"max_new": 2}}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=60)
            assert ei.value.code == 503
            assert int(ei.value.headers["Retry-After"]) >= 1
            for h in handles:
                eng.wait(h)
            assert _wait_idle(eng)
            _assert_no_leaks(eng)
        finally:
            api.stop()


class TestSpecMixedEvent:
    def test_one_shot_informational_event_on_sampled_request(
            self, gen, serve_cfg):
        """The pool-wide `serve.spec_degraded` cliff event is RETIRED
        (speculation routes per row now); a sampled request entering
        a speculative pool emits the downgraded one-shot
        `serve.spec_mixed` informational event instead — and never
        the old degraded one."""
        from veles_tpu.telemetry import flight
        eng = _engine(gen, slots=2, speculative_k=2)
        try:
            eng.cb.tick = lambda: 0        # no decode needed: the
            # event fires at submit, and compiling the spec tick here
            # would buy the test nothing
            def count(kind):
                return sum(1 for e in flight.recorder.snapshot()
                           if e["kind"] == kind)
            before = count("serve.spec_mixed")
            # lint-ok: VC954 — retired event; this gate asserts it
            # never comes back, so nothing is supposed to emit it
            degraded = count("serve.spec_degraded")
            eng.submit_async(PROMPT, 2, temperature=0.7)
            eng.submit_async(PROMPT, 2, temperature=0.9)
            assert count("serve.spec_mixed") - before == 1  # one-shot
            assert count("serve.spec_degraded") == degraded  # retired
        finally:
            eng.stop()


class TestFusedSublaneFallback:
    def test_small_blocks_fall_back_when_mosaic_compiles(
            self, gen, monkeypatch):
        """Construction-time guard (ADVICE r5): on a REAL TPU backend
        (interpret off) a paged_block below Mosaic's sublane minimum
        for the pool dtype must auto-select the gather tick — the
        fused kernel's K/V tile is one block and cannot compile."""
        import veles_tpu.ops.pallas as ops_pallas
        from veles_tpu.models.generate import PagedContinuousBatcher
        from veles_tpu.ops.pallas import mosaic_sublane_min
        assert mosaic_sublane_min(np.float32) == 8
        assert mosaic_sublane_min("bfloat16") == 16
        assert mosaic_sublane_min(np.int8) == 32
        monkeypatch.setattr(ops_pallas, "autodetect_interpret",
                            lambda i: False)   # pretend: real TPU
        dtype_min = mosaic_sublane_min(gen._model_dtype())
        below = max(1, dtype_min // 2)
        cb = PagedContinuousBatcher(gen, slots=2, block=below,
                                    pool_tokens=T * 2, fused=True)
        assert not cb.fused                    # sublane fallback
        cb2 = PagedContinuousBatcher(gen, slots=2, block=dtype_min,
                                     pool_tokens=T * 2, fused=True)
        assert cb2.fused                       # at the minimum: fine

    def test_interpret_mode_keeps_fused(self, gen):
        from veles_tpu.models.generate import PagedContinuousBatcher
        cb = PagedContinuousBatcher(gen, slots=2, block=4,
                                    pool_tokens=T * 2, fused=True)
        assert cb.fused                        # CPU suite: interpret


class TestChaosScaledDown:
    def test_storm_sheds_recovers_and_leaks_nothing(self, gen,
                                                    serve_cfg):
        """The tools/serve_loadtest.py harness at tier-1 scale:
        concurrent streaming clients with mid-stream RSTs, slowloris
        readers, and injected engine faults — afterwards zero leaked
        slots / KV blocks / threads, a shed+recover cycle, and the
        engine serving fresh requests."""
        import tools.serve_loadtest as lt
        serve_cfg.slo_queue_wait_ms = 20
        api = lt.build_api(slots=2, paged_block=4, pool_tokens=96,
                           slo_ms=20, generator=gen)
        # throttle decode so 24 clients over 2 slots provably exceed
        # the 20 ms queue-wait SLO, and ramp the arrivals: on a fast
        # box an unthrottled burst both drains before the valve can
        # open AND submits every client before the first breach is
        # measured, leaving nobody to shed (FaultInjector wraps tick
        # at storm start, so the throttle composes)
        orig = api.engine.cb.tick

        def slow_tick():
            time.sleep(0.02)
            return orig()

        api.engine.cb.tick = slow_tick
        try:
            report = lt.run(clients=24, disconnect=0.3, slowloris=0.1,
                            buffered=0.2, fault_rate=0.03, max_new=10,
                            prompt_len=len(PROMPT), slo_ms=20,
                            slow_delay=0.1, seed=11, api=api,
                            ramp_s=1.0)
        finally:
            api.stop()
        fails = lt.gates(report, expect_shed=True)
        assert not fails, (fails, report)
        assert report["metrics"]["shed_total"] > 0
